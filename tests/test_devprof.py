"""Device-time attribution (ISSUE 7 tentpole): the devprof trace parser
and interval math, the live capture→attribution round-trip on a psum
program, the training sentry, the Perfetto trace export, and the
compile-cache cost manifests + explain CLI."""

import gzip
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import theanompi_tpu as tmpi
from theanompi_tpu.utils import devprof, sentry, telemetry
from theanompi_tpu.utils.sentry import TrainingSentry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_global_telemetry():
    yield
    telemetry.init({})


def _op(ts, dur, name, pid=1, tid=1, module="jit_step"):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": float(ts),
            "dur": float(dur), "name": name,
            "args": {"hlo_op": name, "hlo_module": module}}


# -- attribution math -------------------------------------------------------

def test_attribute_exposed_comm_and_overlap():
    """One lane: compute [0,50], comm [40,60] → 10us of the 20us
    collective is exposed, overlap ratio 0.5."""
    prof = devprof.attribute([
        _op(0, 50, "fusion.1"),
        _op(40, 20, "all-reduce.1"),
    ])
    assert prof["compute_secs"] == pytest.approx(50e-6)
    assert prof["comm_secs"] == pytest.approx(20e-6)
    assert prof["exposed_comm_secs"] == pytest.approx(10e-6)
    assert prof["overlap_ratio"] == pytest.approx(0.5)
    assert prof["lanes"] == 1 and prof["n_op_events"] == 2


def test_attribute_nested_and_multi_lane():
    """Nested compute spans union-merge (no double count); lanes are
    independent — lane A's compute can't hide lane B's collective."""
    prof = devprof.attribute([
        _op(0, 100, "while.2"),                 # outer
        _op(10, 20, "fusion.3"),                # nested inside — no extra
        _op(0, 40, "all-reduce.1", tid=2),      # other lane: fully exposed
    ])
    assert prof["compute_secs"] == pytest.approx(100e-6)
    assert prof["comm_secs"] == pytest.approx(40e-6)
    assert prof["exposed_comm_secs"] == pytest.approx(40e-6)
    assert prof["overlap_ratio"] == pytest.approx(0.0)
    assert prof["lanes"] == 2


def test_attribute_cross_host_lane_ids_do_not_collide():
    """Per-host capture files reuse the same small pid/tid integers —
    profile_dir tags each file's events with _src, and attribute() keys
    lanes on it, so host A's compute can't mask host B's collective as
    overlap (it stays fully exposed)."""
    prof = devprof.attribute([
        dict(_op(0, 100, "fusion.1"), _src=0),
        dict(_op(0, 40, "all-reduce.1"), _src=1),   # same pid/tid, host B
    ])
    assert prof["lanes"] == 2
    assert prof["exposed_comm_secs"] == pytest.approx(40e-6)
    assert prof["overlap_ratio"] == pytest.approx(0.0)


def test_attribute_fully_hidden_comm_and_async_names():
    """An async-pair collective entirely under compute → overlap 1.0;
    -start/-done forms classify as comm and MERGE into one interval
    spanning the whole in-flight window (start-begin → done-end), so
    comm_secs counts the collective's true 60us, not two slivers."""
    prof = devprof.attribute([
        _op(0, 100, "fusion.1"),
        _op(10, 5, "all-gather-start.2"),
        _op(60, 10, "all-gather-done.2"),
    ])
    assert prof["comm_secs"] == pytest.approx(60e-6)
    assert prof["exposed_comm_secs"] == pytest.approx(0.0)
    assert prof["overlap_ratio"] == pytest.approx(1.0)
    comm_ops = {o["op"] for o in prof["top_ops"] if o["comm"]}
    assert comm_ops == {"all-gather-start", "all-gather-done"}


def test_attribute_async_pair_on_dedicated_stream_counts_once():
    """The round-9 lane-classification fix: a runtime that parks the
    ``-done`` on a dedicated async-collective stream (its own tid, no
    compute) must not read as a SECOND, fully-exposed collective — the
    pair merges into ONE start-to-done interval on the ISSUING lane,
    where the overlapping compute hides it."""
    prof = devprof.attribute([
        _op(0, 100, "fusion.1"),                       # compute, lane 1
        _op(10, 5, "all-reduce-start.3"),              # issued on lane 1
        _op(60, 10, "all-reduce-done.3", tid=2),       # waited on stream
    ])
    # one merged interval [10, 70] on lane 1, fully under compute
    assert prof["comm_secs"] == pytest.approx(60e-6)
    assert prof["exposed_comm_secs"] == pytest.approx(0.0)
    assert prof["overlap_ratio"] == pytest.approx(1.0)
    assert prof["lanes"] == 2                 # the stream is still a lane
    assert prof["compute_lanes"] == 1         # ...but carries no compute


def test_attribute_async_two_lane_trace_pairs_in_order():
    """Synthetic two-lane async trace (the regression shape): two
    bucketed pairs whose halves live on a dedicated stream pair
    k-th-start ↔ k-th-done in ts order and merge per pair — NOT into one
    giant span, and never double-counted across the two lanes."""
    prof = devprof.attribute([
        _op(0, 100, "fusion.1"),
        # bucket A in flight [5, 45]; bucket B in flight [50, 90] — both
        # halves of each pair on the dedicated stream (tid=2)
        _op(5, 5, "all-reduce-start.1", tid=2),
        _op(40, 5, "all-reduce-done.1", tid=2),
        _op(50, 5, "all-reduce-start.2", tid=2),
        _op(85, 5, "all-reduce-done.2", tid=2),
    ])
    # two merged intervals, 40us each, on the stream lane
    assert prof["comm_secs"] == pytest.approx(80e-6)
    # per-lane model: the stream lane has no compute, so the merged
    # windows read exposed there (the start-lane assignment only applies
    # to CROSS-lane pairs, where the issuing lane is known)
    assert prof["exposed_comm_secs"] == pytest.approx(80e-6)
    # an unpaired start keeps its own sliver (no phantom done invented)
    prof2 = devprof.attribute([_op(5, 5, "all-reduce-start.9")])
    assert prof2["comm_secs"] == pytest.approx(5e-6)


def test_attribute_no_comm_yields_none_ratio_and_module_split():
    prof = devprof.attribute([
        _op(0, 10, "fusion.1", module="jit_a"),
        _op(20, 10, "convolution.4", module="jit_b"),
    ])
    assert prof["comm_secs"] == 0.0
    assert prof["overlap_ratio"] is None
    assert set(prof["modules"]) == {"jit_a", "jit_b"}
    assert prof["modules"]["jit_a"]["compute_secs"] == pytest.approx(10e-6)


def test_dispatch_anchors_counted_host_junk_ignored():
    prof = devprof.attribute([
        {"ph": "X", "pid": 9, "tid": 9, "ts": 0, "dur": 5,
         "name": devprof.TRAIN_DISPATCH_SPAN},
        {"ph": "X", "pid": 9, "tid": 9, "ts": 6, "dur": 5,
         "name": devprof.TRAIN_DISPATCH_SPAN},
        {"ph": "X", "pid": 9, "tid": 9, "ts": 12, "dur": 2,
         "name": devprof.EXCHANGE_SPAN},
        {"ph": "X", "pid": 9, "tid": 9, "ts": 0, "dur": 99,
         "name": "$builtins isinstance"},        # host python span: ignored
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "x"}},
        _op(0, 10, "fusion.1"),
    ])
    assert prof["train_dispatches"] == 2
    assert prof["exchange_dispatches"] == 1
    assert prof["n_op_events"] == 1


def test_comm_op_classification():
    assert devprof.is_comm_op("all-reduce.17")
    assert devprof.is_comm_op("reduce-scatter.1")
    assert devprof.is_comm_op("collective-permute-start.3")
    assert not devprof.is_comm_op("reduce.5")          # plain reduce ≠ comm
    assert not devprof.is_comm_op("broadcast_multiply_fusion")
    assert devprof.op_class("all-reduce.17") == "all-reduce"


def test_profile_dir_empty_and_truncated(tmp_path):
    assert devprof.profile_dir(str(tmp_path)) is None
    sess = tmp_path / "plugins" / "profile" / "2026_01_01"
    sess.mkdir(parents=True)
    with gzip.open(sess / "host.trace.json.gz", "wt") as f:
        f.write('{"traceEvents": [')          # truncated capture
    assert devprof.profile_dir(str(tmp_path)) is None


# -- live capture round-trip (acceptance: psum step on CPU) -----------------

def test_capture_round_trip_psum(tmp_path):
    """A captured profile of a psum-containing step round-trips: nonzero
    compute AND comm breakdown, ratio in range, all-reduce in the top op
    classes — the acceptance path for attribution on this backend."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from theanompi_tpu.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:4]), ("workers",))

    def f(x):
        return jax.lax.psum(x * 2.0, "workers")

    g = jax.jit(shard_map(f, mesh=mesh, in_specs=P("workers"),
                          out_specs=P()))
    x = jnp.arange(32.0)
    g(x).block_until_ready()                  # compile outside the window
    with devprof.capture(str(tmp_path / "trace")) as cap:
        for _ in range(3):
            r = g(x)
        r.block_until_ready()
    prof = cap.profile
    assert prof is not None, "no usable capture emitted"
    assert prof["comm_secs"] > 0 and prof["compute_secs"] > 0
    assert prof["exposed_comm_secs"] <= prof["comm_secs"] + 1e-9
    assert 0.0 <= prof["overlap_ratio"] <= 1.0
    assert any(o["comm"] and o["op"].startswith("all-reduce")
               for o in prof["top_ops"])
    assert prof["lanes"] >= 1 and prof["n_op_events"] > 0


def test_feed_telemetry_emits_device_gauges():
    prof = devprof.attribute([_op(0, 50, "fusion.1"),
                              _op(40, 20, "all-reduce.1")])
    tm = telemetry.Telemetry(rank=0, run_id="t")
    devprof.feed_telemetry(prof, tm)
    assert set(tm.gauges) == set(devprof.DEVICE_GAUGES)
    assert tm.gauges["device.overlap_ratio"] == pytest.approx(0.5)
    evs = [e for e in tm.tail(4) if e["ev"] == devprof.PROFILE_EVENT]
    assert evs and evs[-1]["top_ops"]
    # disabled registry: feed is a no-op, not an error
    devprof.feed_telemetry(prof, telemetry.DISABLED)


def test_profile_row_fields_columns_and_device_mfu():
    prof = devprof.attribute([_op(0, 50, "fusion.1"),
                              _op(40, 20, "all-reduce.1")])
    fields = devprof.profile_row_fields(prof)
    assert set(fields) == set(devprof.TRACE_ROW_COLUMNS)
    assert fields["device_mfu"] is None          # no flops/peak given
    # 1 lane, 50us compute; 1e9 flops over the window vs 1e15 peak:
    # mfu = 1e9 / 50e-6 / 1e15 = 0.02
    fields = devprof.profile_row_fields(prof, total_flops=1e9,
                                        peak_flops=1e15)
    assert fields["device_mfu"] == pytest.approx(0.02)
    assert fields["overlap_ratio"] == pytest.approx(0.5)


def test_bubble_fraction_per_lane_idle_gaps():
    """ISSUE 14 satellite (ROADMAP item 2's bench column): per-lane idle
    gaps between compute intervals inside the dispatch window, span-
    weighted across compute lanes."""
    prof = devprof.attribute([
        # lane 1: compute [0,10] + [20,30] → span 30, busy 20, idle 10
        _op(0, 10, "fusion.1"),
        _op(20, 10, "fusion.2"),
        # lane 2: compute [0,40] → span 40, no idle
        _op(0, 40, "while.1", tid=2),
    ])
    assert prof["bubble_fraction"] == pytest.approx(10.0 / 70.0, abs=1e-4)
    # a collective inside the gap does NOT fill the bubble: from the
    # compute pipeline's perspective an exposed comm stall is a stall
    prof2 = devprof.attribute([
        _op(0, 10, "fusion.1"),
        _op(20, 10, "fusion.2"),
        _op(0, 40, "while.1", tid=2),
        _op(12, 6, "all-reduce.1"),
    ])
    assert prof2["bubble_fraction"] == pytest.approx(10.0 / 70.0,
                                                     abs=1e-4)
    # no compute at all → None (and the row column carries it verbatim)
    prof3 = devprof.attribute([_op(0, 5, "all-reduce.1")])
    assert prof3["bubble_fraction"] is None
    assert devprof.profile_row_fields(prof3)["bubble_fraction"] is None
    assert "bubble_fraction" in devprof.TRACE_ROW_COLUMNS
    assert devprof.profile_row_fields(prof)["bubble_fraction"] == \
        prof["bubble_fraction"]
    # a perfectly packed single lane is bubble-free
    assert devprof.attribute([_op(0, 50, "fusion.1")])[
        "bubble_fraction"] == pytest.approx(0.0)


# -- training sentry --------------------------------------------------------

def _rec(i, cost=1.0, ips=100.0):
    return {"iter": i, "cost": cost, "images_per_sec": ips}


def test_sentry_nan_loss():
    tm = telemetry.Telemetry(rank=0, run_id="s")
    s = TrainingSentry({"verbose": False}, telemetry=tm)
    assert s.observe_record(_rec(1)) is None
    assert s.observe_record(_rec(2, cost=float("nan"))) == "nan_loss"
    assert s.observe_record(_rec(3, cost=float("inf"))) == "nan_loss"
    evs = [e for e in tm.tail(8) if e["ev"] == sentry.ANOMALY_EVENT]
    assert len(evs) == 2 and evs[-1]["kind"] == "nan_loss"
    assert tm.counters["sentry.anomalies"] == 2
    assert tm.counters["sentry.nan_loss"] == 2


def test_sentry_loss_spike_robust_to_its_own_baseline():
    s = TrainingSentry({"verbose": False, "sentry_min_records": 4,
                        "sentry_loss_spike": 6.0},
                       telemetry=telemetry.DISABLED)
    for i in range(8):
        assert s.observe_record(_rec(i, cost=1.0 + 0.01 * (i % 3))) is None
    assert s.observe_record(_rec(9, cost=50.0)) == "loss_spike"
    # the spike did NOT enter the window: an immediately repeated spike
    # still reads as a spike (the baseline wasn't poisoned)
    assert s.observe_record(_rec(10, cost=50.0)) == "loss_spike"
    # back to normal is healthy
    assert s.observe_record(_rec(11, cost=1.01)) is None


def test_sentry_flat_window_tolerates_noise():
    """A perfectly flat cost window (MAD 0) must not flag float noise —
    the 5%-of-median floor absorbs it."""
    s = TrainingSentry({"verbose": False, "sentry_min_records": 4},
                       telemetry=telemetry.DISABLED)
    for i in range(6):
        assert s.observe_record(_rec(i, cost=2.0)) is None
    assert s.observe_record(_rec(7, cost=2.02)) is None


def test_sentry_throughput_regression():
    s = TrainingSentry({"verbose": False, "sentry_min_records": 4,
                        "sentry_tput_drop": 0.5},
                       telemetry=telemetry.DISABLED)
    for i in range(6):
        assert s.observe_record(_rec(i, ips=1000.0 + i)) is None
    assert s.observe_record(_rec(7, ips=100.0)) == "throughput_regression"
    assert s.observe_record(_rec(8, ips=990.0)) is None
    assert [k for k, _ in s.anomalies] == ["throughput_regression"]


def test_sentry_discontinuity_skips_one_throughput_sample():
    """The first record after a val/ckpt boundary spans dead wall time —
    notice_discontinuity() makes the sentry neither judge nor learn from
    its throughput, so a healthy run doesn't flag once per epoch."""
    s = TrainingSentry({"verbose": False, "sentry_min_records": 4,
                        "sentry_tput_drop": 0.5},
                       telemetry=telemetry.DISABLED)
    for i in range(6):
        assert s.observe_record(_rec(i, ips=1000.0)) is None
    s.notice_discontinuity()
    # spans the val epoch: would be a regression without the notice
    assert s.observe_record(_rec(7, ips=100.0)) is None
    assert 100.0 not in s._tputs                 # not learned either
    # the NEXT record is judged normally again
    assert s.observe_record(_rec(8, ips=100.0)) == "throughput_regression"
    # loss detection is unaffected by the notice
    s2 = TrainingSentry({"verbose": False}, telemetry=telemetry.DISABLED)
    s2.notice_discontinuity()
    assert s2.observe_record(_rec(1, cost=float("nan"))) == "nan_loss"


def test_sentry_dumps_flight_once_per_kind(tmp_path):
    d = str(tmp_path)
    tm = telemetry.Telemetry(rank=0, run_id="s", stream_dir=d)
    s = TrainingSentry({"verbose": False}, telemetry=tm)
    s.observe_record(_rec(1))
    assert s.observe_record(_rec(2, cost=float("nan"))) == "nan_loss"
    flight = os.path.join(d, "flight_rank0.jsonl")
    assert os.path.exists(flight)
    first = open(flight).read()
    assert "sentry nan_loss" in first.splitlines()[0]
    # second nan: event recorded, but the dump (the lead-in trail) stays
    s.observe_record(_rec(3, cost=float("nan")))
    assert open(flight).read() == first
    tm.close()


def test_sentry_wired_into_worker_healthy_run():
    """Session run with telemetry on: the worker builds a sentry, feeds it
    every print record, and a healthy run raises nothing; sentry=false
    opts out."""
    rule = tmpi.BSP()
    rule.init(devices=4, modelfile="tests.conftest", modelclass="TinyModel",
              epochs=1, batch_size=8, n_train=64, verbose=False,
              scale_lr=False, telemetry=True, printFreq=2)
    rule.wait()
    s = rule.worker.sentry
    assert s is not None and s.records_seen >= 1
    assert s.anomalies == []
    rule2 = tmpi.BSP()
    rule2.init(devices=4, modelfile="tests.conftest", modelclass="TinyModel",
               epochs=1, batch_size=8, n_train=64, verbose=False,
               scale_lr=False, telemetry=True, sentry=False)
    rule2.wait()
    assert rule2.worker.sentry is None


def test_worker_trace_capture_feeds_device_gauges(tmp_path):
    """The worker's trace_dir capture now runs attribution: after the
    traced window the process registry carries the device.* gauges and a
    device_profile event, with nonzero comm (the BSP step psums)."""
    trace_dir = str(tmp_path / "trace")
    rule = tmpi.BSP()
    rule.init(devices=4, modelfile="tests.conftest", modelclass="TinyModel",
              epochs=1, batch_size=8, n_train=64, verbose=False,
              scale_lr=False, telemetry=True,
              trace_dir=trace_dir, trace_start=2, trace_iters=2)
    rule.wait()
    tm = rule.worker.telemetry
    assert set(devprof.DEVICE_GAUGES) <= set(tm.gauges), sorted(tm.gauges)
    assert tm.gauges["device.comm_secs"] > 0
    assert tm.gauges["device.compute_secs"] > 0
    assert 0.0 <= tm.gauges["device.overlap_ratio"] <= 1.0
    evs = [e for e in tm.tail(64) if e["ev"] == devprof.PROFILE_EVENT]
    assert evs and evs[-1]["train_dispatches"] >= 1


# -- Perfetto trace export --------------------------------------------------

def _write_stream(d, rank, events):
    with open(os.path.join(d, f"telemetry_rank{rank}.jsonl"), "w") as f:
        for ev in events:
            f.write(json.dumps({"run": "r1", "rank": rank, **ev}) + "\n")


def test_telemetry_report_trace_export(tmp_path):
    """--trace emits Chrome trace-event JSON: one process track per rank,
    monotonic non-negative spans, counter tracks, anomaly markers."""
    d = str(tmp_path / "rec")
    os.makedirs(d)
    t0 = 1000.0
    _write_stream(d, 0, [
        {"ts": t0, "ev": "run_start", "schema": 1},
        {"ts": t0 + 1.0, "ev": "phase", "sec": "train", "dt": 0.5},
        {"ts": t0 + 1.2, "ev": "phase", "sec": "comm", "dt": 0.2},
        {"ts": t0 + 1.3, "ev": "gauges", "hbm_bytes_in_use": 1024,
         "prefetch.queue_depth": 2},
        {"ts": t0 + 1.5, "ev": "train_record", "iter": 4,
         "images_per_sec": 512.0},
        {"ts": t0 + 1.8, "ev": "val_record", "iter": 4, "val_cost": 1.25},
        {"ts": t0 + 2.0, "ev": "anomaly", "kind": "loss_spike", "iter": 6},
        {"ts": t0 + 2.5, "ev": "device_profile", "compute_secs": 1.0,
         "comm_secs": 0.5, "exposed_comm_secs": 0.1, "overlap_ratio": 0.8,
         "lanes": 4, "train_dispatches": 2},
    ])
    _write_stream(d, 1, [
        {"ts": t0 + 0.5, "ev": "phase", "sec": "train", "dt": 0.4},
        {"ts": t0 + 1.1, "ev": "phase", "sec": "train", "dt": 0.5},
    ])
    out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/telemetry_report.py"),
         d, "--trace", out], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "Perfetto" in r.stdout
    trace = json.load(open(out))
    evs = trace["traceEvents"]
    # one process track per rank
    procs = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert procs == {0: "rank 0", 1: "rank 1"}
    spans = [e for e in evs if e.get("ph") == "X"]
    assert {(s["pid"], s["name"]) for s in spans} == \
        {(0, "train"), (0, "comm"), (1, "train")}
    # monotonic, non-negative, ts-ordered within the body
    assert all(s["dur"] >= 0 and s["ts"] >= 0 for s in spans)
    body = [e for e in evs if e.get("ph") != "M"]
    assert [e["ts"] for e in body] == sorted(e["ts"] for e in body)
    # phase span is [ts-dt, ts]: rank 0's train span starts at 0.5s rel
    tr0 = next(s for s in spans if s["pid"] == 0 and s["name"] == "train")
    assert tr0["ts"] == pytest.approx(0.5e6, abs=1e3)
    assert tr0["dur"] == pytest.approx(0.5e6, abs=1e3)
    counters = {e["name"] for e in evs if e.get("ph") == "C"}
    assert counters == {"hbm_bytes_in_use", "prefetch.queue_depth",
                        "images_per_sec", "val_cost",
                        "device.overlap_ratio"}
    instants = [e for e in evs if e.get("ph") == "i"]
    assert instants and instants[0]["name"] == "anomaly:loss_spike"
    # anomalies AND the device attribution also surface in the plain report
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/telemetry_report.py"),
         d], capture_output=True, text=True)
    assert "sentry anomalies" in r2.stdout and "loss_spike" in r2.stdout
    assert "device-time attribution" in r2.stdout
    assert "80.0% overlap" in r2.stdout


# -- explain_program over the cost manifest ---------------------------------

def test_compile_cache_manifest_carries_cost_summary(tmp_path):
    """A cache write records the executable's cost/memory summary; the
    explain CLI prints and diffs it from the manifest alone."""
    import jax
    import jax.numpy as jnp
    from theanompi_tpu.utils.compile_cache import CompileCache

    cc = CompileCache(str(tmp_path))

    def big(x):
        return (x @ x).sum()

    def small(x):
        return (x * 2.0).sum()

    xb = jnp.zeros((64, 64), jnp.float32)
    _, info_a = cc.get_or_compile(jax.jit(big).lower(xb), label="prog:big")
    _, info_b = cc.get_or_compile(jax.jit(small).lower(xb),
                                  label="prog:small")
    manifest = json.load(open(os.path.join(str(tmp_path), "manifest.json")))
    cost_a = manifest[info_a["key"]].get("cost", {})
    cost_b = manifest[info_b["key"]].get("cost", {})
    assert cost_a.get("flops", 0) > cost_b.get("flops", 0) > 0
    script = os.path.join(REPO, "scripts/explain_program.py")
    r = subprocess.run([sys.executable, script, str(tmp_path)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "prog:big" in r.stdout and "prog:small" in r.stdout
    r = subprocess.run([sys.executable, script, str(tmp_path),
                        "--diff", "prog:big", "prog:small"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "flops" in r.stdout and "B/A" in r.stdout
    r = subprocess.run([sys.executable, script, str(tmp_path), "--json"],
                       capture_output=True, text=True)
    assert json.loads(r.stdout)[info_a["key"]]["label"] == "prog:big"
    # unresolvable diff token → exit 2, stderr explains
    r = subprocess.run([sys.executable, script, str(tmp_path),
                        "--diff", "prog:big", "nope"],
                       capture_output=True, text=True)
    assert r.returncode == 2 and "cannot resolve" in r.stderr


# -- merge_matrix column tolerance ------------------------------------------

def test_merge_matrix_tolerates_trace_columns(tmp_path):
    """Rows carrying the BENCH_TRACE columns (and rows with odd value
    types) merge against old rows without KeyErrors — absent columns are
    unknown, never a regression/demotion."""
    sys.path.insert(0, REPO)
    from scripts import merge_matrix

    p = tmp_path / "m.jsonl"
    rows = [
        # old-style row: no trace columns
        {"config": "alexnet-b128", "result": {"metric": "m", "value": 10.0}},
        # tombstone with a ts; then a new-style row whose value is absent
        {"config": "vgg16-b32", "result": None, "note": "degraded window",
         "voided_value": 5.0, "ts": 100.0},
        {"config": "vgg16-b32", "ts": "not-a-number",
         "result": {"metric": "m", "value": None,
                    "overlap_ratio": 0.7, "exposed_comm_secs": 0.01}},
        # newer re-measure of the first config WITH trace columns wins
        {"config": "alexnet-b128",
         "result": {"metric": "m", "value": 12.0, "overlap_ratio": 0.9,
                    "exposed_comm_secs": 0.002, "device_mfu": None}},
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    merge_matrix.merge([str(p)])          # must not raise
    out = {r["config"]: r for r in
           (json.loads(l) for l in p.read_text().splitlines())}
    assert out["alexnet-b128"]["result"]["value"] == 12.0
    assert out["alexnet-b128"]["result"]["overlap_ratio"] == 0.9
    # the None-valued row still merged (it outranks the tombstone's null)
    assert out["vgg16-b32"]["result"]["overlap_ratio"] == 0.7
