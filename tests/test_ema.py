"""EMA/Polyak parameter averaging (utils/opt.py ema_wrap, config
ema_decay): shadow math pinned against a manual recurrence; validation and
generation read the shadow."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import TinyModel
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh


def _make(mesh, **kw):
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "optimizer": "sgd", "learning_rate": 0.05, "weight_decay": 0.0,
           **kw}
    m = TinyModel(cfg)
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)
    return m


def test_ema_matches_manual_recurrence(mesh4):
    decay = 0.9
    base = _make(mesh4)
    ema = _make(mesh4, ema_decay=decay)
    shadow = steps.unbox(jax.device_get(base.step_state["params"]))
    for i in range(4):
        base.train_iter(i, None)
        ema.train_iter(i, None)
        p = steps.unbox(jax.device_get(base.step_state["params"]))
        shadow = jax.tree.map(
            lambda e, q: decay * np.asarray(e) + (1 - decay) * np.asarray(q),
            shadow, p)
    # identical trajectories (EMA is observation-only) ...
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)),
        steps.unbox(jax.device_get(base.step_state["params"])),
        steps.unbox(jax.device_get(ema.step_state["params"])))
    # ... and the shadow follows the recurrence exactly
    got = steps.unbox(jax.device_get(
        ema.step_state["opt_state"]["ema"]))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7), got, shadow)


def test_validation_and_canonical_use_the_shadow(mesh4):
    m = _make(mesh4, ema_decay=0.5)
    for i in range(3):
        m.train_iter(i, None)
    m.begin_val()
    ema_boxed = m.step_state["opt_state"]["ema"]
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        m._val_params_boxed, ema_boxed)
    m.val_iter(0, None)
    m.end_val()
    canon = m.canonical_host_params()
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(jax.device_get(b))),
        canon, steps.unbox(jax.device_get(ema_boxed)))


def test_ema_composes_with_zero1(mesh4):
    """EMA inside ZeRO: the shadow SHARDS with the optimizer state (memory
    /N, no duplicated full copies on disk) and the full shadow assembled at
    read time matches the manual recurrence on the full params."""
    decay = 0.9
    base = _make(mesh4, optimizer="momentum")
    m = _make(mesh4, ema_decay=decay, zero_opt=True, optimizer="momentum")
    st = m.step_state["opt_state"]
    chunk = -(-m.n_params // 4)
    assert st["opt"]["ema"].shape == (4, chunk)      # sharded shadow
    shadow = steps.unbox(jax.device_get(base.step_state["params"]))
    for i in range(3):
        base.train_iter(i, None)
        m.train_iter(i, None)
        p = steps.unbox(jax.device_get(base.step_state["params"]))
        shadow = jax.tree.map(
            lambda e, q: decay * np.asarray(e) + (1 - decay) * np.asarray(q),
            shadow, p)
    got = m._ema_host_params()
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7), got, shadow)
    # begin_val serves the assembled shadow
    m.begin_val()
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(steps.unbox(jax.device_get(a))), np.asarray(b),
        rtol=1e-6, atol=1e-7), m._val_params_boxed, got)
    m.end_val()


def test_ema_rejects_params_mode(mesh4):
    cfg = {"mesh": mesh4, "size": 4, "rank": 0, "verbose": False,
           "ema_decay": 0.9, "exch_mode": "params"}
    model = TinyModel(cfg)
    with pytest.raises(AssertionError, match="grads mode"):
        model.compile_iter_fns(BSP_Exchanger(cfg))


# -- round 4: composition with tensor parallelism ---------------------------

TP_LM = dict(verbose=False, batch_size=8, seq_len=16, vocab=32,
             synthetic_train=64, synthetic_val=32, d_model=32, n_head=4,
             n_layer=2)


def _make_lm(tp, **kw):
    import jax.numpy as jnp
    from theanompi_tpu.models.transformer_lm import TransformerLM
    mesh = worker_mesh(2, tp=tp)
    cfg = {**TP_LM, "mesh": mesh, "size": 2, "rank": 0, "tp": tp,
           "compute_dtype": jnp.float32, **kw}
    m = TransformerLM(cfg)
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)
    return m


def test_ema_under_tp_matches_dense_shadow(mesh8):
    """The tp=2 shadow must equal the dense run's shadow (same model, same
    data, identical math up to fp32 summation order) — round-3 verdict #6."""
    decay = 0.9
    dense = _make_lm(1, ema_decay=decay)
    tp2 = _make_lm(2, ema_decay=decay)
    for i in range(4):
        dense.train_iter(i, None)
        tp2.train_iter(i, None)
    sd = dense._ema_host_params()
    st = tp2._ema_host_params()
    # dense vs tp differ by fp32 summation order (psum vs serial matmul
    # reductions), compounding over 4 adam steps — not an exactness claim
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-3, atol=2e-4), sd, st)
    # validation reads the re-boxed sharded shadow without error
    tp2.begin_val()
    tp2.val_iter(0)
    tp2.end_val()


def test_ema_zero_tp_shadow_matches_plain_ema(mesh8):
    """Triple composition ema×zero×tp: the chunk-sharded shadow, assembled
    by the device-side gather, must be BIT-equal to the plain tp shadow
    (zero is bit-equal math; EMA is elementwise on the same values)."""
    decay = 0.9
    plain = _make_lm(2, ema_decay=decay)
    zero = _make_lm(2, ema_decay=decay, zero_opt=True)
    for i in range(4):
        plain.train_iter(i, None)
        zero.train_iter(i, None)
    sp_ = plain._ema_host_params()
    sz = zero._ema_host_params()
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), sp_, sz)
    # and the sharded layout really is chunks, not a full tree
    st = zero.step_state["opt_state"]
    assert "ema" not in st and "ema" in st["opt"]
    zero.begin_val()
    zero.val_iter(0)
    zero.end_val()

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
