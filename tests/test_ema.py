"""EMA/Polyak parameter averaging (utils/opt.py ema_wrap, config
ema_decay): shadow math pinned against a manual recurrence; validation and
generation read the shadow."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import TinyModel
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh


def _make(mesh, **kw):
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "optimizer": "sgd", "learning_rate": 0.05, "weight_decay": 0.0,
           **kw}
    m = TinyModel(cfg)
    m.compile_iter_fns(BSP_Exchanger(m.config))
    m.data.shuffle_data(0)
    return m


def test_ema_matches_manual_recurrence(mesh4):
    decay = 0.9
    base = _make(mesh4)
    ema = _make(mesh4, ema_decay=decay)
    shadow = steps.unbox(jax.device_get(base.step_state["params"]))
    for i in range(4):
        base.train_iter(i, None)
        ema.train_iter(i, None)
        p = steps.unbox(jax.device_get(base.step_state["params"]))
        shadow = jax.tree.map(
            lambda e, q: decay * np.asarray(e) + (1 - decay) * np.asarray(q),
            shadow, p)
    # identical trajectories (EMA is observation-only) ...
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)),
        steps.unbox(jax.device_get(base.step_state["params"])),
        steps.unbox(jax.device_get(ema.step_state["params"])))
    # ... and the shadow follows the recurrence exactly
    got = steps.unbox(jax.device_get(
        ema.step_state["opt_state"]["ema"]))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7), got, shadow)


def test_validation_and_canonical_use_the_shadow(mesh4):
    m = _make(mesh4, ema_decay=0.5)
    for i in range(3):
        m.train_iter(i, None)
    m.begin_val()
    ema_boxed = m.step_state["opt_state"]["ema"]
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        m._val_params_boxed, ema_boxed)
    m.val_iter(0, None)
    m.end_val()
    canon = m.canonical_host_params()
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(jax.device_get(b))),
        canon, steps.unbox(jax.device_get(ema_boxed)))


def test_ema_composes_with_zero1(mesh4):
    """EMA inside ZeRO: the shadow SHARDS with the optimizer state (memory
    /N, no duplicated full copies on disk) and the full shadow assembled at
    read time matches the manual recurrence on the full params."""
    decay = 0.9
    base = _make(mesh4, optimizer="momentum")
    m = _make(mesh4, ema_decay=decay, zero_opt=True, optimizer="momentum")
    st = m.step_state["opt_state"]
    chunk = -(-m.n_params // 4)
    assert st["opt"]["ema"].shape == (4, chunk)      # sharded shadow
    shadow = steps.unbox(jax.device_get(base.step_state["params"]))
    for i in range(3):
        base.train_iter(i, None)
        m.train_iter(i, None)
        p = steps.unbox(jax.device_get(base.step_state["params"]))
        shadow = jax.tree.map(
            lambda e, q: decay * np.asarray(e) + (1 - decay) * np.asarray(q),
            shadow, p)
    got = m._ema_host_params()
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7), got, shadow)
    # begin_val serves the assembled shadow
    m.begin_val()
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(steps.unbox(jax.device_get(a))), np.asarray(b),
        rtol=1e-6, atol=1e-7), m._val_params_boxed, got)
    m.end_val()


def test_ema_rejects_params_mode(mesh4):
    cfg = {"mesh": mesh4, "size": 4, "rank": 0, "verbose": False,
           "ema_decay": 0.9, "exch_mode": "params"}
    model = TinyModel(cfg)
    with pytest.raises(AssertionError, match="grads mode"):
        model.compile_iter_fns(BSP_Exchanger(cfg))
