"""3-D composition: dp×pipe×model — GPipe stages of tensor-parallel blocks
in one SPMD program, pinned against the dense model.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import (MODEL_AXIS, PIPE_AXIS, WORKER_AXIS,
                                         worker_mesh)

LM_CFG = dict(verbose=False, batch_size=8, seq_len=16, vocab=32,
              synthetic_train=64, synthetic_val=32,
              d_model=32, n_head=4, n_layer=4, compute_dtype=jnp.float32)


def _make(dp, tp, pp, **kw):
    mesh = worker_mesh(dp, tp=tp, pp=pp)
    cfg = {**LM_CFG, "mesh": mesh, "size": dp, "rank": 0, "tp": tp, "pp": pp,
           **kw}
    return TransformerLM(cfg)


def _train_steps(model, n_steps):
    exch = BSP_Exchanger(model.config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    costs = []
    for i in range(n_steps):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    return costs


def test_3d_mesh_shape_and_shardings(mesh8):
    m = _make(dp=2, tp=2, pp=2)
    assert dict(m.mesh.shape) == {WORKER_AXIS: 2, PIPE_AXIS: 2,
                                  MODEL_AXIS: 2}
    m.compile_iter_fns(BSP_Exchanger(m.config))
    w = m.step_state["params"]["blocks"]["fc1"]["w"]
    # boxed [2 workers, 4 layers, d, 4d]: layers over pipe, 4d over model
    assert w.sharding.spec == (WORKER_AXIS, PIPE_AXIS, None, MODEL_AXIS), \
        w.sharding.spec
    assert w.addressable_shards[0].data.shape == (1, 2, 32, 64)
    # vocab-parallel embedding sharded over model, replicated over pipe
    e = m.step_state["params"]["embed"]["w"]
    assert e.sharding.spec == (WORKER_AXIS, MODEL_AXIS, None)


def test_3d_training_matches_dense(mesh8):
    dense = _make(dp=2, tp=1, pp=1)
    m3 = _make(dp=2, tp=2, pp=2)
    c_dense = _train_steps(dense, 5)
    c_3d = _train_steps(m3, 5)
    np.testing.assert_allclose(c_3d, c_dense, rtol=2e-4, atol=2e-5)


def test_compressed_strategies_on_pipe_and_3d_meshes(mesh8):
    """EF compression and the explicit ring wire compose with pipeline (and
    pipe×model) sharding: per-stage EF shards, replicated leaves pmean'd
    back after the decode."""
    from theanompi_tpu.parallel.mesh import PIPE_AXIS

    def run(tp, pp, strat, n=5):
        mesh = worker_mesh(2, tp=tp, pp=pp)
        cfg = {**LM_CFG, "mesh": mesh, "size": 2, "rank": 0, "tp": tp,
               "pp": pp, "exch_strategy": strat}
        model = TransformerLM(cfg)
        return model, _train_steps(model, n)

    for tp, pp, strat in ((1, 4, "onebit"), (1, 4, "ring"),
                          (2, 2, "onebit"), (2, 2, "topk")):
        model, costs = run(tp, pp, strat)
        assert np.isfinite(costs).all(), (tp, pp, strat, costs)
        assert np.mean(costs[-2:]) < np.mean(costs[:2]), (tp, pp, strat)
        if strat in ("onebit", "topk"):
            ef = model.step_state["extra"]["strat"]
            want = (WORKER_AXIS, (PIPE_AXIS, MODEL_AXIS)) if tp > 1 \
                else (WORKER_AXIS, PIPE_AXIS)
            assert ef.sharding.spec == want, (strat, ef.sharding.spec)


def test_3d_val_and_checkpoint(tmp_path, mesh8):
    from theanompi_tpu.parallel import steps
    m3 = _make(dp=2, tp=2, pp=2)
    _train_steps(m3, 3)
    m3.begin_val()
    m3.val_iter(0, None)
    m3.end_val()
    m3.save(str(tmp_path), epoch=0, count=3)
    before = jax.device_get(steps.tree_to_host(m3.step_state["params"]))
    m3b = _make(dp=2, tp=2, pp=2)
    m3b.compile_iter_fns(BSP_Exchanger(m3b.config))
    assert m3b.load(str(tmp_path)) == 0
    after = jax.device_get(steps.tree_to_host(m3b.step_state["params"]))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), before, after)
    m3b.data.shuffle_data(0)
    m3b.train_iter(3, None)
    assert np.isfinite(float(m3b.current_info["cost"]))


def test_worker_mesh_warns_on_idle_remainder(mesh8):
    """ADVICE r3: flooring n_workers must not silently idle chips."""
    del mesh8
    with pytest.warns(UserWarning, match="left idle"):
        worker_mesh(None, tp=3, devices=jax.devices())   # 8 % 3 = 2 idle


def test_4axis_tp_pp_sp_matches_dense(mesh8):
    """round-4: ALL model-parallel axes at once — pipeline stages of
    head-sharded ring-attention blocks over sequence-sharded microbatches
    (workers×pipe×model×seq = 1×2×2×2) — matches the dense model."""
    CFG = {**LM_CFG, "n_layer": 2}
    dense = TransformerLM({**CFG, "mesh": worker_mesh(1), "size": 1,
                           "rank": 0})
    m4 = TransformerLM({**CFG, "mesh": worker_mesh(1, tp=2, pp=2, sp=2),
                        "size": 1, "rank": 0, "tp": 2, "pp": 2, "sp": 2,
                        "pp_microbatches": 2})
    c_d = _train_steps(dense, 4)
    c_4 = _train_steps(m4, 4)
    np.testing.assert_allclose(c_4, c_d, rtol=5e-4, atol=5e-5)
    m4.begin_val()
    m4.val_iter(0)
    m4.end_val()

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
