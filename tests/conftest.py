"""Test harness: simulate an 8-device mesh on CPU.

SURVEY.md §4: the reference had no unit-testable communicator — multi-GPU
correctness was only checkable on a real cluster.  JAX's forced host platform
device count gives every exchanger/rule a real 8-way mesh in CI.

NOTE: ``JAX_PLATFORMS=cpu`` as an env var is hijacked by the axon TPU plugin
in this environment; the programmatic config update below is the reliable
way to force CPU (see .claude/skills/verify/SKILL.md).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: do NOT enable the persistent XLA compile cache here (the lever
# bench.py pulls) — on this container's jax/CPU backend, serializing the
# big 8-device shard_map executables SEGFAULTS the whole pytest process
# (observed round 6, test_3d_mesh).  bench.py's use is unaffected (its
# inner runs in a disposable subprocess and targets the TPU plugin).

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from theanompi_tpu.models import layers as L  # noqa: E402
from theanompi_tpu.models.data import DataBase  # noqa: E402
from theanompi_tpu.models.model_base import ModelBase  # noqa: E402


class SyntheticData(DataBase):
    """Tiny deterministic 2-class dataset for fast rule/equivalence tests."""

    DIM = 16

    def __init__(self, config=None, batch_size=8, n_train=256, n_val=64):
        super().__init__(config, batch_size)
        rng = np.random.RandomState(7)
        w = rng.randn(self.DIM)

        def make(n, seed):
            r = np.random.RandomState(seed)
            x = r.randn(n, self.DIM).astype(np.float32)
            y = (x @ w > 0).astype(np.int32)
            return x, y

        self.x_train, self.y_train = make(n_train, 11)
        self.x_val, self.y_val = make(n_val, 22)
        self._finalize()


class TinyModel(ModelBase):
    """Minimal MLP following the full model contract — compiles in seconds
    on the CPU mesh, used by rule/equivalence/checkpoint tests."""

    batch_size = 8
    epochs = 2
    n_subb = 1
    learning_rate = 0.05
    momentum = 0.9
    weight_decay = 0.0
    lr_adjust_epochs = ()
    seed = 3

    def build_model(self):
        import jax.numpy as jnp
        cd = self.config.get("compute_dtype", jnp.float32)
        dim = SyntheticData.DIM
        self.seq = L.Sequential([
            L.FC(dim, 32, w_init="he", compute_dtype=cd, name="fc1"),
            L.FC(32, 2, w_init=("normal", 0.01), activation=None,
                 compute_dtype=cd, name="out"),
        ])
        self.data = SyntheticData(self.config, self.batch_size,
                                  n_train=int(self.config.get("n_train", 256)))


class _CrashOnceTrainIter:
    """Fault-injection mixin for supervisor/recovery tests: raises at
    ``crash_at`` once (a marker file records that the crash already
    happened, so the restarted run proceeds)."""

    def train_iter(self, count, recorder=None):
        marker = self.config.get("crash_marker")
        if (marker and count >= int(self.config.get("crash_at", 10 ** 9))
                and not os.path.exists(marker)):
            with open(marker, "w") as f:
                f.write("crashed")
            raise RuntimeError("injected crash for supervisor test")
        super().train_iter(count, recorder)


class CrashOnceModel(_CrashOnceTrainIter, TinyModel):
    pass


from theanompi_tpu.models.transformer_lm import TransformerLM  # noqa: E402


class CrashOnceLM(_CrashOnceTrainIter, TransformerLM):
    """The same fault injection on the transformer — pins that the
    supervisor/resume recovery loop is model-agnostic."""


class SleepyModel(TinyModel):
    """Slows each train_iter by ``iter_sleep`` seconds — gives external
    fault injectors (the chaos harness's SIGKILL-mid-epoch tests) a wide,
    deterministic window to land a signal inside an epoch."""

    def train_iter(self, count, recorder=None):
        import time
        time.sleep(float(self.config.get("iter_sleep", 0.05)))
        super().train_iter(count, recorder)


class AlwaysCrashModel(TinyModel):
    """Crashes at every ``crash_at``-th iteration, every run — the
    systemic failure a crash-loop breaker must stop retrying."""

    def train_iter(self, count, recorder=None):
        if count >= int(self.config.get("crash_at", 1)):
            raise RuntimeError("injected systemic crash (chaos test)")
        super().train_iter(count, recorder)


class HangOnceModel(TinyModel):
    """Fault-injection model for the hang-recovery test: STALLS (sleeps far
    past any stall_timeout) at ``hang_at`` once; the marker file makes the
    restarted run proceed.  The worker's watchdog with stall_action=exit is
    what breaks the hang."""

    def train_iter(self, count, recorder=None):
        import time
        marker = self.config.get("hang_marker")
        if (marker and count >= int(self.config.get("hang_at", 10 ** 9))
                and not os.path.exists(marker)):
            with open(marker, "w") as f:
                f.write("hung")
            time.sleep(300)          # the watchdog must kill us long before
        super().train_iter(count, recorder)


@pytest.fixture(scope="session")
def mesh8():
    from theanompi_tpu.parallel.mesh import worker_mesh
    return worker_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    from theanompi_tpu.parallel.mesh import worker_mesh
    return worker_mesh(4)
