"""Multi-host data sharding: each host's data object must emit exactly its
contiguous sub-block of the global batch (disjoint, order-preserving), so
``make_per_host_array`` can stitch them with no cross-host traffic.

Simulated single-process by overriding process_count/process_index in config
— the same override path a dry-run uses.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np

from tests.conftest import SyntheticData
from theanompi_tpu.models.data.imagenet import ImageNet_data


def test_launcher_execs_two_host_training():
    """The launcher's multi-host exec path end to end (VERDICT row 14: it
    had never been executed): two launcher-spawned worker processes × 2
    virtual CPU devices bring up jax.distributed from the composed command
    line and train one tiny epoch each."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_PLATFORMS", None)     # worker pins platform via config

    def cmd(i):
        return [sys.executable, "-u", "-m", "theanompi_tpu.launcher",
                "--rule", "bsp",
                "--modelfile", "theanompi_tpu.models.cifar10",
                "--modelclass", "Cifar10_model",
                "--num-hosts", "2", "--process-id", str(i),
                "--coordinator", f"localhost:{port}",
                "platform=cpu", "epochs=1", "batch_size=8",
                "synthetic_train=64", "synthetic_val=32",
                "compute_dtype=float32", "scale_lr=false", "printFreq=1"]

    procs = [subprocess.Popen(cmd(i), stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
        assert p.returncode == 0, out
    # rank 0 prints the training log; rank 1 stays quiet
    assert "training finished" in outs[0], outs[0]


def test_launcher_emit_only_composes_per_host_commands(capsys):
    from theanompi_tpu import launcher
    rc = launcher.main(["--rule", "bsp", "--num-hosts", "2",
                        "--coordinator", "h0:1234", "--emit-only",
                        "batch_size=8"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "process_id=0" in out and "process_id=1" in out
    assert "coordinator_address=h0:1234" in out


def _run_twoproc_and_compare(mode, oracle):
    """Spawn 2 jax.distributed subprocesses via twoproc_helper.py, parse
    their 'FP ' fingerprint lines, and assert both agree with ``oracle``."""
    helper = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "twoproc_helper.py")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    procs = [subprocess.Popen(
        [sys.executable, helper, str(i), str(port), mode],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in (0, 1)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"proc failed:\n{out}\n{err}"
        outs.append(out)

    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("FP ")]
        assert lines, out
        fp = json.loads(lines[0][3:])
        np.testing.assert_allclose(fp["sums"], oracle["sums"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(fp["first"], oracle["first"],
                                   rtol=1e-5, atol=1e-6)


def test_two_process_jax_distributed_bsp_step():
    """REAL 2-process jax.distributed run (VERDICT round-1 Weak #6): two
    subprocesses × 2 virtual CPU devices form a 4-worker global mesh, load
    per-host data shards, stitch them with make_per_host_array inside
    put_batch, run 2 compiled BSP steps, and gather state multi-host.  Both
    processes must agree with each other AND with a single-process 4-worker
    oracle."""
    from tests.twoproc_model import fingerprint_after_steps
    _run_twoproc_and_compare("dense", fingerprint_after_steps(n_workers=4))


def test_two_process_tp_transformer_step():
    """Multi-host × tensor parallelism — the real-scale layout (dp across
    hosts, tp within a host): two jax.distributed processes × 2 virtual
    devices form a (workers=2, model=2) global mesh; each process feeds its
    worker group's batch shard, the tp-sharded params train 2 BSP steps, and
    the multi-host gather must agree with a single-process oracle."""
    from tests.twoproc_model import fingerprint_after_steps_tp
    _run_twoproc_and_compare("tp", fingerprint_after_steps_tp(dp=2, tp=2))


def test_two_process_pp_transformer_step():
    """Multi-host × pipeline parallelism: dp across the processes, both
    pipeline stages within each process — microbatch ppermutes stay
    intra-host, the gradient reduce crosses hosts; must match a
    single-process oracle."""
    from tests.twoproc_model import fingerprint_after_steps_pp
    _run_twoproc_and_compare("pp", fingerprint_after_steps_pp(dp=2, pp=2))


def test_database_host_slices_partition_global_batch():
    cfg = {"size": 4, "seed": 0}
    whole = SyntheticData({**cfg, "process_count": 1}, batch_size=8)
    h0 = SyntheticData({**cfg, "process_count": 2, "process_index": 0},
                       batch_size=8)
    h1 = SyntheticData({**cfg, "process_count": 2, "process_index": 1},
                       batch_size=8)
    for d in (whole, h0, h1):
        d.shuffle_data(123)
    for _ in range(3):
        g = whole.next_train_batch(0)
        a, b = h0.next_train_batch(0), h1.next_train_batch(0)
        assert a["x"].shape[0] == b["x"].shape[0] == g["x"].shape[0] // 2
        np.testing.assert_array_equal(np.concatenate([a["x"], b["x"]]), g["x"])
        np.testing.assert_array_equal(np.concatenate([a["y"], b["y"]]), g["y"])


def test_database_val_slices_partition():
    cfg = {"size": 4, "seed": 0}
    whole = SyntheticData({**cfg, "process_count": 1}, batch_size=8)
    parts = [SyntheticData({**cfg, "process_count": 2, "process_index": h},
                           batch_size=8) for h in (0, 1)]
    g = whole.next_val_batch(0)
    a, b = (p.next_val_batch(0) for p in parts)
    np.testing.assert_array_equal(np.concatenate([a["x"], b["x"]]), g["x"])


def _imagenet_dir(tmp_path, n_files=8, bs=4):
    d = tmp_path / "imgnet"
    (d / "train_hkl").mkdir(parents=True)
    (d / "val_hkl").mkdir()
    r = np.random.RandomState(0)
    for sub, n in (("train_hkl", n_files), ("val_hkl", n_files)):
        for i in range(n):
            np.save(str(d / sub / f"{i:04d}.npy"),
                    r.randint(0, 256, (bs, 16, 16, 3), dtype=np.uint8))
        np.save(str(d / f"{sub.split('_')[0]}_labels.npy"),
                r.randint(0, 10, n * bs).astype(np.int64))
    return str(d)


def test_imagenet_host_file_slices_partition(tmp_path):
    root = _imagenet_dir(tmp_path)
    cfg = {"size": 4, "data_dir": root, "crop_size": 12, "seed": 7}
    whole = ImageNet_data({**cfg, "process_count": 1}, batch_size=4, crop=12)
    parts = [ImageNet_data({**cfg, "process_count": 2, "process_index": h},
                           batch_size=4, crop=12) for h in (0, 1)]
    for d in (whole, *parts):
        d.shuffle_data(99)
    for _ in range(2):
        g = whole.next_train_batch(0)
        a, b = (p.next_train_batch(0) for p in parts)
        np.testing.assert_array_equal(np.concatenate([a["x"], b["x"]]), g["x"])
        np.testing.assert_array_equal(np.concatenate([a["y"], b["y"]]), g["y"])
    gv = whole.next_val_batch(0)
    av, bv = (p.next_val_batch(0) for p in parts)
    np.testing.assert_array_equal(np.concatenate([av["x"], bv["x"]]), gv["x"])


def test_imagenet_synthetic_host_slices(tmp_path):
    """Synthetic data is host-keyed (O(local) generation): each host gets a
    deterministic local-sized batch, distinct across hosts."""
    cfg = {"size": 4, "synthetic_batches": 2, "n_class": 10, "seed": 7}
    parts = [ImageNet_data({**cfg, "process_count": 2, "process_index": h},
                           batch_size=4, crop=8) for h in (0, 1)]
    a, b = (p.next_train_batch(0) for p in parts)
    assert a["x"].shape == b["x"].shape == (8, 8, 8, 3)
    assert not np.array_equal(a["x"], b["x"])      # distinct host streams
    again = ImageNet_data({**cfg, "process_count": 2, "process_index": 0},
                          batch_size=4, crop=8).next_train_batch(0)
    np.testing.assert_array_equal(a["x"], again["x"])   # deterministic


def test_two_process_spc_matches_single_step():
    """round-4 (verdict #4): steps_per_call=2 on the REAL 2-process
    jax.distributed path — per-host batch stacks stitched by
    put_batch_stack — must match the spc=1 single-process oracle
    bit-for-bit (same data order, same per-step RNG folding)."""
    from tests.twoproc_model import fingerprint_after_steps
    _run_twoproc_and_compare("spc", fingerprint_after_steps(n_workers=4))


def test_two_process_fsdp_matches_single_process():
    """Multi-host FSDP/ZeRO-3 (round-4): the parameter chunks partition
    over workers spanning BOTH processes, so the in-step all_gather and
    its psum_scatter transpose cross the real process boundary; the
    assembled canonical tree must match a single-process 4-worker FSDP
    oracle (itself pinned bit-equal to dense BSP in test_fsdp.py)."""
    from tests.twoproc_model import fingerprint_after_steps
    _run_twoproc_and_compare("fsdp",
                             fingerprint_after_steps(n_workers=4, fsdp=True))


def test_two_process_sp_transformer_step():
    """Multi-host × sequence parallelism (round-4): dp across the
    processes, both seq shards within each process — ring-attention
    ppermutes stay intra-host, the gradient reduce crosses hosts; the
    per-host batch (full sequences for this host's rows) is stitched by
    put_batch with the [workers, seq] sharding.  Must match a
    single-process oracle."""
    from tests.twoproc_model import fingerprint_after_steps_sp
    _run_twoproc_and_compare("sp", fingerprint_after_steps_sp(dp=2, sp=2))


def test_two_process_sp_spc_matches_single_step():
    """The full composition — multi-host × sequence-parallel ×
    steps_per_call: per-host [k, rows, seq] stacks stitched
    P(None, workers, seq) must match the spc=1-equivalent single-process
    oracle (same data order, same per-step RNG folding)."""
    from tests.twoproc_model import fingerprint_after_steps_sp
    _run_twoproc_and_compare("sp_spc",
                             fingerprint_after_steps_sp(dp=2, sp=2))


def test_two_process_compressed_wire_matches_oracle():
    """Multi-host × error-feedback compressed exchange (round-4): the
    onebit strategy's Pallas-packed sign allgather crosses real process
    boundaries and must match the single-process oracle (EF state keeps
    the two runs bit-comparable at matching tolerances)."""
    from tests.twoproc_model import fingerprint_after_steps_onebit
    _run_twoproc_and_compare("onebit",
                             fingerprint_after_steps_onebit(n_workers=4))

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
