"""Optimizer builders vs NumPy oracle (reference lib/opt.py parity)."""

import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.utils.opt import get_optimizer


def _tree():
    return {"w": jnp.asarray([1.0, -2.0]), "b": jnp.asarray([0.5])}


def _grads():
    return {"w": jnp.asarray([0.1, 0.2]), "b": jnp.asarray([-0.3])}


def test_sgd_oracle():
    opt = get_optimizer("sgd", weight_decay=0.0)
    p, g = _tree(), _grads()
    s = opt.init(p)
    p2, _ = opt.update(g, s, p, 0.1)
    np.testing.assert_allclose(np.asarray(p2["w"]), [1 - 0.01, -2 - 0.02])


def test_momentum_oracle():
    mu, lr, wd = 0.9, 0.1, 0.01
    opt = get_optimizer("momentum", mu=mu, weight_decay=wd)
    p, g = _tree(), _grads()
    v = opt.init(p)
    # two steps, tracked by hand: v' = mu v - lr (g + wd p); p' = p + v'
    pw, vw = np.asarray(p["w"]), np.zeros(2)
    for _ in range(2):
        p, v = opt.update(g, v, p, lr)
        vw = mu * vw - lr * (np.asarray(g["w"]) + wd * pw)
        pw = pw + vw
    np.testing.assert_allclose(np.asarray(p["w"]), pw, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v["w"]), vw, rtol=1e-6)


def test_nesterov_oracle():
    mu, lr = 0.9, 0.1
    opt = get_optimizer("nesterov", mu=mu, weight_decay=0.0)
    p, g = _tree(), _grads()
    v = opt.init(p)
    p2, v2 = opt.update(g, v, p, lr)
    # v' = mu*0 - lr*g ; p' = p + mu*v' - lr*g
    vw = -lr * np.asarray(g["w"])
    pw = np.asarray(_tree()["w"]) + mu * vw - lr * np.asarray(g["w"])
    np.testing.assert_allclose(np.asarray(p2["w"]), pw, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v2["w"]), vw, rtol=1e-6)


def test_unknown_optimizer_raises():
    with pytest.raises(ValueError, match="unknown optimizer"):
        get_optimizer("adamw")


def test_cosine_lr_schedule():
    """lr_schedule='cosine': base -> min_lr_frac*base over `epochs`, with
    the step schedule untouched by default."""
    import math
    from tests.conftest import TinyModel
    from theanompi_tpu.parallel.mesh import worker_mesh
    mesh = worker_mesh(2)
    m = TinyModel({"mesh": mesh, "size": 2, "rank": 0, "verbose": False,
                   "lr_schedule": "cosine", "epochs": 10,
                   "min_lr_frac": 0.1, "learning_rate": 1.0})
    m.adjust_hyperp(0)
    assert m.current_lr == 1.0
    m.adjust_hyperp(5)
    want_mid = 0.1 + 0.9 * 0.5 * (1 + math.cos(math.pi * 0.5))
    assert abs(m.current_lr - want_mid) < 1e-9
    m.adjust_hyperp(10)
    assert abs(m.current_lr - 0.1) < 1e-9
    # default remains the reference step schedule
    m2 = TinyModel({"mesh": mesh, "size": 2, "rank": 0, "verbose": False,
                    "learning_rate": 1.0})
    m2.lr_adjust_epochs = (3,)
    m2.adjust_hyperp(2)
    assert m2.current_lr == 1.0
    m2.adjust_hyperp(3)
    assert m2.current_lr == pytest.approx(0.1)
