"""Checkpoint/resume roundtrip, loader determinism (SURVEY.md §4 item d),
prefetch-loader equivalence, helper roundtrips, recorder accounting."""

import os

import jax
import numpy as np
import pytest

from tests.conftest import SyntheticData, TinyModel
from theanompi_tpu.models.data.prefetch import PrefetchLoader
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh
from theanompi_tpu.utils import checkpoint as ckpt
from theanompi_tpu.utils import helper_funcs as hf
from theanompi_tpu.utils.recorder import Recorder


# -- checkpoint -------------------------------------------------------------

def _model(n=4, **cfg):
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 8, **cfg}
    m = TinyModel(config)
    m.compile_iter_fns(BSP_Exchanger(config))
    m.data.shuffle_data(0)
    return m


def test_checkpoint_resume_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt")
    m1 = _model()
    for i in range(3):
        m1.train_iter(i + 1, None)
    m1.save(d, epoch=5, count=3)
    p_saved = jax.device_get(steps.unbox(m1.step_state["params"]))

    m2 = _model()
    epoch = m2.load(d)
    assert epoch == 5
    p_loaded = jax.device_get(steps.unbox(m2.step_state["params"]))
    for a, b in zip(jax.tree_util.tree_leaves(p_saved),
                    jax.tree_util.tree_leaves(p_loaded)):
        np.testing.assert_array_equal(a, b)
    # resumed model must keep training identically to the original
    # (align the data cursor — resume semantics are epoch-granular)
    for _ in range(3):
        m2.data.next_train_batch(0)
    m1.train_iter(4, None)
    m2.train_iter(4, None)
    for a, b in zip(
            jax.tree_util.tree_leaves(
                jax.device_get(steps.unbox(m1.step_state["params"]))),
            jax.tree_util.tree_leaves(
                jax.device_get(steps.unbox(m2.step_state["params"])))):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_checkpoint_latest_and_missing(tmp_path):
    d = str(tmp_path / "none")
    assert ckpt.latest_epoch(d) is None
    m = _model()
    m.save(d, epoch=1)
    m.save(d, epoch=2)
    assert ckpt.latest_epoch(d) == 2
    # params_epoch dir holds reference-style per-leaf .npy snapshots
    assert os.path.isdir(os.path.join(d, "params_epoch2"))


def test_save_params_npy_roundtrip(tmp_path):
    d = str(tmp_path / "p")
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "nest": {"b": np.ones((4,), np.float32)}}
    hf.save_params(tree, d)
    loaded = hf.load_params(tree, d)
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(loaded)):
        np.testing.assert_array_equal(a, b)


# -- flatten/unflatten ------------------------------------------------------

def test_flatten_unflatten_roundtrip():
    rng = np.random.RandomState(0)
    tree = {"w": rng.randn(3, 4).astype(np.float32),
            "b": rng.randn(5).astype(np.float32)}
    flat = hf.flatten_tree(tree, pad_to_multiple_of=8)
    assert flat.shape[0] % 8 == 0
    back = hf.unflatten_like(tree, flat)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(back[k]), tree[k])


# -- data -------------------------------------------------------------------

def test_shuffle_determinism_and_coverage():
    cfg = {"size": 4}
    d1 = SyntheticData(cfg, batch_size=8)
    d2 = SyntheticData(cfg, batch_size=8)
    d1.shuffle_data(42)
    d2.shuffle_data(42)
    b1 = d1.next_train_batch(1)
    b2 = d2.next_train_batch(1)
    np.testing.assert_array_equal(b1["x"], b2["x"])   # common-seed identical
    d2.shuffle_data(43)
    b3 = d2.next_train_batch(1)
    assert not np.array_equal(b1["x"], b3["x"])       # reshuffles

    # one epoch covers each sample at most once (disjoint strided shards)
    d1.shuffle_data(1)
    seen = []
    for i in range(d1.n_batch_train):
        seen.append(d1.next_train_batch(i)["y"].shape[0])
    assert sum(seen) <= len(d1.y_train)


def test_global_batch_scales_with_size():
    d = SyntheticData({"size": 8}, batch_size=8)
    b = d.next_train_batch(1)
    assert b["x"].shape[0] == 64
    assert b["y"].dtype == np.int32


def test_prefetch_loader_equivalence():
    direct = SyntheticData({"size": 2}, batch_size=8)
    wrapped = PrefetchLoader(SyntheticData({"size": 2}, batch_size=8))
    direct.shuffle_data(9)
    wrapped.shuffle_data(9)
    for i in range(direct.n_batch_train):
        a = direct.next_train_batch(i + 1)
        b = wrapped.next_train_batch(i + 1)
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])
    assert wrapped.n_batch_train == direct.n_batch_train


def test_prefetch_loader_surfaces_errors():
    class Boom(SyntheticData):
        def next_train_batch(self, count):
            raise RuntimeError("loader exploded")

    w = PrefetchLoader(Boom({"size": 1}, batch_size=8))
    w.shuffle_data(0)
    with pytest.raises(RuntimeError, match="loader exploded"):
        w.next_train_batch(1)


def test_hkl_batch_files_read_via_h5py(tmp_path):
    """Reference data prep produces hickle .hkl files (HDF5 inside,
    SURVEY.md §2.8); they must load without hickle installed."""
    h5py = pytest.importorskip("h5py")
    from theanompi_tpu.models.data.imagenet import (ImageNet_data,
                                                    _load_batch_file)

    rng = np.random.RandomState(7)
    batch = rng.randint(0, 256, (4, 3, 256, 256), dtype=np.uint8)  # bc01
    p = str(tmp_path / "0000.hkl")
    with h5py.File(p, "w") as f:      # hickle v2/v3 layout: root 'data'
        f.create_dataset("data", data=batch)
    np.testing.assert_array_equal(_load_batch_file(p), batch)

    # and a full ImageNet_data epoch over a tiny .hkl-backed data dir
    d = tmp_path / "imagenet"
    for sub in ("train_hkl", "val_hkl"):
        (d / sub).mkdir(parents=True)
        for i in range(2):
            with h5py.File(str(d / sub / f"{i:04d}.hkl"), "w") as f:
                f.create_dataset("data", data=batch)
    np.save(str(d / "train_labels.npy"), np.arange(8) % 4)
    np.save(str(d / "val_labels.npy"), np.arange(8) % 4)
    np.save(str(d / "img_mean.npy"),
            np.zeros((3, 256, 256), np.float32))
    data = ImageNet_data({"size": 1, "data_dir": str(d)}, batch_size=4)
    assert not data.synthetic
    data.shuffle_data(0)
    b = data.next_train_batch(0)
    assert b["x"].shape == (4, 227, 227, 3)
    assert b["x"].dtype == np.float32
    v = data.next_val_batch(0)
    assert v["y"].shape == (4,)


# -- recorder ---------------------------------------------------------------

def test_recorder_accounting(tmp_path):
    r = Recorder({"verbose": False, "printFreq": 2,
                  "record_dir": str(tmp_path)})
    for i in range(1, 5):
        r.start(); r.end("train")
        r.train_error(i, cost=1.0 / i, error=0.5, n_images=32)
        r.print_train_info(i)
    assert len(r._all_records) == 2
    assert r.n_images_total == 128
    r.val_error(4, 0.9, 0.4, 0.1)
    rec = r.print_val_info(4)
    assert rec["val_error"] == 0.4
    r.save()
    assert os.path.exists(os.path.join(str(tmp_path), "inforec_rank0.jsonl"))


def test_recorder_accepts_device_scalars():
    import jax.numpy as jnp
    r = Recorder({"verbose": False, "printFreq": 1})
    r.start(); r.end("train")
    r.train_error(1, cost=jnp.float32(2.0), error=jnp.float32(0.25),
                  n_images=8)
    r.print_train_info(1)
    assert r._all_records[-1]["cost"] == 2.0
