"""Checkpoint/resume roundtrip, loader determinism (SURVEY.md §4 item d),
prefetch-loader equivalence, helper roundtrips, recorder accounting."""

import os

import jax
import numpy as np
import pytest

from tests.conftest import SyntheticData, TinyModel
from theanompi_tpu.models.data.prefetch import PrefetchLoader
from theanompi_tpu.parallel import steps
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import worker_mesh
from theanompi_tpu.utils import checkpoint as ckpt
from theanompi_tpu.utils import helper_funcs as hf
from theanompi_tpu.utils.recorder import Recorder


# -- checkpoint -------------------------------------------------------------

def _model(n=4, **cfg):
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 8, **cfg}
    m = TinyModel(config)
    m.compile_iter_fns(BSP_Exchanger(config))
    m.data.shuffle_data(0)
    return m


def test_checkpoint_resume_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt")
    m1 = _model()
    for i in range(3):
        m1.train_iter(i + 1, None)
    m1.save(d, epoch=5, count=3)
    p_saved = jax.device_get(steps.unbox(m1.step_state["params"]))

    m2 = _model()
    epoch = m2.load(d)
    assert epoch == 5
    p_loaded = jax.device_get(steps.unbox(m2.step_state["params"]))
    for a, b in zip(jax.tree_util.tree_leaves(p_saved),
                    jax.tree_util.tree_leaves(p_loaded)):
        np.testing.assert_array_equal(a, b)
    # resumed model must keep training identically to the original — the
    # checkpoint carries the data cursor, so no manual realignment
    m1.train_iter(4, None)
    m2.train_iter(4, None)
    for a, b in zip(
            jax.tree_util.tree_leaves(
                jax.device_get(steps.unbox(m1.step_state["params"]))),
            jax.tree_util.tree_leaves(
                jax.device_get(steps.unbox(m2.step_state["params"])))):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def _train_loop(m, exch, counts):
    """Reference worker cadence: train_iter then the rule's exchange hook."""
    for c in counts:
        m.train_iter(c, None)
        exch.exchange(None, c)


@pytest.mark.parametrize("rule", ["bsp", "gosgd"])
def test_exact_resume_across_kill(tmp_path, rule):
    """Deterministic replay must survive a save/kill/resume boundary
    bit-identically (VERDICT: checkpoint completeness) — including the
    per-worker diverged replicas, GoSGD α, both PRNG keys, and the data
    cursor, mid-epoch."""
    from theanompi_tpu.parallel.exchanger import get_exchanger
    d = str(tmp_path / "ckpt")
    n = 4

    def make():
        mesh = worker_mesh(n)
        config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
                  "batch_size": 8, "exch_prob": 1.0}
        m = TinyModel(config)
        exch = get_exchanger(rule, config)
        m.compile_iter_fns(exch)
        return m, exch

    # uninterrupted run: 6 iterations
    mA, eA = make()
    mA.data.shuffle_data(0)
    _train_loop(mA, eA, range(1, 7))
    ref = jax.device_get(mA.step_state)

    # interrupted run: 3 iterations, save mid-epoch, "kill", rebuild, resume
    mB, eB = make()
    mB.data.shuffle_data(0)
    _train_loop(mB, eB, range(1, 4))
    mB.save(d, epoch=0, count=3)
    del mB, eB

    mC, eC = make()
    assert mC.load(d) == 0
    _train_loop(mC, eC, range(4, 7))
    got = jax.device_get(mC.step_state)
    for key in ref:
        for a, b in zip(jax.tree_util.tree_leaves(ref[key]),
                        jax.tree_util.tree_leaves(got[key])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prefetch_cursor_tracks_consumer():
    """The prefetch producer runs ahead; get_cursor must report the CONSUMED
    position, and a fresh loader resumed from it continues identically."""
    base = SyntheticData({"size": 2}, batch_size=8)
    w = PrefetchLoader(SyntheticData({"size": 2}, batch_size=8))
    base.shuffle_data(5)
    w.shuffle_data(5)
    for i in range(3):
        np.testing.assert_array_equal(base.next_train_batch(i)["x"],
                                      w.next_train_batch(i)["x"])
    assert w.get_cursor()["train_ptr"] == base.get_cursor()["train_ptr"] == 3

    w2 = PrefetchLoader(SyntheticData({"size": 2}, batch_size=8))
    w2.set_cursor(w.get_cursor())
    np.testing.assert_array_equal(base.next_train_batch(3)["x"],
                                  w2.next_train_batch(3)["x"])


def test_imagenet_cursor_restores_aug_stream():
    """ImageNet augmentation draws from a stateful RandomState; the cursor
    must capture it so crops/mirrors replay exactly after resume."""
    from theanompi_tpu.models.data.imagenet import ImageNet_data
    cfg = {"size": 1, "synthetic_batches": 4}
    d1 = ImageNet_data(cfg, batch_size=4)
    d1.shuffle_data(1)
    for i in range(2):
        d1.next_train_batch(i)
    cur = d1.get_cursor()
    a = d1.next_train_batch(2)
    d2 = ImageNet_data(cfg, batch_size=4)
    d2.set_cursor(cur)
    b = d2.next_train_batch(2)
    np.testing.assert_array_equal(a["x"], b["x"])


def test_bsp_checkpoint_is_worker_count_portable(tmp_path):
    """Elastic resume: a BSP grads-mode checkpoint stores ONE replica, so it
    restores onto a mesh of any worker count — train on 4 chips, resume on
    8 (the reference could not change -np between runs)."""
    d = str(tmp_path / "ckpt")
    m4 = _model(n=4)
    for i in range(3):
        m4.train_iter(i + 1, None)
    m4.save(d, epoch=0, count=3)
    ref = jax.device_get(steps.unbox(m4.step_state["params"]))

    m8 = _model(n=8)
    assert m8.load(d) == 0
    got = jax.device_get(m8.step_state["params"])
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(got)):
        for w in range(8):
            np.testing.assert_array_equal(np.asarray(b)[w], np.asarray(a))
    m8.train_iter(4, None)               # and it keeps training
    # async-rule (boxed) checkpoints are NOT portable — they must fail
    # loudly, not silently collapse replicas
    from theanompi_tpu.parallel.exchanger import GOSGD_Exchanger
    mesh = worker_mesh(4)
    cfg = {"mesh": mesh, "size": 4, "rank": 0, "verbose": False,
           "batch_size": 8}
    g4 = TinyModel(cfg)
    g4.compile_iter_fns(GOSGD_Exchanger(cfg))
    g4.data.shuffle_data(0)
    g4.train_iter(1, None)
    d2 = str(tmp_path / "gossip")
    g4.save(d2, epoch=0, count=1)
    mesh8 = worker_mesh(8)
    cfg8 = {"mesh": mesh8, "size": 8, "rank": 0, "verbose": False,
            "batch_size": 8}
    g8 = TinyModel(cfg8)
    g8.compile_iter_fns(GOSGD_Exchanger(cfg8))
    # round-5: the raw leaf-shape mismatch ("incompatible checkpoint")
    # became a targeted error naming the per-worker-state limitation
    with pytest.raises(ValueError, match="no.*worker-count refit"):
        g8.load(d2)


def test_async_ckpt_matches_sync(tmp_path):
    """async_ckpt moves only the disk write off-thread: the landed files
    must be byte-equivalent to a synchronous save of the same state."""
    d_sync = str(tmp_path / "sync")
    d_async = str(tmp_path / "async")
    m = _model(async_ckpt=True)
    for i in range(2):
        m.train_iter(i + 1, None)
    m.config["async_ckpt"] = False
    m.save(d_sync, epoch=0, count=2)
    m.config["async_ckpt"] = True
    m.save(d_async, epoch=0, count=2)
    m.wait_pending_ckpt()

    a = np.load(os.path.join(d_sync, "ckpt_epoch0.npz"))
    b = np.load(os.path.join(d_async, "ckpt_epoch0.npz"))
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])
    # and the async checkpoint restores
    m2 = _model()
    assert m2.load(d_async) == 0


def test_async_ckpt_write_failure_surfaces():
    """A failed background write must raise at the next join point — a
    silently-lost checkpoint would let a supervisor resume from an older
    epoch with no signal."""
    m = _model(async_ckpt=True)
    m.train_iter(1, None)
    m.save("/proc/definitely/not/writable", epoch=0, count=1)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        m.wait_pending_ckpt()


def test_checkpoint_latest_and_missing(tmp_path):
    d = str(tmp_path / "none")
    assert ckpt.latest_epoch(d) is None
    m = _model()
    m.save(d, epoch=1)
    m.save(d, epoch=2)
    assert ckpt.latest_epoch(d) == 2
    # params_epoch dir holds reference-style per-leaf .npy snapshots
    assert os.path.isdir(os.path.join(d, "params_epoch2"))


def test_corrupted_latest_checkpoint_falls_back_to_newest_valid(tmp_path):
    """A SIGKILL mid-save must never brick `--supervise` resume: with the
    newest checkpoint truncated (pre-atomic writer) or its sidecar torn,
    latest_epoch falls back to the newest VALID epoch and model.load
    resumes from it."""
    d = str(tmp_path / "c")
    m = _model()
    m.save(d, epoch=0)
    m.save(d, epoch=1)
    assert ckpt.checkpoint_valid(d, 1)
    # simulate the mid-save kill: epoch 1's archive truncated to half
    path1 = os.path.join(d, "ckpt_epoch1.npz")
    blob = open(path1, "rb").read()
    with open(path1, "wb") as f:
        f.write(blob[:len(blob) // 2])
    assert not ckpt.checkpoint_valid(d, 1)
    assert ckpt.latest_epoch(d) == 0               # newest VALID wins
    m2 = _model()
    assert m2.load(d) == 0                          # resume did not brick
    # torn LATEST pointer alone must not brick either
    with open(os.path.join(d, "LATEST"), "w") as f:
        f.write("not-an-int")
    assert ckpt.latest_epoch(d) == 0
    # a fully healthy dir keeps the fast path
    m.save(d, epoch=1)
    assert ckpt.latest_epoch(d) == 1
    # sidecar torn: same fallback
    with open(os.path.join(d, "ckpt_epoch1.json"), "w") as f:
        f.write('{"epoch": 1, "count"')
    assert ckpt.latest_epoch(d) == 0


def test_checkpoint_writes_are_atomic_no_temp_residue(tmp_path):
    d = str(tmp_path / "a")
    m = _model()
    m.save(d, epoch=0)
    assert not [f for f in os.listdir(d) if f.endswith(".tmp")]
    # every artifact is complete and parseable immediately after save
    assert ckpt.checkpoint_valid(d, 0)
    with open(os.path.join(d, "LATEST")) as f:
        assert int(f.read()) == 0


def test_save_params_npy_roundtrip(tmp_path):
    d = str(tmp_path / "p")
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "nest": {"b": np.ones((4,), np.float32)}}
    hf.save_params(tree, d)
    loaded = hf.load_params(tree, d)
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(loaded)):
        np.testing.assert_array_equal(a, b)


# -- flatten/unflatten ------------------------------------------------------

def test_flatten_unflatten_roundtrip():
    rng = np.random.RandomState(0)
    tree = {"w": rng.randn(3, 4).astype(np.float32),
            "b": rng.randn(5).astype(np.float32)}
    flat = hf.flatten_tree(tree, pad_to_multiple_of=8)
    assert flat.shape[0] % 8 == 0
    back = hf.unflatten_like(tree, flat)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(back[k]), tree[k])


# -- data -------------------------------------------------------------------

def test_shuffle_determinism_and_coverage():
    cfg = {"size": 4}
    d1 = SyntheticData(cfg, batch_size=8)
    d2 = SyntheticData(cfg, batch_size=8)
    d1.shuffle_data(42)
    d2.shuffle_data(42)
    b1 = d1.next_train_batch(1)
    b2 = d2.next_train_batch(1)
    np.testing.assert_array_equal(b1["x"], b2["x"])   # common-seed identical
    d2.shuffle_data(43)
    b3 = d2.next_train_batch(1)
    assert not np.array_equal(b1["x"], b3["x"])       # reshuffles

    # one epoch covers each sample at most once (disjoint strided shards)
    d1.shuffle_data(1)
    seen = []
    for i in range(d1.n_batch_train):
        seen.append(d1.next_train_batch(i)["y"].shape[0])
    assert sum(seen) <= len(d1.y_train)


def test_global_batch_scales_with_size():
    d = SyntheticData({"size": 8}, batch_size=8)
    b = d.next_train_batch(1)
    assert b["x"].shape[0] == 64
    assert b["y"].dtype == np.int32


def test_prefetch_loader_equivalence():
    direct = SyntheticData({"size": 2}, batch_size=8)
    wrapped = PrefetchLoader(SyntheticData({"size": 2}, batch_size=8))
    direct.shuffle_data(9)
    wrapped.shuffle_data(9)
    for i in range(direct.n_batch_train):
        a = direct.next_train_batch(i + 1)
        b = wrapped.next_train_batch(i + 1)
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])
    assert wrapped.n_batch_train == direct.n_batch_train


def test_prefetch_overlaps_slow_io_with_compute():
    """The point of para_load (SURVEY.md §2.8): loader latency must hide
    behind compute.  Producer costs 30ms/batch; consumer 'computes' 45ms;
    with depth-2 prefetch the summed load-wait must be a fraction of the
    serial 6×30ms."""
    import time

    class SlowData(SyntheticData):
        def next_train_batch(self, count):
            time.sleep(0.03)
            return super().next_train_batch(count)

    w = PrefetchLoader(SlowData({"size": 1}, batch_size=8))
    w.shuffle_data(0)
    t_load = 0.0
    for i in range(6):
        t0 = time.perf_counter()
        w.next_train_batch(i + 1)
        t_load += time.perf_counter() - t0
        time.sleep(0.045)            # stand-in for the training step
    # serial loading would cost 6×30ms = 180ms of load wait; require clear
    # overlap but leave headroom for CI scheduler noise
    assert t_load < 0.75 * 6 * 0.03, f"load wait {t_load:.3f}s — no overlap"


def test_para_load_stages_batches_onto_device():
    """With para_load=True the producer thread device_puts batches; the
    training loop must consume device-resident arrays (t_load' covers only
    the queue get) and still train correctly."""
    import jax.numpy as jnp
    m = _model(para_load=True)
    r = Recorder({"verbose": False, "printFreq": 1})
    m.data.shuffle_data(0)
    b = m.data.next_train_batch(1)
    assert isinstance(jax.tree_util.tree_leaves(b)[0], jax.Array)
    m.data.set_cursor(m.data.get_cursor())   # restart producer at ptr=1
    for i in range(2, 5):
        m.train_iter(i, r)
    assert np.isfinite(float(jnp.mean(np.asarray(m.current_info["cost"]))))
    # equivalence with the unwrapped path
    m2 = _model()
    m2.data.shuffle_data(0)
    m2.data.next_train_batch(1)
    for i in range(2, 5):
        m2.train_iter(i, None)
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(m.step_state["params"])),
            jax.tree_util.tree_leaves(jax.device_get(m2.step_state["params"]))):
        np.testing.assert_array_equal(a, b)


def test_prefetch_loader_surfaces_errors():
    class Boom(SyntheticData):
        def next_train_batch(self, count):
            raise RuntimeError("loader exploded")

    w = PrefetchLoader(Boom({"size": 1}, batch_size=8))
    w.shuffle_data(0)
    with pytest.raises(RuntimeError, match="loader exploded"):
        w.next_train_batch(1)


def test_hkl_batch_files_read_via_h5py(tmp_path):
    """Reference data prep produces hickle .hkl files (HDF5 inside,
    SURVEY.md §2.8); they must load without hickle installed."""
    h5py = pytest.importorskip("h5py")
    from theanompi_tpu.models.data.imagenet import (ImageNet_data,
                                                    _load_batch_file)

    rng = np.random.RandomState(7)
    batch = rng.randint(0, 256, (4, 3, 256, 256), dtype=np.uint8)  # bc01
    p = str(tmp_path / "0000.hkl")
    with h5py.File(p, "w") as f:      # hickle v2/v3 layout: root 'data'
        f.create_dataset("data", data=batch)
    np.testing.assert_array_equal(_load_batch_file(p), batch)

    # and a full ImageNet_data epoch over a tiny .hkl-backed data dir
    d = tmp_path / "imagenet"
    for sub in ("train_hkl", "val_hkl"):
        (d / sub).mkdir(parents=True)
        for i in range(2):
            with h5py.File(str(d / sub / f"{i:04d}.hkl"), "w") as f:
                f.create_dataset("data", data=batch)
    np.save(str(d / "train_labels.npy"), np.arange(8) % 4)
    np.save(str(d / "val_labels.npy"), np.arange(8) % 4)
    np.save(str(d / "img_mean.npy"),
            np.zeros((3, 256, 256), np.float32))
    data = ImageNet_data({"size": 1, "data_dir": str(d)}, batch_size=4)
    assert not data.synthetic
    data.shuffle_data(0)
    b = data.next_train_batch(0)
    assert b["x"].shape == (4, 227, 227, 3)
    assert b["x"].dtype == np.float32
    v = data.next_val_batch(0)
    assert v["y"].shape == (4,)


# -- recorder ---------------------------------------------------------------

def test_recorder_accounting(tmp_path):
    r = Recorder({"verbose": False, "printFreq": 2,
                  "record_dir": str(tmp_path)})
    for i in range(1, 5):
        r.start(); r.end("train")
        r.train_error(i, cost=1.0 / i, error=0.5, n_images=32)
        r.print_train_info(i)
    assert len(r._all_records) == 2
    assert r.n_images_total == 128
    r.val_error(4, 0.9, 0.4, 0.1)
    rec = r.print_val_info(4)
    assert rec["val_error"] == 0.4
    r.save()
    assert os.path.exists(os.path.join(str(tmp_path), "inforec_rank0.jsonl"))


def test_sync_each_iter_writes_wait_bucket():
    """In blocking mode t_train (dispatch) + t_wait (device-bound block) sum
    to wall time — the wait bucket must actually be written (VERDICT: it had
    no writer anywhere)."""
    m = _model(sync_each_iter=True)
    r = Recorder({"verbose": False, "printFreq": 1})
    m.train_iter(1, r)
    assert "wait" in r.t_sec_total
    assert r.t_sec_total["wait"] >= 0.0
    assert r.t_sec_total["train"] > 0.0


def test_recorder_accepts_device_scalars():
    import jax.numpy as jnp
    r = Recorder({"verbose": False, "printFreq": 1})
    r.start(); r.end("train")
    r.train_error(1, cost=jnp.float32(2.0), error=jnp.float32(0.25),
                  n_images=8)
    r.print_train_info(1)
    assert r._all_records[-1]["cost"] == 2.0


def test_pooled_prefetch_stream_bit_identical(tmp_path):
    """round-4: the pooled producer (sequential plans, thread-pool
    materialization) must emit EXACTLY the serial producer's batch stream —
    same order, same augmentation draws — for any pool size."""
    import numpy as np
    from theanompi_tpu.models.data.imagenet import ImageNet_data
    from theanompi_tpu.models.data.prefetch import PrefetchLoader

    cfg = {"size": 1, "synthetic_batches": 6, "n_class": 10, "seed": 9}
    serial = PrefetchLoader(ImageNet_data(dict(cfg), batch_size=4),
                            n_workers=1)
    pooled = PrefetchLoader(ImageNet_data(dict(cfg), batch_size=4),
                            n_workers=4)
    serial.shuffle_data(3)
    pooled.shuffle_data(3)
    for i in range(6):
        a = serial.next_train_batch(i)
        b = pooled.next_train_batch(i)
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])
    # cursor semantics survive pooling (mid-epoch resume contract)
    assert serial.get_cursor()["train_ptr"] == \
        pooled.get_cursor()["train_ptr"]
