"""Native C++ loader hot path vs the NumPy reference implementation.

The native library (theanompi_tpu/native/loader.cc) must be bit-identical to
the NumPy fallback for every supported mode: both compute
``float32(uint8) - float32(mean)`` with no intermediate rounding, so exact
equality is the correct assertion (not allclose).
"""

import numpy as np
import pytest

from theanompi_tpu import native


def _params(rng, n, h, w, crop, per_image):
    m = n if per_image else 1
    oy = rng.randint(0, h - crop + 1, size=m).astype(np.int32)
    ox = rng.randint(0, w - crop + 1, size=m).astype(np.int32)
    flip = rng.randint(0, 2, size=m).astype(np.uint8)
    return oy, ox, flip


@pytest.mark.parametrize("per_image", [False, True])
@pytest.mark.parametrize("layout", ["nhwc", "nchw"])
@pytest.mark.parametrize("mean_kind", ["scalar", "image"])
def test_native_matches_numpy(per_image, layout, mean_kind):
    if not native.native_available():
        pytest.skip("no native toolchain in this environment")
    rng = np.random.RandomState(0)
    n, h, w, c, crop = 7, 20, 24, 3, 13
    x = rng.randint(0, 256, (n, h, w, c), dtype=np.uint8)
    if layout == "nchw":
        x = np.ascontiguousarray(x.transpose(0, 3, 1, 2))
    oy, ox, flip = _params(rng, n, h, w, crop, per_image)
    mean = (rng.randn(crop, crop, c).astype(np.float32) * 10
            if mean_kind == "image" else None)
    ms = 0.0 if mean_kind == "image" else 117.5

    got = native.augment_batch(x, oy, ox, flip, crop, mean=mean,
                               mean_scalar=ms)
    want = native._augment_numpy(
        x, np.broadcast_to(oy, (n,)), np.broadcast_to(ox, (n,)),
        np.broadcast_to(flip, (n,)), crop, mean, ms)
    assert got.shape == (n, crop, crop, c)
    assert got.dtype == np.float32
    np.testing.assert_array_equal(got, want)


def test_single_thread_matches_multi():
    if not native.native_available():
        pytest.skip("no native toolchain in this environment")
    rng = np.random.RandomState(1)
    n, h, w, c, crop = 16, 32, 32, 3, 27
    x = rng.randint(0, 256, (n, h, w, c), dtype=np.uint8)
    oy, ox, flip = _params(rng, n, h, w, crop, True)
    a = native.augment_batch(x, oy, ox, flip, crop, n_threads=1)
    b = native.augment_batch(x, oy, ox, flip, crop, n_threads=8)
    np.testing.assert_array_equal(a, b)


def test_imagenet_data_uses_fused_pass():
    """The ImageNet data object routes through augment_batch in both
    synthetic and per-image modes and produces the contract shapes."""
    from theanompi_tpu.models.data.imagenet import ImageNet_data

    d = ImageNet_data({"size": 1, "synthetic_batches": 2, "n_class": 10,
                       "aug_per_image": True}, batch_size=4)
    b = d.next_train_batch(0)
    assert b["x"].shape == (4, 227, 227, 3) and b["x"].dtype == np.float32
    assert b["y"].shape == (4,) and b["y"].dtype == np.int32
    v = d.next_val_batch(0)
    assert v["x"].shape == (4, 227, 227, 3)


@pytest.mark.parametrize("per_image", [False, True])
def test_u8_wire_mode_matches_f32_pipeline(per_image):
    """round-4 u8-wire lever: uint8 crops shipped to device + on-device
    float32 cast/mean-subtract must equal the host fused pass bit-for-bit
    (scalar mean; identical augmentation RNG draws)."""
    from theanompi_tpu.models.data.imagenet import ImageNet_data

    cfg = {"size": 1, "synthetic_batches": 2, "n_class": 10,
           "aug_per_image": per_image, "seed": 5}
    f32 = ImageNet_data(dict(cfg), batch_size=4)
    u8 = ImageNet_data(dict(cfg, aug_wire_u8=True), batch_size=4)
    f32.shuffle_data(0)
    u8.shuffle_data(0)
    a = f32.next_train_batch(0)
    b = u8.next_train_batch(0)
    assert b["x"].dtype == np.uint8 and a["x"].dtype == np.float32
    np.testing.assert_array_equal(a["y"], b["y"])
    # device-side arithmetic (float32(u8) - scalar mean) == host fused pass
    mean = float(u8.img_mean)
    np.testing.assert_array_equal(
        a["x"], b["x"].astype(np.float32) - np.float32(mean))
    # val path: center crop, no mirror
    av, bv = f32.next_val_batch(0), u8.next_val_batch(0)
    np.testing.assert_array_equal(
        av["x"], bv["x"].astype(np.float32) - np.float32(mean))


def test_u8_wire_trains_alexnet_smoke(mesh8):
    """End to end: AlexNet consumes the uint8 batch, the ModelBase loss
    path casts+subtracts on device, and a train step runs finite."""
    import jax
    import jax.numpy as jnp
    from theanompi_tpu.models.alex_net import AlexNet
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger
    from theanompi_tpu.parallel.mesh import worker_mesh

    mesh = worker_mesh(2)
    cfg = {"mesh": mesh, "size": 2, "rank": 0, "verbose": False,
           "batch_size": 4, "synthetic_batches": 2, "aug_wire_u8": True,
           "compute_dtype": jnp.float32}
    m = AlexNet(cfg)
    m.compile_iter_fns(BSP_Exchanger(cfg))
    m.data.shuffle_data(0)
    m.train_iter(1, None)
    cost = float(m.current_info["cost"])
    assert np.isfinite(cost)
    # the VAL path stages u8 too (ModelBase.stage_input is shared — a raw
    # 0..255 val input would score garbage silently)
    m.begin_val()
    m.val_iter(0)
    m.end_val()


def test_u8_wire_mean_survives_para_load(tmp_path):
    """Regression (round-4 review): with para_load on, the model's data is
    a PrefetchLoader — the u8-wire device mean must still read the REAL
    mean image through the wrapper, not fall back to the scalar 122."""
    import subprocess
    import sys as _sys

    import jax.numpy as jnp
    from theanompi_tpu.models.alex_net import AlexNet
    from theanompi_tpu.parallel.mesh import worker_mesh

    d = str(tmp_path / "mini_imagenet")
    subprocess.run(
        [_sys.executable, "scripts/make_batch_dataset.py", "--synthetic",
         "4", "--batch-size", "4", "--out", d],
        check=True, capture_output=True)
    cfg = {"mesh": worker_mesh(1), "size": 1, "rank": 0, "verbose": False,
           "batch_size": 4, "data_dir": d, "para_load": True,
           "aug_wire_u8": True, "compute_dtype": jnp.float32}
    m = AlexNet(cfg)
    from theanompi_tpu.models.data.prefetch import PrefetchLoader
    assert isinstance(m.data, PrefetchLoader)
    mean = np.asarray(m._u8_input_mean())
    # the generated img_mean.npy is a full [256,256,3] mean image — the
    # device constant must be its center crop, not a scalar
    assert mean.ndim == 3 and mean.shape[-1] == 3, mean.shape
    import os as _os
    full = np.load(_os.path.join(d, "img_mean.npy"))
    c = mean.shape[0]
    cy, cx = (full.shape[0] - c) // 2, (full.shape[1] - c) // 2
    np.testing.assert_allclose(mean, full[cy:cy + c, cx:cx + c, :],
                               rtol=1e-6)
