"""Native C++ loader hot path vs the NumPy reference implementation.

The native library (theanompi_tpu/native/loader.cc) must be bit-identical to
the NumPy fallback for every supported mode: both compute
``float32(uint8) - float32(mean)`` with no intermediate rounding, so exact
equality is the correct assertion (not allclose).
"""

import numpy as np
import pytest

from theanompi_tpu import native


def _params(rng, n, h, w, crop, per_image):
    m = n if per_image else 1
    oy = rng.randint(0, h - crop + 1, size=m).astype(np.int32)
    ox = rng.randint(0, w - crop + 1, size=m).astype(np.int32)
    flip = rng.randint(0, 2, size=m).astype(np.uint8)
    return oy, ox, flip


@pytest.mark.parametrize("per_image", [False, True])
@pytest.mark.parametrize("layout", ["nhwc", "nchw"])
@pytest.mark.parametrize("mean_kind", ["scalar", "image"])
def test_native_matches_numpy(per_image, layout, mean_kind):
    if not native.native_available():
        pytest.skip("no native toolchain in this environment")
    rng = np.random.RandomState(0)
    n, h, w, c, crop = 7, 20, 24, 3, 13
    x = rng.randint(0, 256, (n, h, w, c), dtype=np.uint8)
    if layout == "nchw":
        x = np.ascontiguousarray(x.transpose(0, 3, 1, 2))
    oy, ox, flip = _params(rng, n, h, w, crop, per_image)
    mean = (rng.randn(crop, crop, c).astype(np.float32) * 10
            if mean_kind == "image" else None)
    ms = 0.0 if mean_kind == "image" else 117.5

    got = native.augment_batch(x, oy, ox, flip, crop, mean=mean,
                               mean_scalar=ms)
    want = native._augment_numpy(
        x, np.broadcast_to(oy, (n,)), np.broadcast_to(ox, (n,)),
        np.broadcast_to(flip, (n,)), crop, mean, ms)
    assert got.shape == (n, crop, crop, c)
    assert got.dtype == np.float32
    np.testing.assert_array_equal(got, want)


def test_single_thread_matches_multi():
    if not native.native_available():
        pytest.skip("no native toolchain in this environment")
    rng = np.random.RandomState(1)
    n, h, w, c, crop = 16, 32, 32, 3, 27
    x = rng.randint(0, 256, (n, h, w, c), dtype=np.uint8)
    oy, ox, flip = _params(rng, n, h, w, crop, True)
    a = native.augment_batch(x, oy, ox, flip, crop, n_threads=1)
    b = native.augment_batch(x, oy, ox, flip, crop, n_threads=8)
    np.testing.assert_array_equal(a, b)


def test_imagenet_data_uses_fused_pass():
    """The ImageNet data object routes through augment_batch in both
    synthetic and per-image modes and produces the contract shapes."""
    from theanompi_tpu.models.data.imagenet import ImageNet_data

    d = ImageNet_data({"size": 1, "synthetic_batches": 2, "n_class": 10,
                       "aug_per_image": True}, batch_size=4)
    b = d.next_train_batch(0)
    assert b["x"].shape == (4, 227, 227, 3) and b["x"].dtype == np.float32
    assert b["y"].shape == (4,) and b["y"].dtype == np.int32
    v = d.next_val_batch(0)
    assert v["x"].shape == (4, 227, 227, 3)
