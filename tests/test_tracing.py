"""Profiler trace capture via the worker loop's trace_dir hook."""

import glob
import os

import theanompi_tpu as tmpi


def test_trace_dir_produces_a_capture(tmp_path):
    trace_dir = str(tmp_path / "trace")
    rule = tmpi.BSP()
    rule.init(devices=2, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model", epochs=1, synthetic_train=64,
              synthetic_val=16, batch_size=8, compute_dtype="float32",
              verbose=False, scale_lr=False,
              trace_dir=trace_dir, trace_start=2, trace_iters=2)
    rule.wait()
    # jax writes plugins/profile/<ts>/*.trace.json.gz (exact layout varies by
    # jax version) — assert a trace artifact exists at all
    found = glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in found), found


def test_trace_window_outliving_training_still_flushes(tmp_path):
    """A trace window extending past the last iteration must still be
    stopped and flushed (regression: stop was an exact-count match)."""
    trace_dir = str(tmp_path / "trace2")
    rule = tmpi.BSP()
    rule.init(devices=2, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model", epochs=1, synthetic_train=32,
              synthetic_val=16, batch_size=8, compute_dtype="float32",
              verbose=False, scale_lr=False,
              # 2 train iters; window starts at 2 and wants 50 more
              trace_dir=trace_dir, trace_start=2, trace_iters=50)
    rule.wait()
    found = glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in found), found

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
