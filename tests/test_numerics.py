"""Numerics health plane (ISSUE 19, docs/design.md §25).

The acceptance contract, pinned here:

* **inertness** — per exchange rule (BSP grads, BSP fused spc>1, EASGD,
  onebit-compressed wire), the training stream with ``numerics=true`` is
  bit-identical (``assert_array_equal``, params/opt_state/extra AND the
  cost stream) to the same run with the plane off: the observer reads
  already-live values and changes no update math;
* **beacon semantics** — bit-identical BSP replicas produce bitwise-equal
  digests (divergence exactly 0.0), EASGD reports the exact ``‖w_i − c‖``
  distance, the EF-buffer norm streams for the compressed wires, and a
  corrupted per-rank digest shows as ``divergence > 0`` in the same
  report;
* **host plane** — ``host_report`` worst-rank aggregation, nan-safe
  divergence, no-sample/no-beacon None semantics; ``record`` covers the
  declared gauge/histogram/event vocabulary under one ``enabled`` check;
* **sentry detectors** — grad_overflow / replica_divergence /
  update_ratio_collapse ordering, the latest-sample-carry iter dedupe,
  and ``notice_discontinuity`` consuming exactly one report;
* **compile-cache identity** — the train key stamps the plane only when
  it is effectively on, so every pre-existing (and every numerics-off)
  key stays byte-stable.
"""

import math

import numpy as np
import pytest

import jax

from tests.conftest import TinyModel
from theanompi_tpu.parallel.exchanger import (BSP_Exchanger,
                                              EASGD_Exchanger)
from theanompi_tpu.parallel.mesh import worker_mesh
from theanompi_tpu.utils import compile_cache, numerics, telemetry
from theanompi_tpu.utils.sentry import TrainingSentry

N = 4


def _build(exch_cls, spc=1, numerics_on=False, n=N, **cfg):
    mesh = worker_mesh(n)
    config = {"mesh": mesh, "size": n, "rank": 0, "verbose": False,
              "batch_size": 8, "steps_per_call": spc, **cfg}
    if numerics_on:
        config["numerics"] = True
    model = TinyModel(config)
    exch = exch_cls(config)
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    return model, exch


def _drive(model, exch, k=1, n_steps=8):
    """Worker-loop shape (test_fused_exchange idiom): count strides by
    steps_per_call, the standalone hook still called — fused exchangers
    stand down by themselves."""
    costs = []
    for count in range(k, n_steps + 1, k):
        model.train_iter(count, None)
        exch.exchange(None, count)
        costs.append(float(model.current_info["cost"]))
    return jax.device_get(model.step_state), costs


def _assert_state_equal(a, b):
    for part in ("params", "opt_state", "extra"):
        for x, y in zip(jax.tree_util.tree_leaves(a[part]),
                        jax.tree_util.tree_leaves(b[part])):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=part)


# -- inertness: the tentpole guarantee ---------------------------------------

@pytest.mark.parametrize("exch_cls,spc,cfg", [
    (BSP_Exchanger, 1, {}),
    (BSP_Exchanger, 4, {}),                         # fused in-scan sampling
    (EASGD_Exchanger, 1, {"sync_freq": 2}),
    (BSP_Exchanger, 1, {"exch_strategy": "onebit"}),
], ids=["bsp", "bsp-fused-spc4", "easgd", "onebit"])
def test_numerics_observer_is_inert(exch_cls, spc, cfg):
    s_off, c_off = _drive(*_build(exch_cls, spc, **cfg), k=spc)
    s_on, c_on = _drive(*_build(exch_cls, spc, numerics_on=True, **cfg),
                        k=spc)
    _assert_state_equal(s_off, s_on)
    np.testing.assert_array_equal(np.asarray(c_off), np.asarray(c_on))


def test_numerics_off_exposes_no_aux():
    model, exch = _build(BSP_Exchanger)
    _drive(model, exch)
    assert model.numerics_aux is None


# -- beacon semantics (traced plane) -----------------------------------------

def test_bsp_digests_bitwise_equal_and_stats_live():
    model, exch = _build(BSP_Exchanger, numerics_on=True)
    _drive(model, exch, n_steps=6)
    aux = jax.device_get(model.numerics_aux)
    rep = numerics.host_report(aux)
    assert rep is not None and rep["iter"] == 6
    assert rep["n_workers"] == N
    # BSP post-exchange replicas are bit-identical → the per-rank digests
    # are EXACTLY equal floats, and the gathered divergence is exactly 0.0
    digests = rep["per_rank"]["digest"]
    assert all(d == digests[0] for d in digests), digests
    assert rep["divergence"] == 0.0
    assert all(b == 1.0 for b in rep["per_rank"]["beacon"])
    # the stats read live values: a real training step has nonzero norms
    assert rep["grad_norm"] > 0 and rep["param_norm"] > 0
    assert rep["update_norm"] > 0 and rep["update_ratio"] > 0
    assert rep["nonfinite"] == 0
    assert math.isfinite(rep["grad_max_abs"]) and rep["grad_max_abs"] > 0


def test_bsp_corrupted_digest_reads_as_divergence():
    model, exch = _build(BSP_Exchanger, numerics_on=True)
    _drive(model, exch, n_steps=4)
    aux = jax.device_get(model.numerics_aux)
    aux = {k: np.asarray(v).copy() for k, v in aux.items()}
    aux["digest"][2] += 1e-3            # one replica bit-desyncs
    rep = numerics.host_report(aux)
    # f32 digest arithmetic: the perturbation lands to ulp precision
    assert rep["divergence"] == pytest.approx(1e-3, rel=1e-2)


def test_easgd_reports_exact_distance_to_center():
    model, exch = _build(EASGD_Exchanger, numerics_on=True, sync_freq=2)
    # odd last step: the unfused sample reads the extra tree of ITS OWN
    # step (pre-exchange), so stop where no sync round follows and the
    # final state is exactly what the sample saw
    _drive(model, exch, n_steps=7)
    aux = jax.device_get(model.numerics_aux)
    rep = numerics.host_report(aux)
    # ‖w_i − c‖ — the central quantity of the source paper — recomputed
    # here against the live state the dispatch returned
    params = jax.device_get(model.step_state["params"])
    center = jax.device_get(model.step_state["extra"]["center"])
    for w in range(N):
        want = math.sqrt(sum(
            float(np.sum(np.square(
                np.asarray(p[w], np.float64) -
                np.asarray(c[w], np.float64))))
            for p, c in zip(jax.tree_util.tree_leaves(params),
                            jax.tree_util.tree_leaves(center))))
        got = rep["per_rank"]["dist_center"][w]
        np.testing.assert_allclose(got, want, rtol=1e-4)
    assert rep["dist_center"] == max(rep["per_rank"]["dist_center"])
    # the center copies must agree → the beacon digests them, divergence 0
    assert rep["divergence"] == 0.0


def test_onebit_streams_error_feedback_norm():
    model, exch = _build(BSP_Exchanger, numerics_on=True,
                         exch_strategy="onebit")
    _drive(model, exch, n_steps=6)
    rep = numerics.host_report(jax.device_get(model.numerics_aux))
    # the 1-bit quantizer always leaves a residual on a real gradient
    assert rep["ef_norm"] > 0


def test_cadence_spc1_documented_semantics():
    # spc=1 has no scan to carry a sample through: an off-cadence
    # dispatch returns the template and the host report skips it (§25) —
    # align numerics_every with the print cadence to see every sample
    model, exch = _build(BSP_Exchanger, numerics_on=True, numerics_every=4)
    _drive(model, exch, n_steps=6)
    assert numerics.host_report(jax.device_get(model.numerics_aux)) is None
    model, exch = _build(BSP_Exchanger, numerics_on=True, numerics_every=4)
    _drive(model, exch, n_steps=8)
    rep = numerics.host_report(jax.device_get(model.numerics_aux))
    assert rep is not None and rep["iter"] == 8


def test_cadence_fused_carries_latest_sample():
    # inside a fused window the scan carry holds the latest sample: the
    # spc=4 window ending at count 8 runs c = 5..8, only c=6 is on the
    # every=3 cadence, and THAT sample survives to the window's output
    model, exch = _build(BSP_Exchanger, spc=4, numerics_on=True,
                         numerics_every=3)
    _drive(model, exch, k=4, n_steps=8)
    rep = numerics.host_report(jax.device_get(model.numerics_aux))
    assert rep is not None and rep["iter"] == 6


# -- host report plane -------------------------------------------------------

def test_host_report_none_before_first_sample():
    assert numerics.host_report(None) is None
    aux = {k: [0.0, 0.0] for k in numerics.SAMPLE_KEYS}
    aux["iter"] = [-1.0, -1.0]
    assert numerics.host_report(aux) is None


def test_host_report_worst_rank_aggregation():
    aux = {k: [0.0, 0.0] for k in numerics.SAMPLE_KEYS}
    aux.update(iter=[8.0, 8.0], grad_norm=[1.0, 3.0],
               grad_max_abs=[0.5, 0.25], nonfinite=[1.0, 2.0],
               param_norm=[10.0, 20.0], update_norm=[0.1, 0.2],
               update_ratio=[0.01, 0.002], dist_center=[0.3, 0.7],
               ef_norm=[0.0, 0.4], digest=[5.0, 5.5], beacon=[1.0, 1.0])
    rep = numerics.host_report(aux)
    assert rep["grad_norm"] == 3.0 and rep["grad_max_abs"] == 0.5
    assert rep["nonfinite"] == 3.0                     # summed, not max'd
    assert rep["update_ratio"] == 0.002                # min: the collapse
    assert rep["dist_center"] == 0.7 and rep["ef_norm"] == 0.4
    assert rep["divergence"] == pytest.approx(0.5)


def test_host_report_divergence_nan_safe_and_beacon_gated():
    aux = {k: [0.0, 0.0] for k in numerics.SAMPLE_KEYS}
    aux.update(iter=[2.0, 2.0], digest=[1.0, float("nan")],
               beacon=[1.0, 1.0])
    # a corrupted replica whose digest went nan must still TRIP the
    # beacon, not slip through max() comparisons
    assert numerics.host_report(aux)["divergence"] == float("inf")
    aux["beacon"] = [1.0, 0.0]           # <2 valid beacons → no verdict
    assert numerics.host_report(aux)["divergence"] is None


def test_record_covers_declared_vocabulary():
    tm = telemetry.Telemetry(rank=0, run_id="numerics-test")
    numerics.record(tm, numerics.example_report(), rank=3)
    assert set(numerics.NUMERICS_GAUGES) <= set(tm.gauges)
    assert set(numerics.NUMERICS_HISTOGRAMS) <= set(tm.hists)
    evs = [e for e in tm.tail(4) if e["ev"] == numerics.NUMERICS_EVENT]
    assert len(evs) == 1 and evs[0]["rank"] == 3
    assert evs[0]["beacon"] == 1
    # divergence None (no beacon) still gauges 0.0 and events as None
    rep = dict(numerics.example_report())
    rep["divergence"] = None
    rep["iter"] = 2
    numerics.record(tm, rep)
    assert tm.gauges["numerics.divergence"] == 0.0
    ev = [e for e in tm.tail(4) if e["ev"] == numerics.NUMERICS_EVENT][-1]
    assert ev["divergence"] is None and ev["beacon"] == 0


# -- sentry detectors --------------------------------------------------------

def _rep(**kw):
    rep = dict(numerics.example_report())
    rep.update(kw)
    return rep


def test_sentry_detector_order_and_kinds():
    s = TrainingSentry({"verbose": False}, telemetry=telemetry.DISABLED)
    # overflow wins even when the report ALSO diverges
    assert s.observe_numerics(_rep(iter=1, nonfinite=2.0,
                                   divergence=9.0)) == "grad_overflow"
    assert s.observe_numerics(_rep(iter=2, divergence=1e-6)) == \
        "replica_divergence"
    assert s.observe_numerics(_rep(iter=3, update_ratio=1e-15)) == \
        "update_ratio_collapse"
    assert s.observe_numerics(_rep(iter=4)) is None     # healthy
    # a non-finite grad_norm is an overflow even with nonfinite count 0
    assert s.observe_numerics(_rep(iter=5, grad_norm=float("inf"))) == \
        "grad_overflow"
    assert [k for k, _ in s.anomalies] == \
        ["grad_overflow", "replica_divergence", "update_ratio_collapse",
         "grad_overflow"]
    assert set(k for k, _ in s.anomalies) <= set(numerics.SENTRY_KINDS)


def test_sentry_iter_dedupe_latest_sample_carry():
    s = TrainingSentry({"verbose": False}, telemetry=telemetry.DISABLED)
    bad = _rep(iter=7, nonfinite=1.0)
    assert s.observe_numerics(bad) == "grad_overflow"
    # the aux is a latest-sample carry: the SAME sampled step surfacing
    # under the next print record must not double-count
    assert s.observe_numerics(bad) is None
    assert s.observe_numerics(_rep(iter=9, nonfinite=1.0)) == \
        "grad_overflow"


def test_sentry_discontinuity_consumes_one_report():
    s = TrainingSentry({"verbose": False}, telemetry=telemetry.DISABLED)
    s.notice_discontinuity()
    # first report after a val/ckpt/restore boundary: neither judged nor
    # learned from (a rejoin legitimately moves the beacon)
    assert s.observe_numerics(_rep(iter=1, divergence=5.0)) is None
    assert s.observe_numerics(_rep(iter=2, divergence=5.0)) == \
        "replica_divergence"


def test_sentry_thresholds_are_config_knobs():
    s = TrainingSentry({"verbose": False, "sentry_divergence_eps": 10.0},
                       telemetry=telemetry.DISABLED)
    assert s.observe_numerics(_rep(iter=1, divergence=5.0)) is None
    assert s.observe_numerics(_rep(iter=2, divergence=11.0)) == \
        "replica_divergence"
    s2 = TrainingSentry({"verbose": False, "sentry_ratio_floor": 0.5},
                        telemetry=telemetry.DISABLED)
    assert s2.observe_numerics(_rep(iter=3, update_ratio=0.4)) == \
        "update_ratio_collapse"
    assert s2.observe_numerics(_rep(iter=4, update_ratio=0.6)) is None


def test_sentry_none_report_is_noop():
    s = TrainingSentry({"verbose": False}, telemetry=telemetry.DISABLED)
    assert s.observe_numerics(None) is None
    assert s.anomalies == []


# -- compile-cache identity --------------------------------------------------

class _FakeModel:
    n_subb = 1
    pp_interleave = 1
    _fsdp = None

    def __init__(self, cfg):
        self.config = cfg


def test_compile_key_stamps_numerics_only_when_on():
    base = compile_cache.key_extra("train", _FakeModel({}), spc=1)
    off = compile_cache.key_extra(
        "train", _FakeModel({"numerics": False}), spc=1)
    assert base == off and "numerics" not in base      # byte-stable keys
    on = compile_cache.key_extra(
        "train", _FakeModel({"numerics": True}), spc=1)
    assert on["numerics"] == numerics.DEFAULT_EVERY
    on2 = compile_cache.key_extra(
        "train", _FakeModel({"numerics": True, "numerics_every": 5}),
        spc=1)
    assert on2["numerics"] == 5 and on2 != on
    # the plane only reshapes the TRAIN step; spc-independent programs
    # (and fsdp builds, where the plane stands down) stay unstamped
    val = compile_cache.key_extra(
        "val", _FakeModel({"numerics": True}))
    assert "numerics" not in val
    fsdp_model = _FakeModel({"numerics": True})
    fsdp_model._fsdp = object()
    assert "numerics" not in compile_cache.key_extra(
        "train", fsdp_model, spc=1)
