"""Sequence parallelism as a model mode (parallel/sp.py): TransformerLM with
sp=k shards the TIME dimension over a 'seq' axis and runs ring attention —
it must be the same model as the dense layout (same init, same losses),
which also pins the batch-spec plumbing (x/y sharded [workers, seq]).

The ring-attention op itself is oracle-pinned in test_ring_attention.py;
this file pins the MODEL integration.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.models.transformer_lm import TransformerLM
from theanompi_tpu.parallel.exchanger import BSP_Exchanger, get_exchanger
from theanompi_tpu.parallel.mesh import SEQ_AXIS, WORKER_AXIS, worker_mesh

LM_CFG = dict(verbose=False, batch_size=8, seq_len=32, vocab=32,
              synthetic_train=64, synthetic_val=32,
              d_model=32, n_head=4, n_layer=2, compute_dtype=jnp.float32)


def _make(dp, sp, **kw):
    mesh = worker_mesh(dp, sp=sp)
    cfg = {**LM_CFG, "mesh": mesh, "size": dp, "rank": 0, "sp": sp, **kw}
    return TransformerLM(cfg)


def _train_steps(model, exch, n_steps):
    model.compile_iter_fns(exch)
    model.data.shuffle_data(0)
    costs = []
    for i in range(n_steps):
        model.train_iter(i, None)
        costs.append(float(model.current_info["cost"]))
    return costs


def test_sp_mesh_and_batch_sharding(mesh8):
    model = _make(dp=2, sp=4)
    assert dict(model.mesh.shape) == {WORKER_AXIS: 2, SEQ_AXIS: 4}
    model.compile_iter_fns(BSP_Exchanger(model.config))
    from theanompi_tpu.parallel import steps
    model.data.shuffle_data(0)
    batch = model.data.next_train_batch(0)
    dev = steps.put_batch(model.mesh, batch, model.batch_spec())
    assert dev["x"].sharding.spec == (WORKER_AXIS, SEQ_AXIS)
    # one chip holds [rows/dp, T/sp]
    assert dev["x"].addressable_shards[0].data.shape == (8, 8)


def test_sp_init_identical_to_dense(mesh8):
    dense = _make(dp=2, sp=1)
    sp = _make(dp=2, sp=4)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), dense.params, sp.params)


def test_sp_bsp_training_matches_dense(mesh8):
    dense = _make(dp=2, sp=1)
    sp = _make(dp=2, sp=4)
    c_dense = _train_steps(dense, BSP_Exchanger(dense.config), 6)
    c_sp = _train_steps(sp, BSP_Exchanger(sp.config), 6)
    np.testing.assert_allclose(c_sp, c_dense, rtol=2e-4, atol=2e-5)
    from theanompi_tpu.parallel import steps
    pd = steps.unbox(jax.device_get(steps.tree_to_host(
        dense.step_state["params"])))
    ps = steps.unbox(jax.device_get(steps.tree_to_host(
        sp.step_state["params"])))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5), pd, ps)


def test_sp_val_matches_dense(mesh8):
    dense = _make(dp=2, sp=1)
    sp = _make(dp=2, sp=4)
    for m in (dense, sp):
        m.compile_iter_fns(BSP_Exchanger(m.config))
        m.data.shuffle_data(0)
        m.begin_val()
    recs = []
    from theanompi_tpu.parallel import steps
    for m in (dense, sp):
        batch = m.data.next_val_batch(0)
        dev = steps.put_batch(m.mesh, batch, m.batch_spec())
        cost, err, err5 = m.val_fn(m._val_params_boxed, m._val_bn_boxed, dev)
        recs.append((float(np.mean(np.asarray(cost))),
                     float(np.mean(np.asarray(err)))))
    (cd, ed), (cs, es) = recs
    assert cd == pytest.approx(cs, abs=1e-4)
    assert ed == pytest.approx(es, abs=1e-6)


def test_sp_with_compressed_wire(mesh8):
    """EF compression under sp: params (and so grads, after the automatic
    transpose-psum) are replicated over 'seq', so the EF state stays
    replicated too — the default spec path must handle a 'seq'-axis mesh."""
    model = _make(dp=2, sp=4, exch_strategy="onebit")
    costs = _train_steps(model, BSP_Exchanger(model.config), 6)
    assert np.isfinite(costs).all()
    assert np.mean(costs[-3:]) < np.mean(costs[:3])
    ef = model.step_state["extra"]["strat"]
    assert ef.sharding.spec == (WORKER_AXIS,), ef.sharding.spec


def test_attn_impl_plumbing(mesh8):
    """attn_impl threads from config to every attention layer; 'flash' is
    TPU-only so CPU tests check the wiring, not the kernel."""
    model = _make(dp=2, sp=1)
    assert all(b.attn.attn_impl == "reference" for b in model.blocks)
    mesh = worker_mesh(2)
    cfg = {**LM_CFG, "mesh": mesh, "size": 2, "rank": 0,
           "attn_impl": "flash", "seq_len": 128}
    m2 = TransformerLM(cfg)
    assert all(b.attn.attn_impl == "flash" for b in m2.blocks)
    # flash needs 128-aligned sequence blocks — rejected at build time
    with pytest.raises(AssertionError, match="128"):
        TransformerLM({**cfg, "seq_len": 96})
    with pytest.raises(AssertionError):
        from theanompi_tpu.models import layers as L
        L.MultiHeadAttention(32, 4, attn_impl="nope")


def test_sp_with_async_rule_smoke(mesh8):
    model = _make(dp=2, sp=4, sync_freq=2)
    exch = get_exchanger("easgd", model.config)
    costs = _train_steps(model, exch, 4)
    exch.exchange(None, exch.exchange_freq)
    assert np.isfinite(costs).all()
    model.begin_val()
    model.val_iter(0, None)
    model.end_val()


def test_sp_composes_with_steps_per_call(mesh8):
    """round-4 (verdict #4): the multi-step dispatch stacks sequence-
    parallel batches P(None, workers, seq) and must trace the same params
    as single-step dispatch on the same sp layout."""
    one = _make(dp=2, sp=4)
    c1 = _train_steps(one, BSP_Exchanger(one.config), 4)
    spc = _make(dp=2, sp=4, steps_per_call=2)
    spc.compile_iter_fns(BSP_Exchanger(spc.config))
    spc.data.shuffle_data(0)
    for count in (1, 3):              # each call covers steps {c-1, c}
        spc.train_iter(count, None)
    from theanompi_tpu.parallel import steps
    p1 = steps.unbox(jax.device_get(steps.tree_to_host(
        one.step_state["params"])))
    p2 = steps.unbox(jax.device_get(steps.tree_to_host(
        spc.step_state["params"])))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7), p1, p2)


def test_sp_composes_with_tp_3d_mesh(mesh8):
    """round-4: dp=2 × tp=2 × sp=2 — head-sharded ring attention,
    vocab-parallel CE + seq-mean loss — must match the dense model (same
    seed, same data) up to fp32 summation order."""
    from theanompi_tpu.parallel.mesh import MODEL_AXIS
    dense = TransformerLM({**LM_CFG, "mesh": worker_mesh(2), "size": 2,
                           "rank": 0})
    m3 = TransformerLM({**LM_CFG, "mesh": worker_mesh(2, tp=2, sp=2),
                        "size": 2, "rank": 0, "tp": 2, "sp": 2})
    assert dict(m3.mesh.shape) == {WORKER_AXIS: 2, MODEL_AXIS: 2,
                                   SEQ_AXIS: 2}
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), dense.params, m3.params)
    c_dense = _train_steps(dense, BSP_Exchanger(dense.config), 5)
    c_3d = _train_steps(m3, BSP_Exchanger(m3.config), 5)
    np.testing.assert_allclose(c_3d, c_dense, rtol=3e-4, atol=3e-5)
    from theanompi_tpu.parallel import steps
    pd = steps.unbox(jax.device_get(steps.tree_to_host(
        dense.step_state["params"])))
    p3 = steps.unbox(jax.device_get(steps.tree_to_host(
        m3.step_state["params"])))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-4), pd, p3)
    # val path composes too (vocab-parallel metrics + seq mean)
    m3.begin_val()
    m3.val_iter(0)
    m3.end_val()


def test_sp_composes_with_pp(mesh8):
    """round-4: dp=2 × pp=2 × sp=2 — pipeline stages of ring-attention
    blocks over sequence-sharded microbatches — matches the dense model."""
    from theanompi_tpu.parallel.mesh import PIPE_AXIS
    dense = TransformerLM({**LM_CFG, "mesh": worker_mesh(2), "size": 2,
                           "rank": 0})
    m3 = TransformerLM({**LM_CFG, "mesh": worker_mesh(2, pp=2, sp=2),
                        "size": 2, "rank": 0, "pp": 2, "sp": 2,
                        "pp_microbatches": 2})
    assert dict(m3.mesh.shape) == {WORKER_AXIS: 2, PIPE_AXIS: 2,
                                   SEQ_AXIS: 2}
    c_dense = _train_steps(dense, BSP_Exchanger(dense.config), 4)
    c_3d = _train_steps(m3, BSP_Exchanger(m3.config), 4)
    np.testing.assert_allclose(c_3d, c_dense, rtol=3e-4, atol=3e-5)
    m3.begin_val()
    m3.val_iter(0)
    m3.end_val()


def test_moe_sp_pp_trains(mesh8):
    """MoE under sp×pp (round-4): the homogeneous all-MoE pipeline with
    sequence-sharded microbatches trains finite/decreasing and validates
    (the microbatch aux re-anchors its seq invariance after the pipeline
    scan)."""
    from theanompi_tpu.models.transformer_lm import MoETransformerLM
    m = MoETransformerLM({**LM_CFG, "mesh": worker_mesh(2, pp=2, sp=2),
                          "size": 2, "rank": 0, "pp": 2, "sp": 2,
                          "pp_microbatches": 2, "moe_every": 1,
                          "moe_experts": 4})
    costs = _train_steps(m, BSP_Exchanger(m.config), 4)
    assert np.isfinite(costs).all()
    assert np.mean(costs[-2:]) < np.mean(costs[:2])
    m.begin_val()
    m.val_iter(0)
    m.end_val()

# excluded from the 870s-budgeted tier-1 gate; see pytest.ini (slow marker)
import pytest as _pytest
pytestmark = _pytest.mark.slow
