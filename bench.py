#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line for the driver.

Measures steady-state training throughput (images/sec/chip) of the flagship
AlexNet BSP configuration on the available hardware — the reference's
headline metric (time per 5120 images, SURVEY.md §6) recast per-chip as
``BASELINE.json`` specifies.

The reference's published numbers are not retrievable this session
(``BASELINE.md``): ``vs_baseline`` is computed against an ESTIMATED 1×K80
AlexNet figure from the Theano-MPI era (~128 images/sec for batch-128
train+comm on one worker — the order of magnitude the arXiv:1605.08325 setup
reports qualitatively).  Replace ``K80_ALEXNET_IPS`` if real numbers surface.
"""

import json
import os
import sys
import time

import numpy as np

K80_ALEXNET_IPS = 128.0   # estimated reference single-K80 AlexNet throughput


def main() -> int:
    model_name = os.environ.get("BENCH_MODEL", "alexnet")
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))

    import jax
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger
    from theanompi_tpu.parallel.mesh import WORKER_AXIS, worker_mesh
    from theanompi_tpu.parallel import steps

    mesh = worker_mesh()
    n_chips = mesh.shape[WORKER_AXIS]
    config = {"mesh": mesh, "size": n_chips, "rank": 0, "verbose": False}

    if model_name == "alexnet":
        from theanompi_tpu.models.alex_net import AlexNet
        config["synthetic_batches"] = 4
        model = AlexNet(config)
    else:
        from theanompi_tpu.models.cifar10 import Cifar10_model
        config["synthetic_train"] = 4096
        model = Cifar10_model(config)

    model.compile_iter_fns(BSP_Exchanger(config))
    batch = model.data.next_train_batch(0)
    dev_batch = steps.put_batch(mesh, batch)
    n_images = int(batch["y"].shape[0])

    import jax.numpy as jnp
    lr = jnp.float32(model.current_lr)
    rng = jax.random.key(0)

    def step(i):
        nonlocal dev_batch
        model.step_state, cost, err = model.train_fn(
            model.step_state, dev_batch, lr, rng, jnp.int32(i))
        return cost

    for i in range(warmup):
        cost = step(i)
    jax.block_until_ready(cost)

    t0 = time.time()
    for i in range(iters):
        cost = step(warmup + i)
    jax.block_until_ready(cost)
    dt = time.time() - t0

    ips = n_images * iters / dt
    ips_chip = ips / n_chips
    out = {
        "metric": f"images_per_sec_per_chip ({model_name} batch "
                  f"{model.batch_size} BSP, {n_chips} chip(s), "
                  f"{jax.devices()[0].platform})",
        "value": round(ips_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_chip / K80_ALEXNET_IPS, 3),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
