#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line for the driver.

Measures steady-state training throughput (images/sec/chip) of the flagship
AlexNet BSP configuration on the available hardware — the reference's
headline metric (time per 5120 images, SURVEY.md §6) recast per-chip as
``BASELINE.json`` specifies.

Env knobs: ``BENCH_MODEL`` (alexnet|googlenet|vgg16|resnet50|cifar10),
``BENCH_RULE`` (bsp|easgd|asgd|gosgd — the BASELINE.json staged configs pair
VGG-16 with EASGD and ResNet-50 with GoSGD), ``BENCH_ITERS``,
``BENCH_WARMUP``, ``BENCH_BATCH`` (per-chip batch override),
``BENCH_STRATEGY`` (exchange strategy string), ``BENCH_PRNG``
(rbg|threefry2x32 — default rbg: the TPU hardware RNG, ~10% faster on
AlexNet's dropout; dropout statistics are unaffected; the chosen impl is
recorded in the metric string).

The reference's published numbers are not retrievable this session
(``BASELINE.md``): ``vs_baseline`` is computed against an ESTIMATED 1×K80
AlexNet figure from the Theano-MPI era (~128 images/sec for batch-128
train+comm on one worker — the order of magnitude the arXiv:1605.08325 setup
reports qualitatively).  Replace ``K80_ALEXNET_IPS`` if real numbers surface.
"""

import json
import os
import sys
import time

from theanompi_tpu.models.registry import MODELS  # noqa: E402

K80_ALEXNET_IPS = 128.0   # estimated reference single-K80 AlexNet throughput



def _peak_flops(device) -> float:
    """Best-effort bf16 peak FLOP/s by device kind (for the BENCH_MFU=1
    column); 0 when unknown (CPU sim)."""
    kind = getattr(device, "device_kind", "").lower()
    table = (("v5 lite", 197e12), ("v5litepod", 197e12), ("v6 lite", 918e12),
             ("v6e", 918e12), ("v5p", 459e12), ("v5", 459e12),
             ("v4", 275e12), ("v3", 123e12), ("v2", 45e12))
    for key, peak in table:
        if key in kind:
            return peak
    return 0.0

def main() -> int:
    model_name = os.environ.get("BENCH_MODEL", "alexnet")
    if model_name not in MODELS:
        print(f"unknown BENCH_MODEL {model_name!r}; have {sorted(MODELS)}",
              file=sys.stderr)
        return 2
    iters = max(1, int(os.environ.get("BENCH_ITERS", "20")))
    warmup = max(1, int(os.environ.get("BENCH_WARMUP", "5")))

    import jax
    from theanompi_tpu.base import canonical_prng_impl
    prng = canonical_prng_impl(os.environ.get("BENCH_PRNG", "rbg"))
    if prng:
        jax.config.update("jax_default_prng_impl", prng)

    from theanompi_tpu.parallel.exchanger import get_exchanger
    from theanompi_tpu.parallel.mesh import WORKER_AXIS, worker_mesh
    from theanompi_tpu.parallel import steps
    import importlib

    rule = os.environ.get("BENCH_RULE", "bsp")
    mesh = worker_mesh()
    n_chips = mesh.shape[WORKER_AXIS]
    modelfile, modelclass, extra = MODELS[model_name]
    config = {"mesh": mesh, "size": n_chips, "rank": 0, "verbose": False,
              **extra}
    if os.environ.get("BENCH_BATCH"):
        config["batch_size"] = int(os.environ["BENCH_BATCH"])
    if os.environ.get("BENCH_SYNTH_BATCHES"):
        # the CNN zoo's synthetic data keeps 4 batches by default; spc>4
        # multi-step dispatch needs at least spc distinct batches or
        # compile_iter_fns rejects it (every epoch would train zero steps)
        config["synthetic_batches"] = int(os.environ["BENCH_SYNTH_BATCHES"])
    if os.environ.get("BENCH_CFG"):
        # arbitrary config overrides as JSON (transformer dims etc.)
        config.update(json.loads(os.environ["BENCH_CFG"]))
    if os.environ.get("BENCH_STRATEGY"):
        config["exch_strategy"] = os.environ["BENCH_STRATEGY"]
    if os.environ.get("BENCH_SPC"):
        # multi-step dispatch (BASELINE.md round-3 analysis) — opt-in:
        # measured faster on TPU where host dispatch dominates, but the CPU
        # sim shows the opposite, so the default stays 1 until the TPU
        # numbers justify flipping it (scripts/perf_matrix.sh probes it)
        config["steps_per_call"] = int(os.environ["BENCH_SPC"])
    if os.environ.get("BENCH_BN_DTYPE"):
        config["bn_norm_dtype"] = os.environ["BENCH_BN_DTYPE"]

    import jax.numpy as jnp
    want_mfu = bool(os.environ.get("BENCH_MFU"))

    def measure(cfg):
        """Build + warm up + time one configuration; XLA compilation happens
        at the first warmup call, so any lowering failure lands here."""
        model = getattr(importlib.import_module(modelfile), modelclass)(cfg)
        exchanger = get_exchanger(rule, cfg)
        model.compile_iter_fns(exchanger)
        spc = int(cfg.get("steps_per_call", 1))
        if spc > 1:
            batches = [model.data.next_train_batch(j) for j in range(spc)]
            dev_batch = steps.put_batch_stack(mesh, batches)
            n_images = int(batches[0]["y"].shape[0]) * spc
        else:
            batch = model.data.next_train_batch(0)
            dev_batch = steps.put_batch(mesh, batch)
            n_images = int(batch["y"].shape[0])
        lr = jnp.float32(model.current_lr)
        rng = jax.random.key(0)

        compiled = None
        mfu_this = want_mfu and spc == 1
        if want_mfu and not mfu_this:
            # XLA's cost_analysis does not reliably scale the scan body by
            # its trip count — an spc>1 MFU would misread; the spc=1 row of
            # the same config carries the MFU
            print("mfu suppressed for steps_per_call > 1", file=sys.stderr)
        if mfu_this:
            # AOT-compile once and reuse the SAME executable for the timed
            # loop and the flop count (a separate lower().compile() after
            # the run would pay a second full XLA compile)
            compiled = model.train_fn.lower(
                model.step_state, dev_batch, lr, rng, jnp.int32(0)).compile()
            train_fn = compiled
        else:
            train_fn = model.train_fn

        def step(i):
            model.step_state, cost, err = train_fn(
                model.step_state, dev_batch, lr, rng, jnp.int32(i))
            exchanger.exchange(None, i)  # rule cadence (no-op for BSP grads)

        def drain():
            # block on the state, not the cost: the last exchange collective
            # (non-BSP rules) reassigns step_state and would otherwise still
            # be in flight when the clock stops
            jax.block_until_ready(model.step_state["params"])

        for i in range(warmup):
            step(i)
        drain()
        t0 = time.time()
        for i in range(iters):
            step(warmup + i)
        drain()
        return model, spc, n_images, time.time() - t0, compiled

    retry = False
    try:
        model, spc, n_images, dt, compiled = measure(config)
    except Exception as e:
        if int(config.get("steps_per_call", 1)) <= 1:
            raise
        print(f"steps_per_call={config['steps_per_call']} failed "
              f"({e!r}); falling back to 1", file=sys.stderr)
        retry = True
    if retry:
        # retry OUTSIDE the except block: the failed attempt's traceback
        # would otherwise keep its device buffers rooted while the fallback
        # allocates a second full model
        config["steps_per_call"] = 1
        model, spc, n_images, dt, compiled = measure(config)

    ips = n_images * iters / dt
    ips_chip = ips / n_chips

    mfu = None
    if compiled is not None:
        # XLA's flop count for the (per-device, SPMD-partitioned) module vs
        # one chip's bf16 peak → per-chip MFU
        peak = _peak_flops(jax.devices()[0])
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            flops = float(ca.get("flops", 0.0))
            if flops > 0 and peak:
                mfu = round(flops / (dt / iters) / peak, 4)
        except Exception as e:
            print(f"mfu unavailable: {e}", file=sys.stderr)

    # a sequence model's "image" is a sequence — label it honestly, and
    # don't divide sequences/sec by an AlexNet images/sec estimate
    kind = extra.get("sample_kind", "images")
    base_note = ("vs_baseline is vs ESTIMATED-K80 "
                 f"{K80_ALEXNET_IPS:.0f} img/s, not a measured reference"
                 if kind == "images" else
                 "vs_baseline n/a for sequence models")
    out = {
        "metric": f"{kind}_per_sec_per_chip ({model_name} batch "
                  f"{model.batch_size} {rule.upper()}, {n_chips} chip(s), "
                  f"{jax.devices()[0].platform}, prng={prng or 'default'}"
                  f"{', spc=' + str(spc) if spc > 1 else ''}; {base_note})",
        "value": round(ips_chip, 2),
        "unit": f"{kind}/sec/chip",
        "vs_baseline": round(ips_chip / K80_ALEXNET_IPS, 3)
        if kind == "images" else None,
    }
    if mfu is not None:
        out["mfu"] = mfu
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
