#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line for the driver.

Measures steady-state training throughput (images/sec/chip) of the flagship
AlexNet BSP configuration on the available hardware — the reference's
headline metric (time per 5120 images, SURVEY.md §6) recast per-chip as
``BASELINE.json`` specifies.  A bare invocation (no BENCH_* env — the
driver's round-end run) measures the flagship at its best honest config,
steps_per_call=4 multi-step dispatch (see ``_apply_flagship_defaults``);
the metric string records the spc so the number is never mislabeled.

Env knobs — measurement: ``BENCH_MODEL``
(alexnet|googlenet|vgg16|resnet50|cifar10|transformer_lm|moe_lm),
``BENCH_RULE`` (bsp|easgd|asgd|gosgd — the BASELINE.json staged configs pair
VGG-16 with EASGD and ResNet-50 with GoSGD), ``BENCH_ITERS``,
``BENCH_WARMUP``, ``BENCH_BATCH`` (per-chip batch override),
``BENCH_STRATEGY`` (exchange strategy string), ``BENCH_PRNG``
(rbg|threefry2x32 — default rbg: the TPU hardware RNG, ~10% faster on
AlexNet's dropout; dropout statistics are unaffected; the chosen impl is
recorded in the metric string), ``BENCH_CFG`` (JSON config overrides —
transformer dims, tp/pp/sp), ``BENCH_SPC`` (steps_per_call) +
``BENCH_SYNTH_BATCHES``, ``BENCH_BN_DTYPE`` (bn_norm_dtype lever),
``BENCH_MFU`` (=1 adds the MFU column; ``BENCH_SPC_MFU=0`` disables the
spc>1 single-step-flops derivation), ``BENCH_BUCKET_BYTES`` (bucketed
overlap-scheduled wire, ``parallel/buckets.py``: splits every exchange
collective into ~N-byte async start/done buckets; the row JSON then
carries ``bucket_bytes`` + ``n_buckets`` — vocabulary pinned as
``devprof.BUCKET_ROW_COLUMNS`` — and the ``-bucket<sz>`` label suffix
keeps bucketed rows from serving as last_good for monolithic ones),
``BENCH_USHARD`` (=1 enables leaf-wise update-plane sharding,
``parallel/update_sharding.py``; rows carry the
``devprof.USHARD_ROW_COLUMNS`` memory columns and the ``-ushard`` label
token; ``BENCH_USHARD_REPORT=1`` adds the columns to control rows),
``BENCH_REAL_DATA`` (=1 drives the
whole disk→augment→device pipeline; + ``BENCH_DATA_DIR``,
``BENCH_WIRE_U8``), ``BENCH_WINLOAD`` (=1, with BENCH_SPC>1: para_load
window mode — the producer stacks+stages whole spc windows off the hot
path and the timed loop dequeues mesh-resident windows),
``BENCH_TRACE`` (=1 captures a ``jax.profiler`` window of
``BENCH_TRACE_ITERS`` extra dispatches AFTER the timed loop — the
measurement itself is never perturbed — and folds the
``utils/devprof`` device-time attribution into the row:
``overlap_ratio`` / ``exposed_comm_secs`` / ``device_compute_secs`` /
``device_comm_secs`` plus ``device_mfu``, the trace-derived cross-check
of the ``cost_analysis`` MFU column; ``BENCH_TRACE_DIR`` keeps the raw
capture for Perfetto).

Env knobs — wedge-proof wrapper: ``BENCH_TIMEOUT`` (hard kill, default
1500 s), ``BENCH_PROBE_TIMEOUT`` (default 90 s), ``BENCH_PROBE_RETRIES``
(recovery re-probes, default 3, exponential backoff + jitter from
``BENCH_RECOVERY_WAIT``),
``BENCH_SKIP_PROBE`` (matrix rows probe once per pass),
``BENCH_FORCE_CPU`` / ``BENCH_ALLOW_CPU`` (explicit CPU intent / fallback
acceptance — otherwise CPU rows are refused), ``BENCH_COMPILE_CACHE``
(persistent XLA compile cache dir, default /tmp/jax_bench_cache — ALSO the
AOT executable store: compile_iter_fns serializes/deserializes whole
executables there via utils/compile_cache, so a prewarmed or re-run row
deserializes in seconds; every row JSON carries ``compile_secs`` +
``cache: hit|miss|off`` (+ ``aot_donate``: on non-TPU platforms the store
measures the donation-free twin of the train program — see
``compile_cache.donated_load_safe``); ``BENCH_EXEC_CACHE=0`` disables just
the executable store, or names a different dir for it).

Every row JSON also folds in a telemetry summary (utils/telemetry, run
in-memory for the row): ``p50_step_secs``/``p95_step_secs`` (per-iteration
wall inside the timed loop — tail evidence the mean hides),
``peak_hbm_bytes`` (device ``memory_stats()`` after the run), and
``min_queue_depth`` (streaming rows: the lowest prefetch queue depth the
consumer saw) — so ``scripts/merge_matrix.py`` artifacts can be ranked on
tails, not just means.

The reference's published numbers are not retrievable this session
(``BASELINE.md``): ``vs_baseline`` is computed against an ESTIMATED 1×K80
AlexNet figure from the Theano-MPI era (~128 images/sec for batch-128
train+comm on one worker — the order of magnitude the arXiv:1605.08325 setup
reports qualitatively).  Replace ``K80_ALEXNET_IPS`` if real numbers surface.
"""

import glob
import json
import os
import subprocess
import sys
import time

# Estimated reference single-K80 AlexNet throughput.  NOT a bare guess:
# derived from the paper's time-per-5120-images shape (~40 s single
# worker => 128 img/s) and cross-checked by FLOP arithmetic against
# 2016-era cuDNN/K80 capability, with a ~90-250 img/s sensitivity band —
# full derivation in BASELINE.md "Derivation of the 128 img/s K80
# anchor".  vs_baseline cells inherit that ~2x band.
K80_ALEXNET_IPS = 128.0

# ---------------------------------------------------------------------------
# Wedge-proof wrapper (round 4).  The axon TPU tunnel has wedged mid-round
# twice; after a wedge every jax.devices() call HANGS in every fresh process,
# so the measurement must run behind a killable subprocess with a timeout and
# the driver must receive structured JSON either way — never a raw traceback
# (round-3 verdict, weak #2).  ``python bench.py`` is the wrapper; it probes
# the backend, attempts the documented recovery once (clear a stale libtpu
# lockfile, wait, re-probe), then runs the real measurement as a subprocess
# of itself with BENCH_INNER=1.  On any failure it emits
# ``{"error": ..., "last_good": <newest matching perf_matrix row>}``.
# ---------------------------------------------------------------------------

PROBE_SRC = "import jax; print(jax.devices()[0].platform)"
# the JAX_PLATFORMS=cpu env var is hijacked by the axon plugin in this image
# (tests/conftest.py NOTE); the programmatic config update is the reliable
# way to force CPU for both the probe and the inner measurement
PROBE_SRC_CPU = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
                 "print(jax.devices()[0].platform)")


def _force_cpu() -> bool:
    """Explicit don't-even-try-TPU intent (dev/test workflows)."""
    return (os.environ.get("BENCH_FORCE_CPU") == "1"
            or os.environ.get("JAX_PLATFORMS", "") == "cpu")


def _probe(timeout_s: float, cpu: bool = False) -> str | None:
    """Return the backend platform ('tpu'/'cpu'/...) or None if the probe
    hung or crashed — run in a subprocess so a wedged tunnel can be killed."""
    try:
        src = PROBE_SRC_CPU if cpu else PROBE_SRC
        r = subprocess.run([sys.executable, "-c", src],
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        sys.stderr.write(r.stderr[-2000:])
        return None
    lines = [ln.strip() for ln in r.stdout.splitlines() if ln.strip()]
    return lines[-1] if lines else None


def _clear_stale_locks() -> None:
    """The local half of the documented tunnel-recovery recipe (memory:
    tpu-tunnel-wedge): nothing local holds the chip, so recovery is limited
    to clearing a stale libtpu lockfile and letting the tunnel settle."""
    for lock in glob.glob("/tmp/libtpu_lockfile*"):
        try:
            os.remove(lock)
            print(f"bench: removed stale {lock}", file=sys.stderr)
        except OSError:
            pass


def _recovery_waits() -> list:
    """Bounded exponential backoff schedule for the recovery re-probes:
    ``BENCH_PROBE_RETRIES`` attempts (default 3), base
    ``BENCH_RECOVERY_WAIT`` seconds (default 15) doubling per attempt,
    capped at 120 s, with ±25% jitter so fleet-mates retrying the same
    wedged tunnel don't re-probe in lockstep.  The old single fixed 45 s
    re-probe lost BENCH_r05 to one wedge that settled just after it."""
    import random
    retries = max(0, int(os.environ.get("BENCH_PROBE_RETRIES", "3")))
    base = float(os.environ.get("BENCH_RECOVERY_WAIT", "15"))
    return [min(base * (2 ** i), 120.0) * (0.75 + 0.5 * random.random())
            for i in range(retries)]


def _probe_with_recovery(timeout_s: float):
    """Probe the backend; on failure retry per ``_recovery_waits`` with the
    stale-lock clear before each attempt.  Returns the platform or None."""
    platform = _probe(timeout_s)
    if platform is not None:
        return platform
    waits = _recovery_waits()
    for i, wait in enumerate(waits):
        _clear_stale_locks()
        print(f"bench: backend probe failed; recovery re-probe "
              f"{i + 1}/{len(waits)} in {wait:.0f}s", file=sys.stderr)
        time.sleep(wait)
        platform = _probe(timeout_s)
        if platform is not None:
            return platform
    return None


# the matrix labels always carry the batch; when BENCH_BATCH is unset the
# run uses the model's class default — keep the two in sync so last_good
# can't hand a b64 number to a default-b32 run
_DEFAULT_BATCH = {"alexnet": 128, "googlenet": 32, "vgg16": 32,
                  "resnet50": 32, "cifar10": 128, "transformer_lm": 16,
                  "moe_lm": 16}


def _cfg_matches(cfg: str) -> bool:
    """True when a matrix row label describes the SAME configuration this
    invocation was asked to measure — matching the matrix scripts' label
    conventions (model[-bN][-rule][-strategy][-spcK][-realdata][-...]).
    Tokenized on '-' (substring checks would make 'asgd' match 'easgd');
    a BSP run must not inherit an EASGD number and vice versa."""
    model = os.environ.get("BENCH_MODEL", "alexnet")
    if not cfg.startswith(model + "-"):
        return False
    parts = set(cfg[len(model) + 1:].split("-"))
    batch = os.environ.get("BENCH_BATCH") or _DEFAULT_BATCH.get(model)
    if batch is not None and f"b{batch}" not in parts:
        return False
    rule = os.environ.get("BENCH_RULE", "bsp")
    for r in ("easgd", "asgd", "gosgd"):
        if (r in parts) != (rule == r):
            return False
    strat = os.environ.get("BENCH_STRATEGY", "")
    for s in ("topk", "onebit", "asa16", "asa32", "ring", "copper",
              "copper16", "nccl16", "bf16", "powersgd", "powersgd2",
              "powersgd4"):
        if (s in parts) != (strat == s):
            return False
    spc = os.environ.get("BENCH_SPC", "")
    want_spc = f"spc{spc}" if spc and spc != "1" else None
    has_spc = any(p.startswith("spc") for p in parts)
    if (want_spc is not None) != has_spc:
        return False
    if want_spc is not None and want_spc not in parts:
        return False
    if ("realdata" in parts) != (os.environ.get("BENCH_REAL_DATA") == "1"):
        return False
    # winload rows stream through the para_load window producer (staged
    # [spc, ...] windows dequeued per dispatch) — a different pipeline
    # from the reused staged stack the plain spc rows measure
    if ("winload" in parts) != (os.environ.get("BENCH_WINLOAD") == "1"):
        return False
    # 'lc' rows compile client-side (PALLAS_AXON_REMOTE_COMPILE=0) — a
    # different compile venue the r5 matrix treats as an A/B variable, so
    # they must not serve as fallback for the standard remote-compile
    # config (or vice versa)
    if ("lc" in parts) != (os.environ.get("PALLAS_AXON_REMOTE_COMPILE")
                           == "0"):
        return False
    if ("bnbf16" in parts) != bool(os.environ.get("BENCH_BN_DTYPE")):
        return False
    if ("u8w" in parts) != (os.environ.get("BENCH_WIRE_U8") == "1"):
        return False
    # bucketed-wire rows (BENCH_BUCKET_BYTES, label token bucket<sz> per
    # _bucket_label): a different collective schedule — never an honest
    # fallback for the monolithic control row or vice versa
    bb = os.environ.get("BENCH_BUCKET_BYTES", "")
    want_bucket = f"bucket{_bucket_label(int(bb))}" if bb and bb != "0" \
        else None
    has_bucket = any(p.startswith("bucket") for p in parts)
    if (want_bucket is not None) != has_bucket:
        return False
    if want_bucket is not None and want_bucket not in parts:
        return False
    # pipeline rows (pp / pp_interleave in BENCH_CFG; label tokens ppN /
    # vN): a pipelined program is never an honest fallback for the dense
    # row, and an interleaved v=2 schedule is not a fill/drain one — the
    # schedules run different tick counts on different meshes
    import re as _re
    try:
        bcfg = json.loads(os.environ.get("BENCH_CFG") or "{}")
    except ValueError:
        bcfg = {}
    pp = int(bcfg.get("pp", 1) or 1)
    want_pp = f"pp{pp}" if pp > 1 else None
    has_pp = any(_re.fullmatch(r"pp\d+", p) for p in parts)
    if (want_pp is not None) != has_pp:
        return False
    if want_pp is not None and want_pp not in parts:
        return False
    v = int(bcfg.get("pp_interleave", 1) or 1)
    want_v = f"v{v}" if v > 1 else None
    has_v = any(_re.fullmatch(r"v\d+", p) for p in parts)
    if (want_v is not None) != has_v:
        return False
    if want_v is not None and want_v not in parts:
        return False
    # explicit-worker-count rows (n_workers in BENCH_CFG; label token
    # nN): a 2-worker mesh and a 4-worker mesh run different programs —
    # neither is an honest fallback for the other (or for the
    # full-device-count default rows)
    nw = int(bcfg.get("n_workers", 0) or 0)
    want_n = f"n{nw}" if nw else None
    has_n = any(_re.fullmatch(r"n\d+", p) for p in parts)
    if (want_n is not None) != has_n:
        return False
    if want_n is not None and want_n not in parts:
        return False
    # update-sharding rows (BENCH_USHARD=1, label token 'ushard'): the
    # sharded update plane runs a different program (chunked opt state +
    # allgather rebuild) — never an honest fallback for the replicated
    # control row or vice versa
    if ("ushard" in parts) != (os.environ.get("BENCH_USHARD") == "1"):
        return False
    # fused-compression A/B rows (BENCH_FUSE, label token 'fuse'): fuse
    # rows run the Pallas kernel pipeline, control rows force the jnp
    # oracle path (THEANOMPI_TPU_NO_PALLAS=1) — same bit layout, different
    # programs, so neither is an honest fallback for the other
    if ("fuse" in parts) != (os.environ.get("BENCH_FUSE") == "1"):
        return False
    return True


def _bucket_label(nbytes: int) -> str:
    """Label token for one bucket size: 4194304 → '4m', 65536 → '64k',
    else the raw byte count (matrix labels stay short and unambiguous)."""
    if nbytes % (1 << 20) == 0:
        return f"{nbytes >> 20}m"
    if nbytes % (1 << 10) == 0:
        return f"{nbytes >> 10}k"
    return str(nbytes)


def _matrix_round(path: str) -> int:
    """Numeric round for perf_matrix_rN.jsonl (lexicographic sort would put
    r10 before r4)."""
    import re
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def _is_degraded_row(row: dict) -> bool:
    """Degraded-window marker check — the convention is DEFINED in
    scripts/merge_matrix.py (_is_degraded); reuse it so the fallback and
    the merge hygiene can't desynchronize.  Resolved once and cached;
    inline fallback only if the scripts package isn't importable
    (bench.py copied out of the repo)."""
    global _IS_DEGRADED
    if _IS_DEGRADED is None:
        try:
            repo = os.path.dirname(os.path.abspath(__file__))
            if repo not in sys.path:
                sys.path.insert(0, repo)
            from scripts.merge_matrix import _is_degraded
            _IS_DEGRADED = _is_degraded
        except ImportError:
            def _IS_DEGRADED(row: dict) -> bool:
                res = row.get("result")
                blob = str(row.get("note", "")) + str(
                    res.get("metric", "") if isinstance(res, dict) else "")
                return "degraded" in blob.lower()
    return _IS_DEGRADED(row)


_IS_DEGRADED = None


def _last_good() -> tuple[str, dict] | None:
    """Newest non-null perf-matrix row for the SAME configuration — the
    honest fallback number for a wedged round."""
    repo = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(repo, "perf_matrix_*.jsonl")),
                       key=_matrix_round, reverse=True):
        rows: dict = {}
        for line in open(path):
            try:
                row = json.loads(line)
            except ValueError:
                continue
            cfg, res = row.get("config", ""), row.get("result")
            if not isinstance(res, dict) or not _cfg_matches(cfg):
                continue
            if _is_degraded_row(row):
                # a reading tagged as coming from a degraded tunnel window
                # (round-4: 6,334 img/s at 40% below the healthy r3 number)
                # is NOT an honest fallback — skip it (verdict weak #7)
                continue
            rows[cfg] = res        # later duplicates win (newest re-measure)
        if rows:
            # prefer the base config (fewest suffix knobs) within the
            # newest file that has any match
            cfg = min(rows, key=len)
            return cfg, rows[cfg]
    return None


def _fail(error: str) -> int:
    out: dict = {"error": error}
    lg = _last_good()
    if lg is not None:
        cfg, res = lg
        out["last_good"] = {"config": cfg, **res}
        out["metric"] = (f"STALE last-good ({cfg}) — this round's run "
                         f"failed: {error}")
        out["value"] = res.get("value")
        out["unit"] = res.get("unit")
        out["vs_baseline"] = res.get("vs_baseline")
        # machine-readable staleness: scripts/merge_matrix.py ranks stale
        # rows below fresh measurements (a re-emitted old number must
        # never shadow a genuine re-measure in the canonical matrix)
        out["stale"] = True
    print(json.dumps(out))
    return 0 if lg is not None else 3


def _run_inner(run_timeout: float, force_cpu: bool) -> tuple[int, str, str]:
    """Run the measurement (this file, BENCH_INNER=1) in its own process
    GROUP with a hard timeout.  killpg on timeout takes down grandchildren
    too (the dataset-generation subprocess), so the captured pipes always
    reach EOF and the wrapper itself can never hang."""
    import signal
    env = dict(os.environ, BENCH_INNER="1")
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
    proc = None

    def _inner_children():
        """Pids of the inner-measurement child when a signal lands DURING
        the Popen call itself (child live, ``proc`` not yet bound; round-5
        ADVICE).  Post-exec children carry BENCH_INNER=1 in
        /proc/<pid>/environ; a child caught between fork and exec still
        shows the PARENT's environ, so when the environ filter finds
        nothing, fall back to ppid alone — inside ``_run_inner`` the
        wrapper's only live child IS the inner (probes run before/after,
        never concurrently).  /proc scan, linux-only; [] elsewhere."""
        matched, children = [], []
        try:
            for ent in os.listdir("/proc"):
                if not ent.isdigit():
                    continue
                try:
                    with open(f"/proc/{ent}/stat", "rb") as f:
                        # "pid (comm) state ppid ..." — comm may hold spaces
                        ppid = int(f.read().rsplit(b") ", 1)[1].split()[1])
                    if ppid != os.getpid():
                        continue
                    children.append(int(ent))
                    with open(f"/proc/{ent}/environ", "rb") as f:
                        if b"BENCH_INNER=1" in f.read():
                            matched.append(int(ent))
                except (OSError, ValueError, IndexError):
                    continue
        except OSError:
            pass
        return matched or children

    def _reap(signum, frame):
        # the wrapper itself being TERM'd (an outer `timeout`, a watcher
        # restart) must not orphan the detached inner session — a leaked
        # 100%-CPU inner on this 1-core box poisons every later
        # measurement (observed round 5).  Handlers are installed BEFORE
        # the Popen; if the signal lands mid-Popen (child live, ``proc``
        # still None) the /proc scan finds the BENCH_INNER child anyway.
        targets = [proc.pid] if proc is not None else _inner_children()
        for pid in targets:
            try:
                os.killpg(pid, signal.SIGKILL)
            except ProcessLookupError:
                # forked but not yet setsid'd: no own pgroup yet — kill
                # the pid directly (it shares OUR pgroup, killpg on it
                # would take the wrapper down with an uncontrolled signal)
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
            except PermissionError:
                pass
        raise SystemExit(128 + signum)

    prev = {s: signal.signal(s, _reap)
            for s in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP)}
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)
    try:
        out, err = proc.communicate(timeout=run_timeout)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        out, err = proc.communicate()
        return -9, out, err
    finally:
        for s, h in prev.items():
            signal.signal(s, h)


def wrapper_main() -> int:
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "90"))
    run_timeout = float(os.environ.get("BENCH_TIMEOUT", "1500"))
    force_cpu = _force_cpu()
    # BENCH_ALLOW_CPU=1 = FALLBACK semantics: try the TPU first, accept CPU
    # only if it never answers (BENCH_FORCE_CPU=1 skips the TPU entirely)
    allow_cpu = force_cpu or os.environ.get("BENCH_ALLOW_CPU") == "1"
    # matrix scripts probe once per pass (tpu_watch_r4.sh) — the per-row
    # probes would re-pay backend init 27 times, so rows set BENCH_SKIP_PROBE
    skip_probe = os.environ.get("BENCH_SKIP_PROBE") == "1"

    if not force_cpu and not skip_probe:
        # a wedged tunnel either hangs the probe or silently falls back
        # to CPU — both are failures for the metric of record
        platform = _probe_with_recovery(probe_timeout)
        if platform != "tpu" and allow_cpu:
            force_cpu = _probe(probe_timeout, cpu=True) == "cpu"
        if platform is None and not force_cpu:
            n = 1 + len(_recovery_waits())
            return _fail(f"backend probe hung {n} time(s) "
                         f"({probe_timeout:.0f}s each, backed-off retries) "
                         "— TPU tunnel wedged")
        if platform != "tpu" and not force_cpu:
            return _fail(f"only the {platform!r} backend answered (TPU "
                         "unavailable; set BENCH_ALLOW_CPU=1 to accept CPU)")

    rc, out, err = _run_inner(run_timeout, force_cpu)
    sys.stderr.write(err[-4000:])
    lines = [ln for ln in out.splitlines() if ln.strip()]
    if rc == -9:
        msg = f"measurement exceeded BENCH_TIMEOUT={run_timeout:.0f}s (killed)"
        if not force_cpu and _probe(probe_timeout) is None:
            msg += "; post-check probe hung — tunnel wedged"
        return _fail(msg)
    if rc != 0 or not lines:
        tail = (err.strip().splitlines() or ["no stderr"])[-1]
        msg = f"measurement rc={rc}: {tail[:500]}"
        if not force_cpu and ("UNAVAILABLE" in err or
                              _probe(probe_timeout) is None):
            msg += "; tunnel wedged"
        return _fail(msg)
    try:
        json.loads(lines[-1])
    except ValueError:
        return _fail(f"measurement emitted non-JSON tail: {lines[-1][:200]}")
    print(lines[-1])
    return 0



def _peak_flops(device) -> float:
    """Best-effort bf16 peak FLOP/s by device kind (for the BENCH_MFU=1
    column); 0 when unknown (CPU sim)."""
    kind = getattr(device, "device_kind", "").lower()
    table = (("v5 lite", 197e12), ("v5litepod", 197e12), ("v6 lite", 918e12),
             ("v6e", 918e12), ("v5p", 459e12), ("v5", 459e12),
             ("v4", 275e12), ("v3", 123e12), ("v2", 45e12))
    for key, peak in table:
        if key in kind:
            return peak
    return 0.0

def _xla_flops(compiled) -> float | None:
    """Flop count of an AOT-compiled executable via XLA's cost analysis;
    None when unavailable or nonsensical (some backends report -1)."""
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    f = float(ca.get("flops", 0.0))
    return f if f > 0 else None


def _ensure_bench_dataset(n_batches: int, batch_size: int,
                          data_dir: str = None) -> str:
    """Generate (once) a real on-disk batch-file dataset in the reference's
    .hkl layout for the BENCH_REAL_DATA row; ~25 MB per 128-image file.
    Also the shared generator for scripts/loader_bench.py."""
    d = data_dir or os.environ.get(
        "BENCH_DATA_DIR", f"/tmp/bench_imagenet_{batch_size}x{n_batches}")
    # img_mean.npy is written LAST by make_batch_dataset.py — its presence
    # marks a complete dataset; a generation killed mid-write (the wrapper's
    # killpg on timeout) leaves train_hkl/ without it, so wipe and redo
    if os.path.isdir(os.path.join(d, "train_hkl")) and \
            not os.path.exists(os.path.join(d, "img_mean.npy")):
        import shutil
        print(f"bench: {d} is half-generated — regenerating", file=sys.stderr)
        shutil.rmtree(d)
    if not os.path.isdir(os.path.join(d, "train_hkl")):
        repo = os.path.dirname(os.path.abspath(__file__))
        print(f"bench: generating {n_batches}x{batch_size}-image dataset "
              f"at {d}", file=sys.stderr)
        subprocess.run(
            [sys.executable,
             os.path.join(repo, "scripts", "make_batch_dataset.py"),
             "--synthetic", str(n_batches), "--batch-size", str(batch_size),
             "--out", d],
            check=True, stdout=sys.stderr)
    return d


def bench_row_config(environ=None):
    """The ONE BENCH_* → model-config assembly, shared by the inner
    measurement below and ``scripts/prewarm_cache.py``: the prewarmed
    programs are byte-identical to the ones the measurement will request
    from the executable cache only if both venues build the config through
    the same code path (the round-5 lesson — shapes that merely LOOK the
    same forfeit the hit).

    Returns ``(model_name, rule, config, flags)`` where ``config`` has
    every program-shaping key (batch, spc, strategy, dtype levers, BENCH_CFG
    overrides) but NOT the venue keys the caller owns (mesh/size/rank/
    verbose, dataset sizing, para_load wiring); ``flags`` carries
    ``real_data``/``winload``/``prng``.
    """
    env = os.environ if environ is None else environ
    model_name = env.get("BENCH_MODEL", "alexnet")
    rule = env.get("BENCH_RULE", "bsp")
    config: dict = {}
    if env.get("BENCH_BATCH"):
        config["batch_size"] = int(env["BENCH_BATCH"])
    if env.get("BENCH_SYNTH_BATCHES"):
        # the CNN zoo's synthetic data keeps 4 batches by default; spc>4
        # multi-step dispatch needs at least spc distinct batches or
        # compile_iter_fns rejects it (every epoch would train zero steps)
        config["synthetic_batches"] = int(env["BENCH_SYNTH_BATCHES"])
    if env.get("BENCH_CFG"):
        # arbitrary config overrides as JSON (transformer dims etc.)
        config.update(json.loads(env["BENCH_CFG"]))
    if env.get("BENCH_STRATEGY"):
        config["exch_strategy"] = env["BENCH_STRATEGY"]
    if env.get("BENCH_SPC"):
        # multi-step dispatch (BASELINE.md round-3 analysis) — opt-in:
        # measured faster on TPU where host dispatch dominates, but the CPU
        # sim shows the opposite, so the default stays 1 until the TPU
        # numbers justify flipping it (scripts/perf_matrix.sh probes it).
        # Valid for every rule: async-rule rows (easgd-spcK / gosgd-spcK)
        # fuse their exchange cadence into the scanned dispatch
        config["steps_per_call"] = int(env["BENCH_SPC"])
    if env.get("BENCH_BN_DTYPE"):
        config["bn_norm_dtype"] = env["BENCH_BN_DTYPE"]
    if env.get("BENCH_BUCKET_BYTES"):
        # bucketed overlap-scheduled collectives (parallel/buckets.py):
        # every exchange wire splits into ~N-byte async start/done pairs
        config["bucket_bytes"] = int(env["BENCH_BUCKET_BYTES"])
    if env.get("BENCH_WIRE_U8") == "1":
        # u8-wire staging: host ships uint8 crops, device casts+subtracts
        # (4× smaller host→device transfers — the real-data lever)
        config["aug_wire_u8"] = True
    if env.get("BENCH_USHARD") == "1":
        # leaf-wise update-plane sharding (parallel/update_sharding.py):
        # optimizer moments + shardable exchanger state chunked over the
        # data axis, one fused allgather rebuilds full params
        config["update_sharding"] = True
    if env.get("BENCH_FUSE") == "0":
        # fused-compression CONTROL rows: force the jnp oracle path for the
        # compression kernels (ops/_pallas_util dispatch) and drop the
        # memoized decision so it re-reads the env.  Applied HERE — the one
        # shared env→config assembly — so prewarm and the measurement agree
        # on the compile_cache `no_pallas` key stamp.
        os.environ["THEANOMPI_TPU_NO_PALLAS"] = "1"
        from theanompi_tpu.ops import _pallas_util
        _pallas_util.reset_dispatch_cache()
    flags = {"real_data": env.get("BENCH_REAL_DATA") == "1",
             "winload": env.get("BENCH_WINLOAD") == "1",
             "prng": env.get("BENCH_PRNG", "rbg")}
    return model_name, rule, config, flags


def bench_row_mesh(row_config):
    """The row's mesh, shaped by its tp/pp/sp/n_workers keys — the one
    assembly the measurement and ``scripts/prewarm_cache.py`` share (a
    hand-copied twin that drifted would silently re-key every program)."""
    from theanompi_tpu.parallel.mesh import worker_mesh
    return worker_mesh(row_config.get("n_workers"),
                       tp=int(row_config.get("tp", 1)),
                       pp=int(row_config.get("pp", 1)),
                       sp=int(row_config.get("sp", 1)))


def bench_model_config(mesh, extra, row_config, **venue):
    """The row's model config: registry extras, then the row's own keys,
    then venue keys the caller owns (dataset sizing, para_load wiring,
    compile_cache) — shared with prewarm for the same drift-proofing
    reason as :func:`bench_row_mesh`."""
    from theanompi_tpu.parallel.mesh import WORKER_AXIS
    return {"mesh": mesh, "size": mesh.shape[WORKER_AXIS], "rank": 0,
            "verbose": False, **extra, **row_config, **venue}


def main() -> int:
    from theanompi_tpu.models.registry import MODELS
    # the ONE env→config assembly (shared with scripts/prewarm_cache.py)
    model_name, rule, row_config, flags = bench_row_config()
    if model_name not in MODELS:
        print(f"unknown BENCH_MODEL {model_name!r}; have {sorted(MODELS)}",
              file=sys.stderr)
        return 2
    iters = max(1, int(os.environ.get("BENCH_ITERS", "20")))
    warmup = max(1, int(os.environ.get("BENCH_WARMUP", "5")))
    want_trace = os.environ.get("BENCH_TRACE") == "1"
    trace_iters = max(1, int(os.environ.get("BENCH_TRACE_ITERS", "3")))
    # extra dispatches the post-loop trace window consumes — the ONE value
    # both dataset-provisioning computations below and the capture loop
    # share, so they cannot drift
    trace_extra = trace_iters + 1 if want_trace else 0

    import jax
    if _force_cpu():
        # env-var CPU forcing is hijacked by the axon plugin (see wrapper);
        # apply the programmatic override before any backend init
        jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: compile time dominates each matrix row
    # over the tunnel, and a wedge mid-pass throws the warm executables away
    # with the process.  With the cache, a recovery pass re-running a row
    # whose compile already finished (even if the RUN then wedged) skips
    # straight to the measurement.  Harmless no-op if the PJRT plugin can't
    # serialize executables.
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("BENCH_COMPILE_CACHE",
                                         "/tmp/jax_bench_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:                        # unknown flag on old jax
        print(f"bench: compile cache unavailable: {e}", file=sys.stderr)
    from theanompi_tpu.base import canonical_prng_impl
    prng = canonical_prng_impl(flags["prng"])
    if prng:
        jax.config.update("jax_default_prng_impl", prng)
    # in-memory telemetry (utils/telemetry — no stream): collects the
    # prefetch queue-depth histogram and compile-cache counters during the
    # row so the row JSON can carry tail/health evidence (p95 step time,
    # peak HBM, min queue depth) — merge_matrix then ranks on tails, not
    # just the mean the `value` field is
    from theanompi_tpu.utils import telemetry
    telem = telemetry.init({"telemetry": True})

    from theanompi_tpu.parallel.exchanger import get_exchanger
    from theanompi_tpu.parallel.mesh import WORKER_AXIS
    from theanompi_tpu.parallel import steps
    import importlib

    # model-parallel bench rows (tp/pp/sp in BENCH_CFG) shape the mesh
    mesh = bench_row_mesh(row_config)
    n_chips = mesh.shape[WORKER_AXIS]
    if not _force_cpu() and jax.devices()[0].platform != "tpu":
        # a wedged tunnel can fall back to the CPU backend with only a
        # warning; a CPU-speed row recorded as measured would corrupt the
        # matrix and be skipped by the resume logic forever
        print(f"refusing to measure: platform is "
              f"{jax.devices()[0].platform!r}, not 'tpu' (set "
              "BENCH_FORCE_CPU=1 for an explicit CPU run)", file=sys.stderr)
        return 4
    modelfile, modelclass, extra = MODELS[model_name]
    config = bench_model_config(mesh, extra, row_config)
    # AOT executable store (utils/compile_cache): compile_iter_fns then
    # serializes/deserializes whole executables under a key we control —
    # a prewarmed (scripts/prewarm_cache.py) or previously-run row skips
    # the XLA compile outright; BENCH_EXEC_CACHE=0 disables, a path
    # overrides the dir (default: piggyback on the XLA cache dir)
    exec_cache = os.environ.get(
        "BENCH_EXEC_CACHE",
        os.environ.get("BENCH_COMPILE_CACHE", "/tmp/jax_bench_cache"))
    config.setdefault("compile_cache",
                      "" if exec_cache == "0" else exec_cache)
    real_data = flags["real_data"]
    winload = flags["winload"]
    spc_cfg = int(config.get("steps_per_call", 1))
    if winload:
        # window-granular staging row (ISSUE 2): para_load on, the
        # PrefetchLoader producer stacks+stages whole spc windows off the
        # hot path and the timed loop dequeues mesh-resident windows
        assert spc_cfg > 1, "BENCH_WINLOAD needs BENCH_SPC > 1"
        config["para_load"] = True
        if not real_data:
            # synthetic data: size one epoch to cover the whole timed run
            # — windows stream FRESH batches (spc each), and an exhausted
            # epoch would block the dequeue until BENCH_TIMEOUT.  Both
            # synthetic knobs: batch-file-family (ImageNet) counts
            # batches, DataBase-family (cifar10) counts images.
            need = (warmup + iters + 2 + trace_extra) * spc_cfg
            config.setdefault("synthetic_batches", need)
            config.setdefault(
                "synthetic_train",
                need * n_chips * int(config.get(
                    "batch_size", _DEFAULT_BATCH.get(model_name, 128))))
    if real_data:
        # verdict #3: drive the TPU from DISK — real batch files through the
        # native augment pass + PrefetchLoader staging to device — so the
        # recorded img/s includes the whole input pipeline, not just compute
        assert spc_cfg == 1 or winload, (
            "BENCH_REAL_DATA measures the streaming pipeline; spc>1 reuses "
            "a staged stack unless BENCH_WINLOAD=1 streams staged windows")
        # each training step consumes `size` batch FILES (one per chip,
        # imagenet.py files_per_step) — scale the dataset so one epoch
        # covers the whole timed run on any mesh size
        config["data_dir"] = _ensure_bench_dataset(
            n_batches=max(32, (warmup + iters + 4 + trace_extra)
                          * spc_cfg) * n_chips,
            batch_size=int(config.get("batch_size", 128)))
        config["para_load"] = True

    import jax.numpy as jnp
    want_mfu = bool(os.environ.get("BENCH_MFU"))

    def measure(cfg):
        """Build + warm up + time one configuration; XLA compilation happens
        at the first warmup call, so any lowering failure lands here."""
        model = getattr(importlib.import_module(modelfile), modelclass)(cfg)
        exchanger = get_exchanger(rule, cfg)
        model.compile_iter_fns(exchanger)
        spc = int(cfg.get("steps_per_call", 1))
        streaming = real_data or winload
        if streaming and spc > 1:
            # window mode (BENCH_WINLOAD): the producer assembles+stages
            # whole [spc, ...] windows in the background; every timed step
            # dequeues a FRESH mesh-resident window
            model.data.shuffle_data(int(cfg.get("seed", 42)))
            dev_batch = model.data.next_train_window(0)
            n_images = int(dev_batch["y"].shape[0]) * int(
                dev_batch["y"].shape[1])
        elif streaming:
            # PrefetchLoader producer: loads .hkl from disk, augments via the
            # native pass, stages to device; every timed step consumes a
            # FRESH batch so the whole pipeline is on the clock
            model.data.shuffle_data(int(cfg.get("seed", 42)))
            dev_batch = model.data.next_train_batch(0)
            n_images = int(dev_batch["y"].shape[0])
        elif spc > 1:
            batches = [model.data.next_train_batch(j) for j in range(spc)]
            dev_batch = steps.put_batch_stack(mesh, batches,
                                              model.batch_spec())
            n_images = int(batches[0]["y"].shape[0]) * spc
        else:
            batch = model.data.next_train_batch(0)
            dev_batch = steps.put_batch(mesh, batch, model.batch_spec())
            n_images = int(batch["y"].shape[0])
        lr = jnp.float32(model.current_lr)
        rng = jax.random.key(0)

        compiled = None
        mfu_this = want_mfu and spc == 1
        if mfu_this:
            # AOT-compile once and reuse the SAME executable for the timed
            # loop and the flop count (a separate lower().compile() after
            # the run would pay a second full XLA compile).  When the
            # executable cache already AOT-compiled the step inside
            # compile_iter_fns, THAT object (possibly a ~ms deserialize)
            # is the one to reuse — cost_analysis works on it either way.
            compiled = getattr(model, "_train_compiled", None)
            if compiled is None:
                compiled = model.train_fn.lower(
                    model.step_state, dev_batch, lr, rng,
                    jnp.int32(0)).compile()
            train_fn = compiled
        else:
            train_fn = model.train_fn

        load_wait = [0.0]

        def step(i):
            if streaming:
                t0 = time.time()
                b = model.data.next_train_window((i + 1) * spc) if spc > 1 \
                    else model.data.next_train_batch(i)
                load_wait[0] += time.time() - t0   # consumer BLOCKED on the
            else:                                  # producer = overlap gap
                b = dev_batch
            # stride the count exactly like the worker loop (1-based,
            # count += spc before the dispatch): the fused in-scan cadence
            # (easgd-spcK / gosgd-spcK rows) fires at its true rate, and
            # the spc=1 rows fire at the SAME phase — no extra step-0
            # exchange skewing the spc1-vs-spcK comparison
            c = (i + 1) * spc
            model.step_state, cost, err = train_fn(
                model.step_state, b, lr, rng, jnp.int32(c))
            exchanger.exchange(None, c)  # rule cadence (no-op when fused
            #                              in-scan or for BSP grads)

        def drain():
            # block on the state, not the cost: the last exchange collective
            # (non-BSP rules) reassigns step_state and would otherwise still
            # be in flight when the clock stops
            jax.block_until_ready(model.step_state["params"])

        for i in range(warmup):
            step(i)
        drain()
        load_wait[0] = 0.0            # only the timed window counts
        step_secs = []                # per-iteration wall inside the timed
        t0 = time.time()              # loop: host dispatch (+ dequeue wait
        for i in range(iters):        # on streaming rows) — its p95 is the
            ts = time.time()          # row's tail-latency evidence
            step(warmup + i)
            step_secs.append(time.time() - ts)
        drain()
        dt = time.time() - t0

        spc1_flops = None
        if want_mfu and not mfu_this and not streaming and \
                os.environ.get("BENCH_SPC_MFU", "1") != "0":
            # XLA's cost_analysis does not reliably scale the scan body by
            # its trip count, so the spc>1 executable can't be read
            # directly.  AFTER the timed window (no extra buffers or
            # compile perturbing the measurement), AOT-compile the SINGLE-
            # step program — a persistent-compile-cache hit when this
            # config's spc=1 row ran earlier in the matrix, as the row
            # order guarantees; on a cold cache this pays a second compile
            # and the wrapper's BENCH_TIMEOUT still bounds the row — purely
            # for its flop count, scaled by spc in the caller.
            try:
                cache = getattr(model, "compile_cache", None)
                if cache is not None and cache.enabled:
                    # route through the executable store via the ONE shared
                    # avals/label/extras composition
                    # (model_base.aot_train_program) — a guaranteed hit
                    # when this config's spc=1 row (or
                    # scripts/prewarm_cache.py) ran earlier, instead of
                    # hoping for an opaque XLA-cache hit
                    compiled1, info1 = model.aot_train_program(
                        cache, spc=1, exchanger=exchanger)
                    print(f"bench: spc1 flop-count program cache: "
                          f"{info1['cache']} "
                          f"({info1.get('compile_secs')}s)", file=sys.stderr)
                    spc1_flops = _xla_flops(compiled1)
                else:
                    single_fn = steps.build_train_step(mesh, model,
                                                       exchanger, n_steps=1)
                    dev1 = steps.put_batch(mesh, batches[0],
                                           model.batch_spec())
                    spc1_flops = _xla_flops(
                        single_fn.lower(model.step_state, dev1, lr, rng,
                                        jnp.int32(0)).compile())
            except Exception as e:
                print(f"mfu for spc>1 unavailable (single-step flop "
                      f"count failed: {e!r})", file=sys.stderr)
        # the row's load-wait evidence is frozen HERE: the trace window
        # below keeps calling step() (streaming rows dequeue more batches
        # after the producer idled through the flop-count gap), and those
        # waits must not contaminate load_wait_share, which divides by the
        # timed-loop-only dt
        timed_load_wait = load_wait[0]
        trace_profile = None
        if want_trace:
            # AFTER the timed window (nothing perturbs the measurement):
            # capture trace_iters extra dispatches and attribute the device
            # timeline — comm vs compute vs EXPOSED comm, the observability
            # ROADMAP item 1's bucketed-overlap work is gated on
            from theanompi_tpu.utils import devprof
            tdir = os.environ.get("BENCH_TRACE_DIR")
            # pipelined rows read the schedule's tick structure out of the
            # raw hop events (devprof.pipeline_schedule_report), so the
            # capture dir must outlive the context manager
            pipe_pp = int(config.get("pp", 1) or 1)
            own_tdir = None
            if pipe_pp > 1 and tdir is None:
                import tempfile
                tdir = own_tdir = tempfile.mkdtemp(prefix="bench_pipe_")
            try:
                with devprof.capture(tdir) as cap:
                    for i in range(trace_iters):
                        step(warmup + iters + i)
                    drain()
                trace_profile = cap.profile
                if trace_profile is None:
                    print("bench: BENCH_TRACE capture produced no usable "
                          "trace", file=sys.stderr)
                elif pipe_pp > 1:
                    rep = devprof.pipeline_schedule_report(
                        devprof.load_dir_events(tdir), pp=pipe_pp,
                        v=int(config.get("pp_interleave", 1) or 1),
                        m=int(config.get("pp_microbatches", 1) or 1))
                    trace_profile["pipeline_bubble_ticks"] = \
                        rep["bubble_fraction_ticks"]
                    trace_profile["pipeline_bubble_time"] = \
                        rep["bubble_fraction"]
                    trace_profile["pipeline_schedule_verified"] = \
                        rep["schedule_verified"]
            except Exception as e:
                print(f"bench: BENCH_TRACE capture failed ({e!r})",
                      file=sys.stderr)
            finally:
                if own_tdir is not None:
                    import shutil
                    shutil.rmtree(own_tdir, ignore_errors=True)
        return (model, spc, n_images, dt, compiled, timed_load_wait,
                spc1_flops, step_secs, trace_profile)

    retry = False
    try:
        model, spc, n_images, dt, compiled, load_wait, spc1_flops, \
            step_secs, trace_profile = measure(config)
    except Exception as e:
        if int(config.get("steps_per_call", 1)) <= 1:
            raise
        print(f"steps_per_call={config['steps_per_call']} failed "
              f"({e!r}); falling back to 1", file=sys.stderr)
        retry = True
    if retry:
        # retry OUTSIDE the except block: the failed attempt's traceback
        # would otherwise keep its device buffers rooted while the fallback
        # allocates a second full model
        config["steps_per_call"] = 1
        # fresh registry: the failed attempt's queue-depth/histogram
        # samples must not leak into the fallback row's telemetry fields
        # (peak_hbm_bytes stays a process-wide monotone peak — see below)
        telem = telemetry.init({"telemetry": True})
        model, spc, n_images, dt, compiled, load_wait, spc1_flops, \
            step_secs, trace_profile = measure(config)

    ips = n_images * iters / dt
    ips_chip = ips / n_chips

    mfu = None
    peak = _peak_flops(jax.devices()[0])
    if compiled is not None and peak:
        # XLA's flop count for the (per-device, SPMD-partitioned) module vs
        # one chip's bf16 peak → per-chip MFU
        try:
            flops = _xla_flops(compiled)
            if flops:
                mfu = round(flops / (dt / iters) / peak, 4)
        except Exception as e:
            print(f"mfu unavailable: {e}", file=sys.stderr)
    elif spc1_flops and peak:
        # spc>1 rows: flops of ONE step from the separately-compiled spc=1
        # program × spc steps per timed call
        mfu = round(spc1_flops * spc / (dt / iters) / peak, 4)

    # a sequence model's "image" is a sequence — label it honestly, and
    # don't divide sequences/sec by an AlexNet images/sec estimate
    kind = extra.get("sample_kind", "images")
    base_note = ("vs_baseline is vs ESTIMATED-K80 "
                 f"{K80_ALEXNET_IPS:.0f} img/s, not a measured reference"
                 if kind == "images" else
                 "vs_baseline n/a for sequence models")
    bucket_b = int(config.get("bucket_bytes", 0) or 0)
    bucket_note = f", bucket={_bucket_label(bucket_b)}" if bucket_b else ""
    ushard_note = (", ushard (sharded update plane)"
                   if config.get("update_sharding") else "")
    out = {
        "metric": f"{kind}_per_sec_per_chip ({model_name} batch "
                  f"{model.batch_size} {rule.upper()}, {n_chips} chip(s), "
                  f"{jax.devices()[0].platform}, prng={prng or 'default'}"
                  f"{', spc=' + str(spc) if spc > 1 else ''}{bucket_note}"
                  f"{ushard_note}"
                  f"{', real-data (disk->native augment->device)' if real_data else ''}"
                  f"{', winload (producer-staged spc windows)' if winload else ''}"
                  f"; {base_note})",
        "value": round(ips_chip, 2),
        "unit": f"{kind}/sec/chip",
        "vs_baseline": round(ips_chip / K80_ALEXNET_IPS, 3)
        if kind == "images" else None,
    }
    # executable-cache evidence (the round-5 verdict's ask): where the
    # train program came from and what the compile cost this row — ~0 via
    # deserialize when prewarm/a previous pass already built it
    cinfo = (getattr(model, "compile_info", None) or {}).get("train", {})
    out["cache"] = cinfo.get("cache", "off")
    out["compile_secs"] = cinfo.get("compile_secs")
    if out["cache"] not in ("off", "error"):
        # the execution-mode flag: on non-TPU platforms the store runs the
        # donation-free twin (compile_cache.donated_load_safe), a different
        # program than the pre-cache donated lazy jit — a CPU A/B against
        # older rounds must compare like with like (BENCH_EXEC_CACHE=0
        # restores the donated program)
        from theanompi_tpu.utils import compile_cache as _cc
        out["aot_donate"] = _cc.donated_load_safe(mesh)
    if mfu is not None:
        out["mfu"] = mfu
    if bucket_b:
        # the bucketed-wire columns (devprof.BUCKET_ROW_COLUMNS — the
        # schema-drift checker pins both names against bench.py): the
        # knob and the planner's resulting collectives-per-exchange, so
        # overlap_ratio movements can be read against bucket count
        out["bucket_bytes"] = bucket_b
        try:
            out["n_buckets"] = model.exchanger.n_buckets()
        except Exception as e:
            print(f"bench: n_buckets unavailable ({e!r})", file=sys.stderr)
            out["n_buckets"] = None
    if (config.get("update_sharding") or config.get("zero_opt")
            or os.environ.get("BENCH_USHARD_REPORT") == "1"):
        # the update-plane memory columns (devprof.USHARD_ROW_COLUMNS):
        # measured per-chip update-state bytes vs the replicated-equivalent
        # baseline, so the headline ~N× shrink is read off the row itself.
        # Control rows set BENCH_USHARD_REPORT=1 to carry the columns too
        # (shrink ~1.0) so the matrix join never compares against absence.
        from theanompi_tpu.utils import devprof
        try:
            out.update(devprof.update_state_report(model))
        except Exception as e:
            print(f"bench: update_state_report unavailable ({e!r})",
                  file=sys.stderr)
    strat_cfg = str(config.get("exch_strategy", "") or "")
    if strat_cfg in ("onebit", "topk") or strat_cfg.startswith("powersgd"):
        # the compression-traffic columns (devprof.COMPRESS_ROW_COLUMNS):
        # modeled HBM bytes one exchange moves through the compression
        # pipeline, unfused op graph vs fused kernel pipeline (docs/
        # design.md §24) — readable off CPU-sim rows now, joined against
        # step time when the hardware window reopens
        from theanompi_tpu.utils import devprof
        try:
            _rep = devprof.compress_traffic_report(model)
            if _rep:
                out.update(_rep)
        except Exception as e:
            print(f"bench: compress_traffic_report unavailable ({e!r})",
                  file=sys.stderr)
    if trace_profile is not None:
        # trace-derived columns (utils/devprof, BENCH_TRACE=1): device
        # compute/comm/EXPOSED-comm time over the traced window and the
        # overlap ratio — plus device_mfu, the device-timeline cross-check
        # of the host-clock cost_analysis `mfu` column above
        from theanompi_tpu.utils import devprof
        flops_per_dispatch = None
        if spc1_flops:
            flops_per_dispatch = spc1_flops * spc
        elif compiled is not None:
            try:
                flops_per_dispatch = _xla_flops(compiled)
            except Exception:
                flops_per_dispatch = None
        out.update(devprof.profile_row_fields(
            trace_profile,
            total_flops=(flops_per_dispatch * trace_iters
                         if flops_per_dispatch else None),
            peak_flops=peak or None))
        # pipelined rows (devprof.PIPELINE_ROW_COLUMNS): the hop-event
        # schedule measurement — tick-count bubble, wall-time bubble,
        # and whether the capture's hop count verified the tick structure
        for col in devprof.PIPELINE_ROW_COLUMNS:
            if col in trace_profile:
                out[col] = trace_profile[col]
    if real_data or winload:
        # overlap evidence (SURVEY §2.8 "input pipeline at AlexNet
        # speeds"): the share of the timed window the consumer spent
        # BLOCKED waiting for the loader; ~0 = the producer kept up
        out["load_wait_share"] = round(load_wait / dt, 4)
    # telemetry fold-in: tails and health, not just the mean.  p95 of the
    # per-iteration wall inside the timed loop (host dispatch + dequeue
    # wait on streaming rows — a straggling loader or a periodic stall
    # shows here while the mean hides it), device peak HBM after the run,
    # and the minimum prefetch queue depth the consumer ever saw.
    if step_secs:
        h = telemetry.Histogram()     # the ONE percentile definition
        for v in step_secs:
            h.observe(v)
        out["p50_step_secs"] = round(h.percentile(50), 5)
        out["p95_step_secs"] = round(h.percentile(95), 5)
        # the EXACT streaming extreme (tracked outside the reservoir):
        # the single worst iteration — the sample an SLO cares about,
        # which a thinned reservoir's percentile can drop
        out["max_step_secs"] = round(h.max, 5)
    try:
        # NOTE: a process-wide monotone peak — on the rare spc-fallback
        # retry it includes the failed first attempt's high-water mark
        ms = jax.local_devices()[0].memory_stats() or {}
        if "peak_bytes_in_use" in ms:
            out["peak_hbm_bytes"] = int(ms["peak_bytes_in_use"])
    except Exception:
        pass                          # CPU sim: no memory_stats
    qd = telem.hists.get("prefetch.queue_depth")
    if qd is not None and qd.count:
        out["min_queue_depth"] = qd.min
    if getattr(model, "numerics_aux", None) is not None:
        # §25 numerics columns (rows with `numerics` on): the worst-rank
        # grad norm and the cross-rank beacon spread of the LAST sampled
        # step — bench rows carry training-health evidence, not just speed
        from theanompi_tpu.utils import numerics as _numerics
        try:
            _rep = _numerics.host_report(
                jax.device_get(model.numerics_aux))
            if _rep is not None:
                out["grad_norm"] = round(float(_rep["grad_norm"]), 6)
                out["divergence"] = None if _rep["divergence"] is None \
                    else float(_rep["divergence"])
        except Exception as e:
            print(f"bench: numerics report unavailable ({e!r})",
                  file=sys.stderr)
    print(json.dumps(out))
    return 0


def _apply_flagship_defaults() -> None:
    """A bare ``python bench.py`` (the driver's round-end invocation — no
    BENCH_* env) measures the flagship at its BEST honest configuration:
    AlexNet b128 BSP with steps_per_call=4 multi-step dispatch, the
    round-3 record config (14,162 img/s/chip, perf_matrix_r3.jsonl).  The
    spc=4 lever is a framework feature (BASELINE.md round-3 analysis:
    host dispatch over the tunnel is first-order; +34% measured) and the
    metric string records it.  ANY config-shaping BENCH_* knob disables
    the default — matrix rows and hand runs keep their exact semantics;
    only the truly bare invocation gets the flagship config."""
    shaping = ("BENCH_MODEL", "BENCH_RULE", "BENCH_BATCH", "BENCH_STRATEGY",
               "BENCH_CFG", "BENCH_SPC", "BENCH_SYNTH_BATCHES",
               "BENCH_BN_DTYPE", "BENCH_REAL_DATA", "BENCH_WIRE_U8",
               "BENCH_WINLOAD", "BENCH_BUCKET_BYTES", "BENCH_USHARD",
               "BENCH_FUSE")
    if any(k in os.environ for k in shaping):
        return
    if os.environ.get("PALLAS_AXON_REMOTE_COMPILE") == "0":
        # an lc (local-compile) env lingering from a hand-run matrix row
        # is ALSO config-shaping (_cfg_matches distinguishes lc rows) and
        # the metric string doesn't record the compile venue — don't let
        # a bare run measure a mislabeled flagship
        print("bench: PALLAS_AXON_REMOTE_COMPILE=0 is set — skipping the "
              "bare-run flagship spc=4 default (compile venue is a "
              "config variable; unset it or set BENCH_* explicitly)",
              file=sys.stderr)
        return
    os.environ["BENCH_SPC"] = "4"


if __name__ == "__main__":
    _apply_flagship_defaults()
    if os.environ.get("BENCH_INNER") == "1":
        sys.exit(main())
    sys.exit(wrapper_main())
