"""Alias of :mod:`theanompi_tpu.models` so reference-style ``modelfile``
strings (``'theanompi.models.alex_net'``) resolve via importlib.

Registering the real modules in ``sys.modules`` under the alias names makes
``importlib.import_module('theanompi.models.<m>')`` return them directly
(the import system consults ``sys.modules`` before searching the package
path)."""

import importlib
import sys

_SUBMODULES = (
    "model_base", "layers", "cifar10", "alex_net", "googlenet",
    "vggnet_16", "vggnet_11_shallow", "resnet50", "gan", "wgan", "lsgan",
    "data", "data.cifar10", "data.imagenet", "data.prefetch",
)

for _m in _SUBMODULES:
    sys.modules[f"{__name__}.{_m}"] = importlib.import_module(
        f"theanompi_tpu.models.{_m}")

from theanompi_tpu.models import *          # noqa: F401,F403,E402
