"""Alias: ``python -m theanompi.worker`` ≙ the reference's per-rank worker
entry (``mpirun ... python -u -m theanompi.worker`` lines keep working)."""

from theanompi_tpu.worker import *            # noqa: F401,F403
from theanompi_tpu.worker import WORKERS, main  # noqa: F401

if __name__ == "__main__":
    from theanompi_tpu.utils import telemetry
    telemetry.install_signal_hooks()     # same contract as the real entry
    raise SystemExit(main())
