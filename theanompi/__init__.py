"""``theanompi`` — drop-in import alias for ``theanompi_tpu``.

The reference's session scripts start with ``from theanompi import BSP``
(SURVEY.md §2.6); this alias package lets those scripts run against the
TPU-native rebuild without edits.  Everything is re-exported from
:mod:`theanompi_tpu` — see that package for the real implementation.
"""

from theanompi_tpu import ASGD, BSP, EASGD, GOSGD, SyncRule, __version__

__all__ = ["BSP", "EASGD", "ASGD", "GOSGD", "SyncRule", "__version__"]
