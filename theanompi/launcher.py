"""Alias: ``python -m theanompi.launcher`` ≙ ``theanompi_tpu.launcher``."""

from theanompi_tpu.launcher import *          # noqa: F401,F403
from theanompi_tpu.launcher import main       # noqa: F401

if __name__ == "__main__":
    raise SystemExit(main())
