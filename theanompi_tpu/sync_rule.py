"""Sync-rule session API.

TPU-native rebuild of Theano-MPI's ``theanompi/sync_rule.py``
(SURVEY.md §2.6): the 3-call public API every reference session script used —

    from theanompi import BSP
    rule = BSP()
    rule.init(devices=['cuda0', 'cuda1'])   # here: device count / list
    rule.wait()

The reference's ``init`` composed an ``mpirun`` command line (MPMD for
EASGD's server+workers) and ``wait`` blocked on the spawned processes.  On
TPU a single process drives every local chip through the mesh, so by default
``wait()`` runs the training IN-PROCESS; multi-host launch command
composition lives in :mod:`theanompi_tpu.launcher`.

``devices`` accepts the reference's string form (``['cuda0', ...]`` — only
the count matters now), an int, or None for all local chips.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

from .worker import WORKERS


class SyncRule:
    rule = "bsp"

    def __init__(self, config: Optional[dict] = None):
        self.config = dict(config or {})
        self.worker = None
        self.model = None
        self.recorder = None

    def init(self, devices: Union[int, Sequence, None] = None,
             modelfile: str = "theanompi_tpu.models.cifar10",
             modelclass: str = "Cifar10_model", **kwargs) -> "SyncRule":
        """Record topology + model selection (≙ reference ``rule.init``)."""
        if devices is not None and not isinstance(devices, int):
            devices = len(list(devices))
        self.config.update(kwargs)
        self.config["n_workers"] = devices
        self.config["rule"] = self.rule
        self.modelfile, self.modelclass = modelfile, modelclass
        return self

    def wait(self):
        """Run training to completion (≙ reference ``rule.wait()`` blocking
        on the mpirun job) and return the recorder."""
        self.worker = WORKERS[self.rule](self.config)
        self.model = self.worker.build_model(self.modelfile, self.modelclass)
        self.recorder = self.worker.run(self.model)
        return self.recorder


class BSP(SyncRule):
    rule = "bsp"


def _run_async_islands(rule_obj, rule_name: str):
    """Shared async-island tail for EASGD/ASGD (``parallel.async_easgd``).
    ``center_serve=true`` additionally serves the center over TCP;
    ``center_addr='host:port'`` joins a remote center instead — the
    cross-process topology of the reference's server rank."""
    import importlib

    from .parallel.async_easgd import AsyncEASGDTrainer

    mod = importlib.import_module(rule_obj.modelfile)
    cls = getattr(mod, rule_obj.modelclass)
    cfg = dict(rule_obj.config)
    cfg.pop("mesh", None)
    rule_obj.trainer = AsyncEASGDTrainer(cls, cfg, rule=rule_name)
    rule_obj.trainer.run_for(float(cfg.get("run_seconds", 60.0)))
    return rule_obj.trainer


class EASGD(SyncRule):
    """``easgd_mode='sync'`` (default): in-mesh synchronous-cadence elastic
    averaging.  ``easgd_mode='async'``: genuinely asynchronous worker islands
    around a host-side center (``parallel.async_easgd``) — ``async_islands``
    and ``sync_freq`` control the topology/cadence, ``run_seconds`` the
    wall-clock budget; ``center_serve``/``center_addr`` take the center
    across processes (``parallel.center_server``)."""

    rule = "easgd"

    def wait(self):
        if self.config.get("easgd_mode", "sync") != "async":
            return super().wait()
        return _run_async_islands(self, "easgd")


class ASGD(SyncRule):
    """``asgd_mode='async'``: downpour worker islands — each island
    accumulates ``sync_freq`` local steps, ships the delta to the (possibly
    remote) center, and resets to the returned fresh center; asynchrony is
    ASGD's defining property in the reference (SURVEY.md §2.2)."""

    rule = "asgd"

    def wait(self):
        if self.config.get("asgd_mode", "sync") != "async":
            return super().wait()
        return _run_async_islands(self, "asgd")


class GOSGD(SyncRule):
    rule = "gosgd"
