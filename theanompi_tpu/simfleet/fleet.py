"""FleetSim: the virtual fleet — real survivability logic, simulated
everything else.

One seeded run drives N simulated workers (default 1,000) through
exchange rounds against a sharded center and a gossip mesh, while a
chaos schedule (the REAL ``utils/chaos.py`` grammar) kills, wedges, and
slows them and fault windows drop/delay/duplicate/corrupt/partition
their frames.  What is real and what is simulated:

==========================  =============================================
real (production code)      simulated (virtual stand-ins)
==========================  =============================================
MembershipController        worker processes (state structs + events)
  poll/lease folding,         heartbeats (an in-memory lease table the
  dead-ts guard, straggler    controller folds via its ``lease_source``
  demotion + cumulative       seam — same doc schema as WorkerLease)
  base, min-active floor
CenterReactor/MeshReactor   the supervisor loop (death detection,
GoSGD tables                  respawn scheduling — but through the real
  (topology.derangements      Backoff + CrashLoopBreaker)
  + embed_active)
DedupWindow (+ snapshot/    the TCP wire (SimTransport resolves each
  restore on center crash)    frame's fate from the real
Backoff, CrashLoopBreaker     fault_window_active rule)
chaos schedule grammar      the EASGD center math (push counting — the
fault_window_active           membership/dedup planes, not gradients)
==========================  =============================================

The run is a pure function of its seed: one ``random.Random`` drives
every sample, events are totally ordered, and nothing reads wall time —
so the event log is byte-identical across runs (the tier-1 determinism
gate) and any realized schedule can be exported and replayed through
the live harness (``simfleet.fidelity``).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence

import numpy as np

try:
    from ..parallel import topology
    from ..parallel.membership import (Backoff, CenterReactor,
                                       CrashLoopBreaker,
                                       MembershipController, MeshReactor)
    from ..utils import telemetry
    from ..utils.chaos import NET_FAULT_KINDS, Fault, seeded_schedule
except ImportError:        # file-path load: absolute
    from theanompi_tpu.parallel import topology
    from theanompi_tpu.parallel.membership import (Backoff, CenterReactor,
                                                   CrashLoopBreaker,
                                                   MembershipController,
                                                   MeshReactor)
    from theanompi_tpu.utils import telemetry
    from theanompi_tpu.utils.chaos import (NET_FAULT_KINDS, Fault,
                                           seeded_schedule)

from .clock import VirtualClock
from .events import EventLog, EventQueue
from .transport import SimCenter, SimTransport

#: SimCenter's slot in chaos schedules — matches
#: ``ElasticSupervisor.CENTER_ID`` so ``kill@t:0`` means the same thing
#: in a simulated and a live schedule.
CENTER_ID = 0


class SimExchanger:
    """The in-mesh exchanger stand-in the REAL :class:`MeshReactor`
    drives: ``set_active_ranks`` regenerates the GoSGD routing tables
    through the production algebra (``topology.derangements`` +
    ``topology.embed_active``).  ``exclude`` holds non-mesh slots (the
    center's id 0 lives in the worker id space but not in the mesh)."""

    fused = False          # no in-scan cadence to recompile in a sim

    def __init__(self, size: int, n_perms: int = 16, family_seed: int = 0,
                 exclude: Sequence[int] = ()):
        self.size = int(size)
        self.n_perms = int(n_perms)
        self.family_seed = int(family_seed)
        self.exclude = frozenset(int(e) for e in exclude)
        self.active: List[int] = []
        self.tables = np.arange(self.size)[None, :]
        self.regens = 0
        self.table_violations: List[str] = []
        self.set_active_ranks(range(self.size))

    def set_active_ranks(self, active) -> None:
        if active is None:
            active = range(self.size)
        act = sorted(set(int(a) for a in active) - self.exclude)
        self.active = act
        if not act:
            # end-of-run drain: every worker finished and left — a real
            # mesh never shrinks to zero (the run ends first), the sim's
            # controller keeps folding leaves past that point
            self.tables = np.arange(self.size)[None, :]
            self.regens += 1
            return
        m = len(act)
        sub = topology.derangements(m, self.n_perms,
                                    seed=0x605 + self.family_seed)
        self.tables = topology.embed_active(sub, act, self.size) \
            if len(sub) else np.arange(self.size)[None, :]
        self.regens += 1
        self._audit()

    def _audit(self) -> None:
        """Topology invariant at every regeneration: inactive ranks are
        fixed points, active ranks route among themselves and never to
        self (m>1) — pins MeshReactor + embed_active at width."""
        act = np.zeros(self.size, dtype=bool)
        act[self.active] = True
        idx = np.arange(self.size)
        if (self.tables[:, ~act] != idx[~act]).any():
            self.table_violations.append(
                f"regen{self.regens}: an inactive rank is routed")
        sub = self.tables[:, act]
        if sub.size and (not act[sub].all() or
                         (len(self.active) > 1 and
                          (sub == idx[act]).any())):
            self.table_violations.append(
                f"regen{self.regens}: active routing broken "
                f"(left the active set or self-loop)")


class SimWorker:
    """One virtual worker's mutable state (no behavior — the fleet's
    event handlers drive it)."""

    __slots__ = ("wid", "status", "steps_done", "attempts", "gen",
                 "seqs", "round_seqs", "pending", "round_reply_t",
                 "retry_attempts", "wedged_until",
                 "slow_until", "persistent_factor", "round_t0",
                 "last_beat", "delay_episodes", "divergence")

    def __init__(self, wid: int):
        self.wid = wid
        self.status = "new"          # new|live|dead|finished|failed
        self.steps_done = 0
        self.attempts = 0            # spawns (first spawn = 1)
        self.gen = 0                 # bumps on death/spawn: stale events
        self.seqs: List[int] = []    # per-shard next seq
        self.round_seqs: List[int] = []
        self.pending = 0
        self.round_reply_t = 0.0
        self.retry_attempts: List[int] = []
        self.wedged_until = -1.0
        self.slow_until = -1.0
        self.persistent_factor = 1.0
        self.round_t0 = 0.0
        self.last_beat = -1.0
        self.delay_episodes = 0
        self.divergence = 0.0        # §25 beacon spread (corrupt faults)


class FleetSim:
    """Build with a config, ``run()``, then read ``log``, ``summary``,
    and hand the instance to :func:`simfleet.invariants.check_invariants`.
    """

    def __init__(self, n_workers: int = 128, steps: int = 2000,
                 sync_freq: int = 16, seed: int = 0, *,
                 n_shards: int = 2, dedup_depth: int = 64,
                 step_time_s: float = 0.02, step_jitter: float = 0.2,
                 n_stragglers: int = 0, straggler_factor: float = 4.0,
                 lease_timeout: float = 15.0, poll_s: float = 2.0,
                 detect_s: float = 0.25, max_restarts: int = 3,
                 crash_limit: Optional[int] = None,
                 crash_window_s: float = 60.0,
                 schedule: Optional[Sequence[Fault]] = None,
                 net_schedule: Optional[Sequence[Fault]] = None,
                 n_faults: int = 0, net_n_faults: int = 0,
                 fault_t_min: float = 5.0, fault_t_max: float = 60.0,
                 net_fault_duration: float = 3.0,
                 latency_s: float = 0.004, op_timeout_s: float = 3.0,
                 wire_max_retries: int = 8,
                 straggle_windows: int = 3, straggle_window_s: float = 5.0,
                 straggle_poll_s: float = 10.0,
                 straggle_ratio: float = 2.0,
                 exch_prob: float = 0.25, n_perms: int = 16,
                 gossip: bool = True, center_outage_s: float = 2.0,
                 horizon_s: Optional[float] = None,
                 fleetmon: bool = False, fleetmon_rules=None,
                 fleetmon_eval_s: float = 2.0):
        self.n_workers = int(n_workers)
        self.steps_goal = int(steps)
        self.sync_freq = max(1, int(sync_freq))
        self.seed = int(seed)
        self.n_shards = int(n_shards)
        self.step_time_s = float(step_time_s)
        self.step_jitter = float(step_jitter)
        self.straggler_factor = float(straggler_factor)
        self.lease_timeout = float(lease_timeout)
        self.poll_s = float(poll_s)
        self.detect_s = float(detect_s)
        self.max_restarts = int(max_restarts)
        self.op_timeout_s = float(op_timeout_s)
        self.wire_max_retries = int(wire_max_retries)
        self.straggle_windows = int(straggle_windows)
        self.straggle_window_s = float(straggle_window_s)
        self.straggle_poll_s = float(straggle_poll_s)
        self.straggle_ratio = float(straggle_ratio)
        self.exch_prob = float(exch_prob)
        self.gossip_on = bool(gossip)
        self.center_outage_s = float(center_outage_s)

        # -- seeded randomness: ONE stream per concern, all derived from
        # the run seed, so reordering draws in one concern cannot shift
        # another (the determinism gate depends on it)
        self.rng = random.Random(self.seed)               # durations/latency
        self.rng_gossip = random.Random(self.seed ^ 0x9E3779B9)
        self.backoff = Backoff(base=0.5, factor=2.0, cap=8.0,
                               rng=random.Random(
                                   self.seed ^ 0x5DEECE66))  # respawns
        self.wire_backoff = Backoff(base=0.2, factor=2.0, cap=5.0,
                                    rng=random.Random(
                                        self.seed ^ 0x0BACF))  # retries

        # -- schedules: explicit lists, or seeded draws from the real
        # chaos generator
        wids = list(range(1, self.n_workers + 1))
        if schedule is None and n_faults:
            schedule = seeded_schedule(self.seed ^ 0xC4A05, wids,
                                       n_faults=n_faults,
                                       t_min=fault_t_min, t_max=fault_t_max,
                                       kinds=("kill", "stop", "delay"),
                                       duration=4.0)
        if net_schedule is None and net_n_faults:
            net_schedule = seeded_schedule(self.seed ^ 0x7E7, [-1],
                                           n_faults=net_n_faults,
                                           t_min=fault_t_min,
                                           t_max=fault_t_max,
                                           kinds=NET_FAULT_KINDS,
                                           duration=net_fault_duration)
        self.schedule = sorted([f for f in (schedule or ())
                                if f.kind not in NET_FAULT_KINDS],
                               key=lambda f: (f.at, f.target))
        self.net_schedule = sorted([f for f in (schedule or ())
                                    if f.kind in NET_FAULT_KINDS]
                                   + list(net_schedule or ()),
                                   key=lambda f: (f.at, f.target))

        # -- the machinery under test ---------------------------------------
        self.vclock = VirtualClock()
        self.queue = EventQueue(self.vclock)
        self.log = EventLog()
        self.lease_table: Dict[int, dict] = {}
        self.center = SimCenter(self.n_shards, dedup_depth)
        self.mesh = SimExchanger(self.n_workers + 1, n_perms=n_perms,
                                 exclude=(CENTER_ID,))
        self.controller = MembershipController(
            lease_timeout=self.lease_timeout,
            telemetry_=telemetry.DISABLED,
            reactors=(CenterReactor(self.center), MeshReactor(self.mesh)),
            straggle_windows=self.straggle_windows,
            straggle_window_s=self.straggle_window_s,
            min_active=1, clock=self.vclock,
            lease_source=lambda: self.lease_table)
        kills = sum(1 for f in self.schedule if f.kind == "kill")
        self.breaker = CrashLoopBreaker(
            limit=crash_limit if crash_limit is not None
            else max(6, kills + 2),
            window_s=crash_window_s, clock=self.vclock)

        # -- fleet state ------------------------------------------------------
        self.workers = {w: SimWorker(w) for w in wids}
        stragglers = list(wids)
        random.Random(self.seed ^ 0x57A6).shuffle(stragglers)
        self.stragglers = sorted(stragglers[:int(n_stragglers)])
        for w in self.stragglers:
            self.workers[w].persistent_factor = self.straggler_factor
        self.transport = SimTransport(self.vclock, self.rng,
                                      self.net_schedule,
                                      center=self.center,
                                      latency_s=latency_s,
                                      op_timeout_s=self.op_timeout_s)
        # fleet health plane rehearsal (round 18, docs/design.md §20):
        # the REAL FleetCollector + rule engine on the virtual clock —
        # off by default so the §18 determinism hashes are unchanged;
        # enabled, its alerts join the canonical event log
        self.health = None
        if fleetmon:
            from .health import HealthPlane
            self.health = HealthPlane(self, rules=fleetmon_rules,
                                      eval_window_s=fleetmon_eval_s)
        self.finished: set = set()
        self.failed: set = set()
        self.deaths = 0
        self.skips = 0
        self.dedup_first_attempt: List[tuple] = []   # wrongly deduped
        self.lease_violations: List[str] = []
        self.alpha_violations: List[str] = []
        self._alpha_at_demote: Dict[int, float] = {}
        self._clean_streak: Dict[int, int] = {}
        self._window_means: Dict[int, Dict[int, float]] = {}
        self._windows_straggled: Dict[int, int] = {}
        self._last_mean: Dict[int, float] = {}
        self._scored_widx = -1
        self._drained = 0
        self.realized: List[dict] = []
        self.stopped_reason: Optional[str] = None
        # gossip plane: α mass (the conservation invariant) and a mixing
        # scalar (weighted-average merge — variance decay is the mixing
        # observable); index = worker id, slot 0 unused
        self.alpha = [1.0] * (self.n_workers + 1)
        self.mix = [float(w) for w in range(self.n_workers + 1)]
        self.alpha0_sum = float(self.n_workers)
        self.mix_var0 = float(np.var(self.mix[1:]))
        self.horizon_s = horizon_s if horizon_s is not None else \
            max(600.0, 60.0 * self.steps_goal * self.step_time_s *
                self.straggler_factor)
        self.summary: dict = {}

    # -- helpers --------------------------------------------------------------

    def _now(self) -> float:
        return self.vclock.now()

    def _realize(self, fault: Fault, error: Optional[str] = None) -> None:
        now = self._now()
        self.realized.append({
            "ts": round(now, 6), "rel": round(now, 6), "kind": fault.kind,
            "target": fault.target, "duration": fault.duration,
            "pid": None, "error": error, "source": "simfleet"})
        self.log.append(now, "fault", kind=fault.kind, target=fault.target,
                        duration=fault.duration, error=error)

    def _drain_transitions(self) -> None:
        """Mirror every controller transition into the event log (the
        membership-sequence artifact fidelity compares)."""
        trans = self.controller.transitions
        while self._drained < len(trans):
            ev, w, info = trans[self._drained]
            self._drained += 1
            self.log.append(self._now(), ev, worker=w,
                            reason=info.get("reason"),
                            rejoin=bool(info.get("rejoin")))

    #: the live worker's monitor thread beats every ~2 s (WorkerLease
    #: min_interval_s) REGARDLESS of step speed — the sim must match, or
    #: any round longer than lease_timeout falsely reads as a wedge
    BEAT_EVERY_S = 2.0

    def _beat(self, w: SimWorker, status: str = "live") -> None:
        now = self._now()
        w.last_beat = now
        self.lease_table[w.wid] = {"worker": w.wid, "pid": None,
                                   "ts": now, "step": w.steps_done,
                                   "status": status}
        if self.health is not None:
            # a lease beat doubles as a metric-snapshot arrival (the
            # live MetricStreamer cadence) — kills/wedges silence it
            self.health.on_beat(w.wid, status, w.steps_done)

    def _schedule_beats(self, wid: int, gen: int, t_from: float,
                        t_until: float) -> None:
        """Mid-round heartbeats for a compute segment longer than the
        beat cadence (a slow/slowed worker is ALIVE — only wedges and
        deaths may silence the lease)."""
        t = t_from + self.BEAT_EVERY_S
        while t < t_until:
            self.queue.push(t, lambda: self._beat_tick(wid, gen))
            t += self.BEAT_EVERY_S

    def _beat_tick(self, wid: int, gen: int) -> None:
        w = self.workers[wid]
        if self.stopped_reason or w.gen != gen or w.status != "live":
            return
        if self._now() < w.wedged_until:
            return                 # SIGSTOPped: the process can't beat
        self._beat(w)

    def _exchange_duration(self, w: SimWorker) -> float:
        now = self._now()
        j = self.step_jitter
        dt = self.sync_freq * self.step_time_s * \
            (1.0 - j + 2.0 * j * self.rng.random()) * w.persistent_factor
        if now < w.slow_until:
            dt *= self.straggler_factor
        return dt

    def _alldone(self) -> bool:
        return len(self.finished | self.failed) >= self.n_workers

    # -- straggler windows ----------------------------------------------------

    def _window_sample(self, w: SimWorker, dur: float) -> None:
        """Attribute a completed round to the window containing its
        COMPLETION time, immediately — a finalize-on-next-round scheme
        would deliver a slow worker's sample after its window was
        already scored, silently freezing exactly the straggle counts
        the policy runs on."""
        widx = int(self._now() / self.straggle_window_s)
        bucket = self._window_means.setdefault(widx, {})
        ent = bucket.get(w.wid)
        if ent is None:
            bucket[w.wid] = [dur, 1]
        else:
            ent[0] += dur
            ent[1] += 1

    def _score_windows(self) -> None:
        """Fold completed straggler windows into cumulative straggle
        counts (the ranking rows the REAL check_stragglers consumes) and
        clean streaks (the readmission signal)."""
        upto = int(self._now() / self.straggle_window_s) - 1
        for widx in range(self._scored_widx + 1, upto + 1):
            bucket = self._window_means.pop(widx, None)
            if not bucket or len(bucket) < 2:
                continue
            means = {wid: s / c for wid, (s, c) in bucket.items()}
            med = sorted(means.values())[len(means) // 2]
            for wid, mean in sorted(means.items()):
                self._last_mean[wid] = mean
                if med > 0 and mean > self.straggle_ratio * med:
                    self._windows_straggled[wid] = \
                        self._windows_straggled.get(wid, 0) + 1
                    self._clean_streak[wid] = 0
                else:
                    self._clean_streak[wid] = \
                        self._clean_streak.get(wid, 0) + 1
        self._scored_widx = max(self._scored_widx, upto)

    # -- lifecycle events -----------------------------------------------------

    def _spawn(self, wid: int, respawn: bool) -> None:
        w = self.workers[wid]
        w.status = "live"
        w.gen += 1
        w.steps_done = 0
        w.attempts += 1
        w.wedged_until = -1.0
        w.pending = 0
        w.divergence = 0.0     # a respawn restores from the live center
        # a respawn of a straggler-demoted worker rejoins (the real
        # join→on_join path readmits it) — its α legitimately unfreezes
        self._alpha_at_demote.pop(wid, None)
        now = self._now()
        # the real WireClient seeds each incarnation's seq from the clock
        # so a respawn can never replay into its predecessor's HWM shadow
        base = int(now * 1000)
        w.seqs = [base] * self.n_shards
        w.round_seqs = [0] * self.n_shards
        w.retry_attempts = [0] * self.n_shards
        self._beat(w)
        self.controller.join(wid, reason="respawn" if respawn else "spawn",
                             now=now)
        self._drain_transitions()
        w.round_t0 = now
        gen = w.gen
        t_next = now + self._exchange_duration(w)
        self._schedule_beats(wid, gen, now, t_next)
        self.queue.push(t_next, lambda: self._exchange(wid, gen))

    def _on_death(self, wid: int, reason: str) -> None:
        w = self.workers[wid]
        now = self._now()
        self.deaths += 1
        self.controller.leave(wid, reason=reason, now=now, rc=-9)
        self._drain_transitions()
        if self.breaker.record_failure(now):
            self.stopped_reason = "crash_loop_breaker"
            self.log.append(now, "breaker_tripped", deaths=self.deaths)
            return
        if w.attempts > self.max_restarts:
            w.status = "failed"
            self.failed.add(wid)
            self.log.append(now, "restart_exhausted", worker=wid,
                            attempts=w.attempts)
            return
        delay = self.backoff.delay(w.attempts - 1)
        self.log.append(now, "respawn_scheduled", worker=wid,
                        delay=round(delay, 6), attempt=w.attempts)
        self.queue.push(now + delay, lambda: self._respawn(wid))

    def _respawn(self, wid: int) -> None:
        if self.stopped_reason or self.workers[wid].status != "dead":
            return
        self._spawn(wid, respawn=True)

    # -- the exchange round ---------------------------------------------------

    def _exchange(self, wid: int, gen: int) -> None:
        w = self.workers[wid]
        if self.stopped_reason or w.gen != gen or w.status != "live":
            return
        now = self._now()
        if now < w.wedged_until:           # SIGSTOPped: silent, deferred
            self.queue.push(w.wedged_until + 1e-3,
                            lambda: self._exchange(wid, gen))
            return
        # the straggler sample spans the whole round — compute AND wire
        # (retry stalls, delay windows), so network trouble surfaces in
        # the ranking exactly as it does in the live phase brackets
        self._window_sample(w, now - w.round_t0)
        if self.health is not None:
            self.health.on_round(w.wid, now - w.round_t0,
                                 divergence=w.divergence)
        if w.divergence:
            # the elastic pull drags a corrupted replica back toward the
            # center each round: decay until the rule's breach episode
            # clears, so a LATER corrupt fault can re-alert (no-flapping
            # episode semantics need the condition to go false between)
            w.divergence = 0.0 if w.divergence < 1e-9 \
                else w.divergence * 0.5
        w.round_t0 = now
        self._beat(w)
        w.steps_done += self.sync_freq
        if w.steps_done >= self.steps_goal:
            w.status = "finished"
            self.finished.add(wid)
            self._beat(w, status="left")   # the clean-departure lease doc
            self.log.append(now, "worker_finished", worker=wid,
                            steps=w.steps_done, attempts=w.attempts)
            return
        w.pending = self.n_shards
        w.round_reply_t = now
        for shard in range(self.n_shards):
            w.round_seqs[shard] = w.seqs[shard]
            w.seqs[shard] += 1
            w.retry_attempts[shard] = 0
            self._send(wid, shard, gen)

    def _send(self, wid: int, shard: int, gen: int) -> None:
        w = self.workers[wid]
        if self.stopped_reason or w.gen != gen or w.status != "live":
            return
        now = self._now()
        if now < w.wedged_until:
            # SIGSTOP freezes the whole process: retries stall too
            self.queue.push(w.wedged_until + 1e-3,
                            lambda: self._send(wid, shard, gen))
            return
        # the worker's main thread beats through exchange retries (the
        # elastic worker CLI's monitor loop) — only wedges and deaths
        # silence the lease
        self._beat(w)
        seq = w.round_seqs[shard]
        attempt = w.retry_attempts[shard]
        status, verdict, t_done = \
            self.transport.request_push(wid, shard, seq)
        if status == "ok":
            if verdict == "dedup" and attempt == 0:
                # a NEVER-retried token answered from the window: the
                # dedup/HWM machinery swallowed a fresh push
                self.dedup_first_attempt.append((wid, shard, seq))
            self._shard_done(w, t_done)
            return
        # lost / retryable: the client retries the SAME token after the
        # real wire backoff, up to the wire retry budget; past it the
        # island skips the exchange (wire.exchange_skipped semantics)
        w.retry_attempts[shard] = attempt + 1
        if self.health is not None:
            # the live wire.retry counter tick — the wire_degraded rate
            # rule's raw signal
            self.health.on_wire_retry(wid)
        if attempt + 1 > self.wire_max_retries:
            self.skips += 1
            self.log.append(self._now(), "exchange_skipped", worker=wid,
                            shard=shard, attempts=attempt + 1)
            self._shard_done(w, t_done)
            return
        delay = self.wire_backoff.delay(attempt)
        self.queue.push(t_done + delay, lambda: self._send(wid, shard, gen))

    def _shard_done(self, w: SimWorker, t_done: float) -> None:
        w.round_reply_t = max(w.round_reply_t, t_done)
        w.pending -= 1
        if w.pending > 0:
            return
        gen = w.gen
        t_next = w.round_reply_t + self._exchange_duration(w)
        self._schedule_beats(w.wid, gen, w.round_reply_t, t_next)
        self.queue.push(t_next, lambda: self._exchange(w.wid, gen))

    # -- faults ---------------------------------------------------------------

    def _apply_fault(self, fault: Fault, tries: int = 0) -> None:
        if self.stopped_reason:
            return
        now = self._now()
        if fault.target == CENTER_ID:
            if fault.kind == "kill":
                outage = fault.duration or self.center_outage_s
                self.center.crash_and_restore(now, outage)
                self.controller.center_down(reason="crashed", rc=-9,
                                            downs=self.center.restarts)
                self._realize(fault)
                self.queue.push(now + outage, self._center_restored)
            else:
                self._realize(fault, error="center-faults-are-kills")
            return
        w = self.workers.get(fault.target)
        if w is None or w.status != "live":
            # the monkey's grace semantics: retry while the target is
            # between lives, then drop with no-pid
            if tries * 0.5 > 10.0 or w is None or \
                    w.status in ("finished", "failed"):
                self._realize(fault, error="no-pid")
            else:
                self.queue.push(now + 0.5,
                                lambda: self._apply_fault(fault, tries + 1))
            return
        if fault.kind == "kill":
            w.status = "dead"
            w.gen += 1
            self._realize(fault)
            self.queue.push(now + self.detect_s,
                            lambda: self._on_death(fault.target, "crashed"))
        elif fault.kind == "stop":
            w.wedged_until = now + fault.duration
            self._realize(fault)
        elif fault.kind == "delay":
            w.slow_until = now + fault.duration
            w.delay_episodes += 1
            self._realize(fault)
        elif fault.kind == "corrupt":
            # the live semantics (utils/chaos.py): the replica perturbs
            # itself by `duration`-as-scale and the §25 beacon spread
            # jumps; the next round's divergence sample must trip the
            # replica_divergence rule within one beacon period.  The bad
            # push then moves the CENTER, so every live replica's
            # distance to the consensus spikes — the live elastic run
            # alerts fleet-wide, and the rehearsal must match that set
            scale = fault.duration or 1e-3
            for peer in self.workers.values():
                if peer.status != "dead":
                    peer.divergence = max(peer.divergence, scale)
            self._realize(fault)

    def _center_restored(self) -> None:
        self.controller.center_restored(attempt=self.center.restarts)
        self._drain_transitions()

    # -- control loops --------------------------------------------------------

    def _poll(self) -> None:
        if self.stopped_reason:
            return
        trans = self.controller.poll()
        for ev, wid, info in trans:
            if ev == "worker_leave" and \
                    info.get("reason") == "lease_expired":
                w = self.workers[wid]
                # lease-timeout safe region: an expiry verdict against a
                # worker that was alive and beating is a FALSE death
                now = self._now()
                silent = now - w.last_beat
                if w.status == "live" and now >= w.wedged_until and \
                        silent <= self.lease_timeout:
                    self.lease_violations.append(
                        f"false death: worker {wid} expired while "
                        f"beating (silent {silent:.1f}s)")
                # the detection bound: the first poll past expiry must
                # catch it — a wedge goes unnoticed for at most
                # lease_timeout + one poll period
                if silent > self.lease_timeout + self.poll_s + 0.5:
                    self.lease_violations.append(
                        f"late detection: worker {wid} silent "
                        f"{silent:.1f}s before expiry verdict")
                # the supervisor kills a wedged-but-alive process and
                # respawns it (membership step 2)
                if w.status == "live":
                    w.status = "dead"
                    w.gen += 1
                    self._on_death(wid, "wedged")
        self._drain_transitions()
        if not self._alldone():
            self.queue.push(self._now() + self.poll_s, self._poll)

    def _straggle_check(self) -> None:
        if self.stopped_reason:
            return
        self._score_windows()
        status = self.controller.status()
        ranking = [{"rank": wid,
                    "windows_straggled": self._windows_straggled.get(wid, 0),
                    "mean_train_secs": self._last_mean.get(wid)}
                   for wid in sorted(self.workers)
                   if self.workers[wid].status == "live"]
        demoted = self.controller.check_stragglers(ranking)
        for wid in demoted:
            self._alpha_at_demote[wid] = self.alpha[wid]
        # readmission: a demoted worker with a clean streak re-enters
        # (worker_join reason='readmit' — design.md §14)
        for wid in status.get("demoted", ()):
            if self._clean_streak.get(wid, 0) >= self.straggle_windows \
                    and self.workers[wid].status == "live":
                ref = self._alpha_at_demote.pop(wid, None)
                if ref is not None and \
                        abs(self.alpha[wid] - ref) > 1e-9:
                    self.alpha_violations.append(
                        f"demoted worker {wid} alpha moved "
                        f"{ref} -> {self.alpha[wid]}")
                # readmit() itself forgives the stale cumulative
                # evidence (straggle_forgive — the production fix the
                # first 1,000-worker rehearsal forced)
                self.controller.readmit(wid)
        self._drain_transitions()
        if not self._alldone():
            self.queue.push(self._now() + self.straggle_poll_s,
                            self._straggle_check)

    def _gossip_round(self) -> None:
        if self.stopped_reason:
            return
        tables = self.mesh.tables
        active = self.mesh.active
        rng = self.rng_gossip
        if len(active) > 1:
            row = tables[rng.randrange(len(tables))]
            sends = [(i, int(row[i])) for i in active
                     if rng.random() < self.exch_prob]
            # two-phase like the traced algebra: every w_send derives
            # from the PRE-round alpha, receivers then merge
            staged = []
            for i, peer in sends:
                s = self.alpha[i] * 0.5
                staged.append((i, peer, s, self.mix[i]))
                self.alpha[i] -= s
            for i, peer, s, mx in staged:
                a = self.alpha[peer]
                self.mix[peer] = (a * self.mix[peer] + s * mx) / (a + s) \
                    if a + s > 0 else self.mix[peer]
                self.alpha[peer] = a + s
        if not self._alldone():
            self.queue.push(
                self._now() + self.sync_freq * self.step_time_s,
                self._gossip_round)

    # -- run ------------------------------------------------------------------

    def run(self) -> dict:
        # initial joins BEFORE reactors see churn would regenerate the
        # mesh N times for nothing — the reactors are attached already,
        # so spawn order is the regeneration order; with 1,000 workers
        # that is the one deliberately-batched step: spawn with reactors
        # detached, then sync them once.
        self.log.append(0.0, "fleet_start", n_workers=self.n_workers,
                        steps=self.steps_goal, sync_freq=self.sync_freq,
                        seed=self.seed, shards=self.n_shards,
                        schedule=[repr(f) for f in self.schedule],
                        net_schedule=[repr(f) for f in self.net_schedule],
                        stragglers=self.stragglers)
        reactors = self.controller.reactors
        self.controller.reactors = []
        for wid in sorted(self.workers):
            self._spawn(wid, respawn=False)
        self.controller.reactors = reactors
        self.mesh.set_active_ranks(None)
        for f in self.schedule:
            self.queue.push(f.at,
                            lambda fault=f: self._apply_fault(fault))
        for f in self.net_schedule:
            # a window OPENING is the realized event (the live proxy's
            # monitor emits exactly then); per-frame fates are counters
            self.queue.push(f.at, lambda fault=f: self._realize(fault))
        self.queue.push(self.poll_s, self._poll)
        self.queue.push(self.straggle_poll_s, self._straggle_check)
        if self.health is not None:
            self.health.install()
        if self.gossip_on:
            self.queue.push(self.sync_freq * self.step_time_s,
                            self._gossip_round)
        self.queue.run(until=self.horizon_s)
        if not self._alldone() and not self.stopped_reason:
            self.stopped_reason = "horizon"
        self._score_windows()
        self._drain_transitions()
        now = self._now()
        cs = self.center.stats()
        self.summary = {
            "n_workers": self.n_workers, "seed": self.seed,
            "virtual_secs": round(now, 3),
            "events": self.queue.processed,
            "finished": len(self.finished), "failed": len(self.failed),
            "deaths": self.deaths, "skips": self.skips,
            "transitions": len(self.controller.transitions),
            "center": cs,
            "frames_faulted": dict(sorted(
                self.transport.frames_faulted.items())),
            "mesh_regens": self.mesh.regens,
            "alpha_sum": round(sum(self.alpha[1:]), 9),
            "mix_var_ratio": round(
                float(np.var([self.mix[i] for i in self.mesh.active]))
                / self.mix_var0, 9) if self.mesh.active and self.mix_var0
            else None,
            "windows_scored": self._scored_widx + 1,
            "stragglers": self.stragglers,
            "stopped": self.stopped_reason,
        }
        if self.health is not None:
            self.summary["fleetmon"] = self.health.summary()
        self.log.append(now, "summary", **self.summary)
        return self.summary
