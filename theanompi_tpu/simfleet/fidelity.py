"""Fidelity cross-check: the simulator's word, verified against reality.

A simulator that only agrees with itself proves nothing.  This module
closes the loop at small scale: run a schedule through :class:`FleetSim`,
export the *realized* schedule (the same jsonl dialect the live
harness's ``chaos_realized.jsonl`` speaks), replay it through the REAL
elastic runtime — actual worker subprocesses, the actual
ChaosMonkey/ChaosProxy — and assert both worlds produced the same
*membership-event sequence* per worker, modulo timing:

    join ( death rejoin )* finish        for a worker the schedule kills
    join finish                          for one it leaves alone

Event kinds and reasons are normalized (``crashed``/``wedged``/
``lease_expired`` are all a *death*; ``respawn``/``lease`` rejoins are
one *rejoin*) because WHICH detector fires first is timing, while THAT
a kill produces exactly one death and one supervised rejoin is the
contract under test.

This is deliberately cheap (4 workers, one schedule) and sits next to
the width rehearsal: simfleet argues at 1,000 workers, fidelity argues
the simulator tells the truth at 4.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Sequence

try:
    from ..utils.chaos import NET_FAULT_KINDS, schedule_from_realized
except ImportError:        # file-path load: absolute
    from theanompi_tpu.utils.chaos import (NET_FAULT_KINDS,
                                           schedule_from_realized)

from .fleet import FleetSim

#: normalization: event kind + reason -> sequence token
_DEATH_REASONS = ("crashed", "wedged", "lease_expired")


def export_realized(realized: Sequence[dict], path: str, *,
                    min_at: float = 0.0, scale: float = 1.0) -> str:
    """Write a FleetSim's realized fault list in the live harness's
    ``chaos_realized.jsonl`` dialect.  ``scale``/``min_at`` let a replay
    re-time the schedule (live workers spend seconds importing jax
    before a fault can land; virtual workers are live at t=0⁺)."""
    with open(path, "w") as f:
        for doc in realized:
            out = dict(doc)
            out["rel"] = round(max(float(doc["rel"]) * scale, min_at), 3)
            f.write(json.dumps(out, sort_keys=True) + "\n")
    return path


def normalize_sequence(events: Sequence[dict]) -> Dict[int, List[str]]:
    """Per-worker token sequences from membership events (each event a
    dict with ``ev``/``worker``/``reason``/``rejoin`` fields — both the
    sim log and the live telemetry stream satisfy this)."""
    seqs: Dict[int, List[str]] = {}
    for e in events:
        ev, w = e.get("ev"), e.get("worker")
        if w is None or int(w) < 0:
            continue
        w = int(w)
        if ev == "worker_join":
            tok = "rejoin" if e.get("rejoin") else "join"
        elif ev == "worker_leave":
            tok = "finish" if e.get("reason") == "finished" else (
                "death" if e.get("reason") in _DEATH_REASONS else None)
        elif ev == "worker_demote":
            tok = "demote"
        else:
            continue
        if tok is None:
            continue
        seq = seqs.setdefault(w, [])
        # collapse repeats: a wedge can be seen by BOTH the lease expiry
        # and the process exit — one death, two observations
        if not (seq and seq[-1] == tok and tok in ("death", "rejoin")):
            seq.append(tok)
    return seqs


def sim_membership_sequence(fleet: FleetSim) -> Dict[int, List[str]]:
    return normalize_sequence(fleet.log.select(
        "worker_join", "worker_leave", "worker_demote"))


def live_membership_sequence(record_dir: str) -> Dict[int, List[str]]:
    events = []
    for p in sorted(glob.glob(os.path.join(record_dir,
                                           "telemetry_rank*.jsonl"))):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("ev") in ("worker_join", "worker_leave",
                                   "worker_demote"):
                    events.append(e)
    events.sort(key=lambda e: e.get("ts", 0))
    return normalize_sequence(events)


def crosscheck(record_dir: str, *, n_workers: int = 4,
               schedule: str = "kill@6:1", steps: int = 40,
               seed: int = 0, live_timeout_s: float = 420.0,
               run_live: bool = True) -> dict:
    """The acceptance-criteria cross-check: simulate ``schedule`` at
    ``n_workers``, export the realized schedule, replay it through the
    live elastic runtime (ChaosMonkey + ChaosProxy when net windows are
    present), and compare membership sequences.

    Returns ``{"ok", "sim", "live", "realized_path", "live_rc"}``;
    ``run_live=False`` stops after the sim+export (for callers that
    split the phases)."""
    from ..parallel.membership import Backoff, run_elastic
    from ..utils import chaos

    os.makedirs(record_dir, exist_ok=True)
    sched = chaos.parse_schedule(schedule)
    max_at = max((f.at + f.duration for f in sched), default=0.0)
    # virtual step time sized so the schedule lands MID-run, as it will
    # live (live workers run ~0.2–0.5 s/step after a multi-second boot)
    step_time = max(0.02, 2.5 * max_at / max(1, steps))
    fleet = FleetSim(n_workers=n_workers, steps=steps, sync_freq=2,
                     seed=seed, schedule=sched, n_shards=1,
                     step_time_s=step_time, lease_timeout=60.0,
                     gossip=False)
    fleet.run()
    realized_path = os.path.join(record_dir, "sim_realized.jsonl")
    # the live monkey's clock is progress-gated (membership.run_elastic
    # starts it once every worker leases step >= 1), so rel times here
    # are measured from real training progress, not from spawn — min_at
    # only needs to keep the fault clear of the very first steps
    export_realized(fleet.realized, realized_path, min_at=6.0)
    sim_seq = sim_membership_sequence(fleet)
    out = {"sim": sim_seq, "realized_path": realized_path,
           "live": None, "live_rc": None, "ok": None}
    if not run_live:
        return out

    live_sched = schedule_from_realized(realized_path)
    live_dir = os.path.join(record_dir, "live")
    proc_sched = [f for f in live_sched
                  if f.kind not in NET_FAULT_KINDS]
    # SleepyModel stretches the live run past the schedule's last fault:
    # with the progress-gated monkey a bare TinyModel burns all `steps`
    # in well under the re-timed fault offsets, and the kill would land
    # on a finished fleet (no death, sequence mismatch)
    rc = run_elastic(
        "easgd", "tests.conftest", "SleepyModel",
        {"sync_freq": 2, "batch_size": 8, "iter_sleep": 0.25}, n_workers,
        record_dir=live_dir, steps=steps, host_devices=1,
        chaos_schedule=proc_sched,
        net_chaos_schedule=[f for f in live_sched
                            if f.kind in NET_FAULT_KINDS] or None,
        # target 0 = the center: it must exist as its own supervised
        # process for the monkey to kill it (chaos_run derives this the
        # same way)
        center_proc=any(f.target == 0 for f in proc_sched),
        timeout_s=live_timeout_s,
        supervisor_kw={"poll_s": 0.2, "backoff": Backoff(base=0.3),
                       "lease_timeout": 60.0})
    live_seq = live_membership_sequence(live_dir)
    out["live"] = live_seq
    out["live_rc"] = rc
    out["ok"] = rc == 0 and live_seq == sim_seq
    return out
