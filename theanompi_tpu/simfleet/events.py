"""Discrete-event core: the queue the fleet runs on and the log it
proves itself with.

**Queue.**  A heap of ``(time, seq, fn)``; ``seq`` is a monotonically
increasing tiebreaker, so two events at the same virtual instant fire in
scheduling order — the property that makes the whole simulation a total
order and therefore replayable.  Handlers take no arguments (bind state
via closure/partial) and schedule follow-ups through ``push``.

**Log.**  Append-only structured records with virtual timestamps,
serialized canonically (sorted keys, fixed separators, timestamps
rounded to µs) so *same seed ⇒ byte-identical bytes* is a meaningful
claim; :meth:`EventLog.sha256` is the determinism gate's whole
comparison.  The log records decisions (transitions, faults, windows,
respawns), not traffic — a 10⁷-round run logs thousands of lines, not
millions.
"""

from __future__ import annotations

import hashlib
import heapq
import json
from typing import Callable, List, Optional


class EventQueue:
    """Deterministic min-heap event queue over virtual seconds."""

    def __init__(self, clock):
        self.clock = clock
        self._heap: list = []
        self._seq = 0
        self.processed = 0

    def push(self, t: float, fn: Callable[[], None]) -> None:
        assert t >= self.clock.now() - 1e-9, \
            f"scheduling into the past: {t} < {self.clock.now()}"
        self._seq += 1
        heapq.heappush(self._heap, (float(t), self._seq, fn))

    def run(self, until: Optional[float] = None,
            max_events: Optional[int] = None) -> int:
        """Drain events (advancing the clock to each) until the queue is
        empty, virtual ``until`` is reached, or ``max_events`` fired.
        Returns the number of events processed by THIS call."""
        n = 0
        while self._heap:
            if until is not None and self._heap[0][0] > until:
                break
            if max_events is not None and n >= max_events:
                break
            t, _, fn = heapq.heappop(self._heap)
            self.clock.advance_to(t)
            fn()
            n += 1
        self.processed += n
        return n

    def __len__(self) -> int:
        return len(self._heap)


class EventLog:
    """Canonical, hashable record of what the simulation decided."""

    def __init__(self):
        self.records: List[dict] = []

    def append(self, t: float, ev: str, **fields) -> None:
        rec = {"t": round(float(t), 6), "ev": str(ev)}
        rec.update(fields)
        self.records.append(rec)

    # -- canonical serialization --------------------------------------------

    @staticmethod
    def _line(rec: dict) -> str:
        return json.dumps(rec, sort_keys=True, separators=(",", ":"))

    def to_jsonl(self) -> str:
        return "".join(self._line(r) + "\n" for r in self.records)

    def sha256(self) -> str:
        h = hashlib.sha256()
        for r in self.records:
            h.update(self._line(r).encode())
            h.update(b"\n")
        return h.hexdigest()

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl())

    def select(self, *kinds: str) -> List[dict]:
        return [r for r in self.records if r["ev"] in kinds]
