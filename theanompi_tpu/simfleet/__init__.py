"""simfleet: a deterministic virtual-time fleet simulator (round 17).

The survivability plane (PRs 8–11) is real code exercised small: ~8
processes, wall-clock minutes.  simfleet rehearses the SAME code at
production width — hundreds to thousands of workers, tens of thousands
of exchange rounds, seconds of CPU — by replacing processes, sockets,
and sleeps with a seeded discrete-event loop over a virtual clock
(docs/design.md §18):

* the **real** :class:`~theanompi_tpu.parallel.membership
  .MembershipController` state machine (lease folding, dead-ts
  resurrection guard, straggler demotion with the cumulative base),
* the **real** reactors (:class:`~...membership.CenterReactor` island
  demote/readmit, :class:`~...membership.MeshReactor` GoSGD derangement
  regeneration via ``parallel/topology.py``),
* the **real** :class:`~theanompi_tpu.parallel.wire.DedupWindow`
  claim/record/HWM semantics and :class:`~...membership.Backoff`,
* the **real** chaos grammar: ``chaos.parse_schedule`` /
  ``chaos.seeded_schedule`` faults applied by the proxy's own
  window-membership rule (``chaos.fault_window_active``).

Same seed ⇒ byte-identical event log (``EventLog.sha256``).  The
fidelity mode exports the realized simulated schedule and replays it
through the live ChaosProxy/ChaosMonkey at small scale, asserting the
same membership-event sequence modulo timing (``simfleet.fidelity``).

Entry points: ``scripts/simfleet_run.py`` (CLI, determinism gate,
fidelity cross-check) and :class:`simfleet.fleet.FleetSim`.
"""

from .clock import VirtualClock                              # noqa: F401
from .events import EventLog, EventQueue                     # noqa: F401
from .fleet import FleetSim                                  # noqa: F401
from .health import HealthPlane                              # noqa: F401
from .invariants import check_invariants                     # noqa: F401
