"""At-width invariant checkers over a finished :class:`FleetSim`.

Each checker returns ``(name, ok, detail)``; :func:`check_invariants`
runs them all and is the pass/fail verdict the CLI and the tier-1 gate
print.  These are the properties the survivability plane CLAIMS at
production width but could never test there until now:

* **no-resurrect-after-death** — a dead worker re-enters only through a
  supervisor respawn; a stale-but-fresh-looking lease must never fold
  back into a join (the controller's dead-ts guard, at 1,000 workers).
* **Σα conservation under churn** — gossip mass is conserved through
  demotions, readmissions, kills, and derangement regenerations; a
  demoted rank's α is bit-frozen while it is out.
* **applied-exactly-once** — under dup storms, retry-after-applied-ack-
  lost, eviction, and center crash/restore, no (client, seq) lands on a
  shard twice, and no fresh token is wrongly swallowed by the window.
* **straggler stability** — the demotion loop converges: nobody flaps
  (bounded demote count per worker), persistent stragglers end demoted.
* **center-shard/push load balance** — K shards absorb the same pushes
  up to the churn the run actually had (deaths and skips each strand at
  most one partial round).
* **lease-timeout safe region** — no false deaths (a beating worker is
  never expired) and no late detections (an expiry verdict lands within
  lease_timeout + one poll period of silence).
* **topology sanity** — every MeshReactor regeneration produced
  embedded derangements: inactive ranks fixed, active ranks routed
  among themselves.
"""

from __future__ import annotations

from typing import List, Tuple

Result = Tuple[str, bool, str]


def _no_resurrect(fleet) -> Result:
    dead_kind = ("crashed", "wedged", "lease_expired")
    state: dict = {}
    bad: List[str] = []
    for rec in fleet.log.records:
        ev, w = rec["ev"], rec.get("worker")
        if ev == "worker_leave" and rec.get("reason") in dead_kind:
            state[w] = "dead"
        elif ev == "worker_leave":
            state[w] = "left"
        elif ev == "worker_join":
            prev = state.get(w)
            if prev in ("dead", "left") and \
                    rec.get("reason") not in ("respawn",):
                bad.append(f"worker {w} resurrected from {prev} via "
                           f"join reason={rec.get('reason')!r} "
                           f"at t={rec['t']}")
            state[w] = "live"
    for w in sorted(fleet.failed):
        if state.get(w) == "live":
            bad.append(f"restart-exhausted worker {w} came back")
    return ("no_resurrect_after_death", not bad,
            "; ".join(bad[:4]) or "dead workers re-entered only via "
            "supervisor respawns")


def _alpha_conservation(fleet) -> Result:
    total = sum(fleet.alpha[1:])
    drift = abs(total - fleet.alpha0_sum)
    bad: List[str] = list(fleet.alpha_violations)
    # still-demoted ranks at run end: α frozen since their demotion
    for wid, ref in sorted(fleet._alpha_at_demote.items()):
        if abs(fleet.alpha[wid] - ref) > 1e-9:
            bad.append(f"still-demoted worker {wid} alpha moved "
                       f"{ref} -> {fleet.alpha[wid]}")
    ok = drift < 1e-6 * max(1.0, fleet.alpha0_sum) and not bad
    return ("alpha_conservation_under_churn", ok,
            "; ".join(bad[:4]) or f"Σα drift {drift:.2e} over "
            f"{fleet.mesh.regens} topology regenerations")


def _exactly_once(fleet) -> Result:
    cs = fleet.center.stats()
    bad: List[str] = []
    if cs["violations"]:
        bad.append(f"{cs['violations']} re-applications "
                   f"(per-worker applied-seq ledger)")
    if fleet.dedup_first_attempt:
        bad.append(f"{len(fleet.dedup_first_attempt)} fresh tokens "
                   f"wrongly answered from the dedup window, e.g. "
                   f"{fleet.dedup_first_attempt[0]}")
    # only twins that reached a LIVE center had anything to dedup — a
    # dup window entirely inside a center outage is not a dedup miss
    dups = fleet.transport.dup_applied
    hits = sum(cs["dedup_hits_per_shard"])
    if dups and not hits:
        bad.append(f"{dups} duplicated frames but 0 dedup hits — "
                   f"duplicates were re-applied")
    return ("dedup_applied_exactly_once", not bad,
            "; ".join(bad) or f"{sum(cs['applied_per_shard'])} applies, "
            f"{hits} dedup hits, {dups} dup frames, "
            f"{cs['restarts']} center restarts")


def _straggler_stability(fleet) -> Result:
    demotes: dict = {}
    readmits: dict = {}
    for rec in fleet.log.records:
        if rec["ev"] == "worker_demote":
            demotes[rec["worker"]] = demotes.get(rec["worker"], 0) + 1
        elif rec["ev"] == "worker_join" and \
                rec.get("reason") == "readmit":
            readmits[rec["worker"]] = readmits.get(rec["worker"], 0) + 1
    bad: List[str] = []
    # convergence = bounded transitions: a worker may be demoted once
    # for being persistently slow plus once per injected delay episode;
    # more is flapping.  (A late readmit is NOT flapping: once the fast
    # workers finish, a "straggler" is no longer slow relative to the
    # remaining fleet — relative ranking is the policy.)
    for wid, n in sorted(demotes.items()):
        allowance = 1 + fleet.workers[wid].delay_episodes
        if n > allowance:
            bad.append(f"worker {wid} demoted {n}x "
                       f"(allowance {allowance}): flapping")
    enough = fleet.summary.get("windows_scored", 0) >= \
        3 * fleet.straggle_windows
    if enough:
        for wid in fleet.stragglers:
            if fleet.workers[wid].status == "failed":
                continue               # died out of the ranking
            if demotes.get(wid, 0) < 1:
                bad.append(f"persistent straggler {wid} never demoted "
                           f"({fleet.summary['windows_scored']} windows)")
    return ("straggler_demotion_converges", not bad,
            "; ".join(bad[:4]) or f"{sum(demotes.values())} demotions / "
            f"{sum(readmits.values())} readmissions, no flapping")


def _shard_balance(fleet) -> Result:
    per = fleet.center.stats()["applied_per_shard"]
    spread = max(per) - min(per)
    # every death or skip strands at most one partial round's shard
    # asymmetry; center restarts can strand one in-flight round fleetwide
    allowance = fleet.deaths + fleet.skips + \
        fleet.center.restarts * fleet.n_workers // max(1, fleet.n_shards) \
        + 2
    ok = spread <= allowance
    return ("center_shard_load_balance", ok,
            f"per-shard applies {per}, spread {spread} "
            f"(allowance {allowance})")


def _lease_safety(fleet) -> Result:
    bad = fleet.lease_violations
    return ("lease_timeout_safe_region", not bad,
            "; ".join(bad[:4]) or "no false deaths, no late detections")


def _topology(fleet) -> Result:
    bad = fleet.mesh.table_violations
    return ("gossip_topology_regeneration", not bad,
            "; ".join(bad[:4]) or f"{fleet.mesh.regens} regenerations, "
            f"all embedded derangements valid")


def _completion(fleet) -> Result:
    ok = fleet.stopped_reason in (None,) and \
        len(fleet.finished) + len(fleet.failed) == fleet.n_workers
    return ("fleet_completed", ok,
            f"finished={len(fleet.finished)} failed={len(fleet.failed)} "
            f"of {fleet.n_workers}, stopped={fleet.stopped_reason}")


CHECKERS = (_completion, _no_resurrect, _alpha_conservation, _exactly_once,
            _straggler_stability, _shard_balance, _lease_safety, _topology)


def check_invariants(fleet) -> List[Result]:
    """Run every checker; the fleet must have finished ``run()``."""
    assert fleet.summary, "run() the fleet before checking invariants"
    return [c(fleet) for c in CHECKERS]
