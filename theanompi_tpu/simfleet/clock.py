"""The virtual clock: simfleet's half of the utils/clock.py seam.

The production default (:class:`~theanompi_tpu.utils.clock.WallClock`)
reads real time; this clock is *advanced by the event loop* — ``now()``
returns whatever the last processed event said it is.  Nothing in a
simulation ever sleeps: a ``sleep()`` here is a programming error (the
component should have scheduled an event instead), and raising loudly is
what keeps a 1,000-worker rehearsal inside seconds of CPU.
"""

from __future__ import annotations

try:
    from ..utils.clock import Clock
except ImportError:        # file-path load (jax-free tooling): absolute
    from theanompi_tpu.utils.clock import Clock


class VirtualClock(Clock):
    """Manually-advanced time.  The event loop owns ``advance_to``;
    everything else only reads ``now()``."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        assert t >= self._now, \
            f"virtual time went backwards: {t} < {self._now}"
        self._now = float(t)

    def sleep(self, dt: float) -> None:
        raise RuntimeError(
            "VirtualClock.sleep(): a simulated component tried to block — "
            "schedule an event instead (nothing sleeps in virtual time)")
