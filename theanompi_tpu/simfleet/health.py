"""Simulated metric streams through the REAL fleet-health plane.

The §20 rule engine must be rehearsable at width without hardware: this
module wires a :class:`~theanompi_tpu.utils.fleetmon.FleetCollector` —
the PRODUCTION collector and rule engine, not a stand-in — into a
:class:`~theanompi_tpu.simfleet.fleet.FleetSim` run on the fleet's
virtual clock.  The simulated metric stream mirrors what the live
emitters send:

* every lease beat doubles as a snapshot arrival (the live
  ``MetricStreamer`` runs at its own cadence whatever the hot loop
  does; the sim's ``BEAT_EVERY_S`` events are exactly that cadence), so
  the derived ``heartbeat_age_s`` series sees kills and wedges with no
  cooperation from the dying worker;
* a completed exchange round lands one ``step_p99`` sample (round
  duration — compute AND wire, like the live phase brackets);
* every wire retry bumps the rank's CUMULATIVE ``wire_retries`` series
  (the live ``wire.retry`` counter the snapshot carries), which the
  ``wire_degraded`` rate-of-change rule turns into fault-window-shaped
  episodes — it clears when the retries stop, so successive net faults
  each get their own alert.

Alerts fire through the real episode/hysteresis logic and are appended
to the fleet's canonical event log, so the §18 determinism contract
extends to the health plane: same seed ⇒ byte-identical alert log, and
a seeded fault schedule raises exactly the expected alert set with no
flapping (tests/test_fleetmon.py pins both).
"""

from __future__ import annotations

from typing import Optional, Sequence

try:
    from ..utils import telemetry
    from ..utils.fleetmon import FleetCollector
except ImportError:        # file-path load: absolute
    from theanompi_tpu.utils import telemetry
    from theanompi_tpu.utils.fleetmon import FleetCollector


def sim_rules(fleet) -> list:
    """The stock rehearsal rule set, scaled to the fleet's own timing
    parameters (a fixed absolute step-time threshold would mean nothing
    across configs): a heartbeat lost past the lease timeout, a round
    time sustained above 2× the jitter-ceiling expectation (a 4× delay
    straggler clears it, healthy jitter does not), and a wire retry
    burst (rate-of-change over the cumulative counter)."""
    expected_round = fleet.sync_freq * fleet.step_time_s * \
        (1.0 + fleet.step_jitter)
    return [
        {"name": "heartbeat_lost", "series": "heartbeat_age_s",
         "predicate": "threshold", "op": ">",
         "value": float(fleet.lease_timeout), "scope": "rank",
         "roles": ("worker",)},
        {"name": "step_time_degraded", "series": "step_p99",
         "predicate": "sustained", "op": ">",
         "value": 2.0 * expected_round,
         "window_s": float(fleet.straggle_window_s), "scope": "rank",
         "action": "demote", "roles": ("worker",)},
        {"name": "wire_degraded", "series": "wire_retries",
         "predicate": "rate_of_change", "op": ">", "value": 0.05,
         "window_s": 5.0, "scope": "rank", "roles": ("worker",)},
        # the §25 beacon: any nonzero divergence sample above float
        # noise is a replica that bit-desynced from the consensus — a
        # `corrupt` fault sets it orders of magnitude above this floor
        {"name": "replica_divergence", "series": "divergence",
         "predicate": "threshold", "op": ">", "value": 1e-6,
         "scope": "rank", "roles": ("worker",)},
    ]


class HealthPlane:
    """One collector + rule engine over a running :class:`FleetSim`.

    The fleet calls the three ``on_*`` hooks from its event handlers;
    :meth:`_tick` re-schedules itself on the fleet's event queue every
    ``eval_window_s`` virtual seconds — the same evaluation cadence the
    live :class:`~theanompi_tpu.utils.fleetmon.FleetMonServer` runs."""

    def __init__(self, fleet, rules: Optional[Sequence[dict]] = None,
                 eval_window_s: float = 2.0):
        self.fleet = fleet
        self.eval_window_s = float(eval_window_s)
        self._retries: dict = {}            # wid -> cumulative count
        self.collector = FleetCollector(
            rules=sim_rules(fleet) if rules is None else rules,
            eval_window_s=self.eval_window_s,
            telemetry_=telemetry.DISABLED, clock=fleet.vclock,
            on_alert=self._on_alert)

    # -- alert sink ---------------------------------------------------------

    def _on_alert(self, alert: dict) -> None:
        self.fleet.log.append(self.fleet.vclock.now(), "alert",
                              rule=alert["rule"], series=alert["series"],
                              scope=alert["scope"], worker=alert["rank"],
                              value=round(float(alert["value"]), 6))

    # -- the simulated metric stream ----------------------------------------

    def on_beat(self, wid: int, status: str, steps: int) -> None:
        # every snapshot carries the cumulative retry count — the rate
        # rule needs steady baseline samples to measure a burst against
        self.collector.ingest(
            {"steps": float(steps),
             "wire_retries": float(self._retries.get(wid, 0))},
            rank=wid, role="worker", status=status)

    def on_round(self, wid: int, duration_s: float,
                 divergence: Optional[float] = None) -> None:
        sample = {"step_p99": float(duration_s),
                  "wire_retries": float(self._retries.get(wid, 0))}
        if divergence is not None:
            # every round carries the current beacon spread (0.0 when
            # healthy) so the replica_divergence episode can CLEAR once
            # the corruption is pulled back toward the center
            sample["divergence"] = float(divergence)
        self.collector.ingest(sample, rank=wid, role="worker")

    def on_wire_retry(self, wid: int) -> None:
        n = self._retries.get(wid, 0) + 1
        self._retries[wid] = n
        self.collector.ingest({"wire_retries": float(n)}, rank=wid,
                              role="worker")

    # -- evaluation loop ----------------------------------------------------

    def _tick(self) -> None:
        if self.fleet.stopped_reason:
            return
        self.collector.evaluate()
        if not self.fleet._alldone():
            self.fleet.queue.push(
                self.fleet.vclock.now() + self.eval_window_s, self._tick)

    def install(self) -> None:
        """Schedule the first evaluation (called from ``FleetSim.run``)."""
        self.fleet.queue.push(self.eval_window_s, self._tick)

    # -- summary ------------------------------------------------------------

    def summary(self) -> dict:
        by_rule: dict = {}
        for a in self.collector.alerts:
            by_rule[a["rule"]] = by_rule.get(a["rule"], 0) + 1
        return {"alerts": len(self.collector.alerts),
                "by_rule": dict(sorted(by_rule.items())),
                "evaluations": self.collector.evaluations}
