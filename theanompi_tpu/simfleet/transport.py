"""The simulated wire: chaos-scheduled fault windows over virtual frames,
and the sharded center those frames land on.

**Transport.**  No sockets — a request is resolved as pure arithmetic
over virtual time: sample a one-way latency, ask the REAL
window-membership rule (:func:`theanompi_tpu.utils.chaos
.fault_window_active`, the same function the live :class:`ChaosProxy`
routes by) which fault windows cover the frame at its delivery and
reply instants, and produce the exact client-observable outcomes the
proxy produces on real TCP:

* ``net_drop`` / ``net_partition`` at delivery — the frame evaporates;
  the client sees silence and times out (``lost``).
* ``net_delay`` — the frame stalls ``NET_DELAY_PER_FRAME_S`` (the
  proxy's knob, imported not copied) before the server sees it.
* ``net_corrupt`` — the server's CRC rejects it *before* the dedup
  window is consulted (mirroring ``center_server``'s handler order);
  the client gets a retryable error reply.
* ``net_dup`` — the server is hit TWICE; the duplicate's reply is
  swallowed (the client sent one frame, it sees one reply) — the twin
  lands on the dedup window, which is the point.
* ``net_partition`` at reply time — the op APPLIED but the ack is lost:
  the client times out and retries an op that landed, the
  exactly-once case that justifies the whole token machinery.

**Center.**  K shards (ROADMAP item 4b's sharded-center shape), each
with its own REAL :class:`~theanompi_tpu.parallel.wire.DedupWindow`.
Every apply is checked against a per-worker applied-seq high-water mark
— client streams are strictly sequential, so ANY re-application
surfaces as a ledger violation, O(1) memory at 1,000-client width.
``kill@t:0`` restarts the center: windows snapshot/restore through the
real crash-recovery path (in-flight claims dropped, HWMs kept) while
requests during the outage are lost and ridden out on retries.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

try:
    from ..parallel.wire import INFLIGHT, DedupWindow
    from ..utils import telemetry
    from ..utils.chaos import (NET_DELAY_PER_FRAME_S, NET_FAULT_KINDS,
                               fault_window_active)
except ImportError:        # file-path load: absolute
    from theanompi_tpu.parallel.wire import INFLIGHT, DedupWindow
    from theanompi_tpu.utils import telemetry
    from theanompi_tpu.utils.chaos import (NET_DELAY_PER_FRAME_S,
                                           NET_FAULT_KINDS,
                                           fault_window_active)


class SimTransport:
    """Resolve framed request/reply round-trips in virtual time.

    ``request()`` returns ``(status, verdict, t_done)``:

    * ``("ok", <server verdict>, t_reply)`` — reply in hand at t_reply;
    * ``("retry", "corrupt", t_reply)`` — retryable error reply (CRC);
    * ``("lost", None, t_timeout)`` — silence; the client's op timeout
      expires at ``t_timeout``.
    """

    def __init__(self, clock, rng, schedule=(), *, center=None,
                 latency_s: float = 0.004,
                 latency_jitter: float = 0.5, op_timeout_s: float = 3.0):
        self.clock = clock
        self.rng = rng
        self.center = center
        self.schedule = [f for f in (schedule or ())
                         if f.kind in NET_FAULT_KINDS]
        self.latency_s = float(latency_s)
        self.latency_jitter = float(latency_jitter)
        self.op_timeout_s = float(op_timeout_s)
        self.frames_faulted: Dict[str, int] = {}
        self.dup_applied = 0       # duplicated frames a LIVE center saw
        # per-kind sub-schedules with coarse [lo, hi] bounds: a frame
        # outside a kind's span pays two comparisons, and the membership
        # verdict itself still comes from the REAL fault_window_active
        # rule over that kind's faults (filtering by kind first is
        # exactly what the rule does anyway)
        self._by_kind: Dict[str, tuple] = {}
        for kind in NET_FAULT_KINDS:
            fs = [f for f in self.schedule if f.kind == kind]
            if fs:
                self._by_kind[kind] = (
                    fs, min(f.at for f in fs),
                    max(f.at + f.duration for f in fs))

    def _count(self, kind: str) -> None:
        self.frames_faulted[kind] = self.frames_faulted.get(kind, 0) + 1

    def _window(self, kind: str, worker: int, t: float) -> bool:
        sub = self._by_kind.get(kind)
        if sub is None or t < sub[1] or t > sub[2]:
            return False
        return fault_window_active(sub[0], kind, worker, t)

    def _lat(self) -> float:
        j = self.latency_jitter
        return self.latency_s * (1.0 - j + 2.0 * j * self.rng.random())

    def request_push(self, worker: int, shard: int,
                     seq: int) -> Tuple[str, Optional[str], float]:
        """One round-trip for ``worker``'s push to ``shard``."""
        t_send = self.clock.now()
        t_deliver = t_send + self._lat()
        t_lost = t_send + self.op_timeout_s
        if self._window("net_partition", worker, t_deliver):
            self._count("net_partition")
            return "lost", None, t_lost
        if self._window("net_drop", worker, t_deliver):
            self._count("net_drop")
            return "lost", None, t_lost
        if self._window("net_delay", worker, t_deliver):
            self._count("net_delay")
            t_deliver += NET_DELAY_PER_FRAME_S
        if self._window("net_corrupt", worker, t_deliver):
            # CRC verdict precedes the dedup window server-side: a
            # corrupted frame never claims a token
            self._count("net_corrupt")
            return "retry", "corrupt", t_deliver + self._lat()
        center = self.center
        down = center.is_down(t_deliver)
        verdict = None if down else center.apply_push(shard, worker, seq)
        if self._window("net_dup", worker, t_deliver):
            # the duplicate hits the server too; its reply is swallowed.
            # frames_faulted counts the frame (proxy parity) whether or
            # not the center was up; dup_applied counts only twins that
            # actually REACHED a live center — the denominator the
            # dedup invariant is entitled to
            self._count("net_dup")
            if not down:
                center.apply_push(shard, worker, seq)
                self.dup_applied += 1
        if down:
            return "lost", None, t_lost        # outage: the frame dies
        t_reply = t_deliver + self._lat()
        if self._window("net_partition", worker, t_reply):
            # applied, ack lost — the retry-of-a-landed-op case
            self._count("net_partition")
            return "lost", None, t_lost
        return "ok", verdict, t_reply


class SimShard:
    """One center shard: a real DedupWindow plus the exactly-once ledger."""

    def __init__(self, idx: int, dedup_depth: int = 64):
        self.idx = int(idx)
        self.window = DedupWindow(depth=dedup_depth,
                                  telemetry_=telemetry.DISABLED)
        self.applied_hwm: Dict[int, int] = {}      # worker -> max applied seq
        self.applied_by_worker: Dict[int, int] = {}
        self.dropped_by_worker: Dict[int, int] = {}
        self.applied_total = 0
        self.violations: List[Tuple[int, int]] = []  # (worker, seq) reapplied


class SimCenter:
    """K shards behind one membership surface — the object the REAL
    :class:`~theanompi_tpu.parallel.membership.CenterReactor` drives.
    ``demote_island``/``readmit_island`` follow ElasticCenter semantics:
    a demoted island's pushes are dropped-but-acked on every shard, its
    pulls (not modeled) would still serve."""

    def __init__(self, n_shards: int = 2, dedup_depth: int = 64):
        assert n_shards >= 1
        self.shards = [SimShard(i, dedup_depth) for i in range(n_shards)]
        self.demoted: set = set()
        self.down_until: float = -1.0          # center outage (kill@t:0)
        self.restarts = 0

    # -- the CenterReactor surface ------------------------------------------

    def demote_island(self, island: int) -> None:
        self.demoted.add(int(island))

    def readmit_island(self, island: int) -> None:
        self.demoted.discard(int(island))

    # -- outage / crash recovery --------------------------------------------

    def crash_and_restore(self, now: float, outage_s: float) -> None:
        """Kill the center and bring it back from snapshot after
        ``outage_s``: every shard's dedup window round-trips through the
        REAL snapshot/restore (in-flight claims dropped, applied tokens
        and HWMs kept) — the §15 crash-recovery semantics at width."""
        self.restarts += 1
        self.down_until = now + float(outage_s)
        for sh in self.shards:
            snap = sh.window.snapshot()
            sh.window = DedupWindow(depth=sh.window.depth,
                                    telemetry_=telemetry.DISABLED)
            sh.window.restore(snap)

    def is_down(self, t: float) -> bool:
        return t < self.down_until

    # -- the push op ---------------------------------------------------------

    def apply_push(self, shard_idx: int, worker: int, seq: int) -> str:
        """One mutating op on one shard: dedup check → demote drop →
        apply, with the exactly-once ledger audited on the way."""
        sh = self.shards[shard_idx]
        tok = {"w": f"w{worker}", "seq": int(seq)}
        dup, cached = sh.window.check(tok, "push")
        if dup:
            # the sim applies atomically, so a claim can never still be
            # in flight — an INFLIGHT here is itself a violation
            if cached is INFLIGHT:
                sh.violations.append((int(worker), int(seq)))
            return "dedup"
        if int(worker) in self.demoted:
            sh.dropped_by_worker[int(worker)] = \
                sh.dropped_by_worker.get(int(worker), 0) + 1
            sh.window.record(tok, "push", {"ok": True, "dropped": True})
            return "dropped"
        last = sh.applied_hwm.get(int(worker), -1)
        if int(seq) <= last:
            sh.violations.append((int(worker), int(seq)))
        else:
            sh.applied_hwm[int(worker)] = int(seq)
        sh.applied_total += 1
        sh.applied_by_worker[int(worker)] = \
            sh.applied_by_worker.get(int(worker), 0) + 1
        sh.window.record(tok, "push", {"ok": True})
        return "applied"

    # -- views ---------------------------------------------------------------

    def stats(self) -> dict:
        return {
            "shards": len(self.shards),
            "applied_per_shard": [sh.applied_total for sh in self.shards],
            "dedup_hits_per_shard": [sh.window.hits for sh in self.shards],
            "violations": sum(len(sh.violations) for sh in self.shards),
            "restarts": self.restarts,
            "demoted": sorted(self.demoted),
        }
