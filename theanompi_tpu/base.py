"""Process/runtime core.

TPU-native rebuild of Theano-MPI's ``theanompi/lib/base.py``
(SURVEY.md §2.1): the reference's ``MPI_GPU_Process`` did MPI init
(``MPI.COMM_WORLD`` rank/size), GPU device binding via ``THEANO_FLAGS``, and
model import by dotted ``modelfile`` string + ``modelclass`` name, building
the shared ``config`` dict handed to models.

Here one Python process per HOST drives all its local chips; "rank/size" map
to ``jax.process_index()`` / the worker-mesh extent; device binding is
unnecessary (XLA owns the chips); the communicator object is the named-axis
mesh from :mod:`theanompi_tpu.parallel.mesh`.  Method names are kept for
contract parity.
"""

from __future__ import annotations

import importlib
from typing import Optional

import jax

from .parallel.mesh import WORKER_AXIS, init_multihost, worker_mesh


def canonical_prng_impl(impl):
    """Normalize user-facing PRNG names to jax's ('threefry' is accepted as
    an alias for 'threefry2x32'). Shared by the worker path and bench.py."""
    return {"threefry": "threefry2x32"}.get(impl, impl)


class MeshProcess:
    """≙ reference ``MPI_GPU_Process``."""

    def __init__(self, config: Optional[dict] = None):
        self.config = dict(config or {})
        self.verbose: bool = self.config.get("verbose", True)
        self.mesh = None
        self.rank = 0
        self.size = 1

    def get_internode_comm(self):
        """Bring up the communicator (≙ MPI_Init + COMM_WORLD): multi-host
        control plane if configured, then the 1-D workers mesh."""
        platform = self.config.get("platform")
        if platform:
            # programmatic platform pin (config `platform=cpu`): the
            # JAX_PLATFORMS env var is not reliable under external PJRT
            # plugins, and launcher-spawned workers have no other hook
            jax.config.update("jax_platforms", platform)
        impl = canonical_prng_impl(self.config.get("prng_impl"))
        if impl:
            # 'rbg' uses the TPU hardware RNG for in-step randomness
            # (dropout, GAN z draws) — measurably cheaper than threefry on
            # AlexNet-sized dropout; default stays threefry (jax's default,
            # fully deterministic across backends).
            jax.config.update("jax_default_prng_impl", impl)
        init_multihost(
            coordinator_address=self.config.get("coordinator_address"),
            num_processes=self.config.get("num_processes"),
            process_id=self.config.get("process_id"),
        )
        # tp>1 (tensor parallelism, parallel/tp.py): n_workers counts
        # data-parallel GROUPS; the mesh gains a 'model' axis and each group
        # spans tp chips.  rank/size semantics (and the data sharding they
        # drive) stay data-parallel.
        self.mesh = worker_mesh(self.config.get("n_workers"),
                                tp=int(self.config.get("tp", 1)),
                                pp=int(self.config.get("pp", 1)),
                                sp=int(self.config.get("sp", 1)))
        self.rank = jax.process_index()
        self.size = self.mesh.shape[WORKER_AXIS]
        self.config.update(rank=self.rank, size=self.size, mesh=self.mesh,
                           verbose=self.verbose and self.rank == 0)
        return self.mesh

    def init_device(self):
        """No-op on TPU (the reference bound THEANO_FLAGS=device=cudaN here);
        kept so session scripts written against the reference API run."""
        return jax.devices()

    def build_model(self, modelfile: str, modelclass: str):
        """Import the model by dotted module path + class name — identical
        contract to the reference's importlib-based model loading."""
        mod = importlib.import_module(modelfile)
        cls = getattr(mod, modelclass)
        return cls(self.config)
