"""Elastic membership: heartbeat leases, straggler demotion, rule-aware
reactions.

``launcher --supervise`` restarts a dead WORLD; production TPU pods lose
*individual* hosts to preemption as a routine event (PAPERS.md, 2204.06514).
The async rules were built for exactly this — EASGD/ASGD/GoSGD tolerate
workers arriving late or dropping out because their algebra is per-worker
push-pull/gossip, not a world-sized barrier (PAPERS.md, 1605.08325) — so
this module promotes the supervisor into an **elastic membership
controller**:

* **Leases** — each worker heartbeats a small JSON lease file
  (``<lease_dir>/lease_w{id}.json``, atomic tmp+rename) and mirrors the
  beat into the telemetry event stream as the ``heartbeat.iter`` gauge.
  A lease older than ``lease_timeout`` means the worker is dead or wedged
  (a SIGSTOPped process stops beating without exiting — the chaos
  harness's ``stop`` fault).
* **:class:`MembershipController`** — the worker-state machine: ``poll()``
  folds lease files and process observations into ``worker_join`` /
  ``worker_leave`` / ``worker_demote`` transitions (each one telemetry
  event + reactor callbacks), and ``check_stragglers()`` closes the loop
  with ``scripts/telemetry_report.py``'s windowed straggler ranking —
  a rank that straggles ``straggle_windows`` windows is demoted from the
  active set instead of dragging the run.
* **Reactors** — the rule reaction matrix (docs/design.md §14):
  :class:`CenterReactor` demotes/readmits islands at the EASGD/ASGD
  center (a demoted island's pushes are dropped, its pulls still serve so
  it can keep training locally and recover); :class:`MeshReactor` drives
  an in-mesh exchanger's ``set_active_ranks`` (GoSGD gossip topologies
  regenerated without the demoted rank, EASGD/ASGD collective masks).
  BSP has no shrink reaction — a membership change there is a supervised
  bounded-backoff world restart resuming at the committed window cursor
  (``launcher --supervise``).
* **:class:`ElasticSupervisor`** — spawns worker subprocesses, detects
  death (exit OR lease expiry), respawns with :class:`Backoff` (bounded
  exponential + jitter — the bench probe-recovery pattern), and trips a
  :class:`CrashLoopBreaker` when failures cluster.  A rejoining worker
  restores params from the center (``center_restore``), hits the AOT
  cache, and re-enters at a window boundary.

Module-scope imports are stdlib-only (the tpulint schema-drift checker
probes the membership event vocabulary from a jax-free process); jax and
the trainer machinery import lazily inside the worker entry points.

Every time comparison that DECIDES something (lease freshness, backoff
due-times, crash-loop windows, straggler horizons) goes through the
injectable clock seam (``utils/clock.py``, docs/design.md §18): real
runs keep wall time via the :data:`~theanompi_tpu.utils.clock.WALL`
default, while ``theanompi_tpu.simfleet`` drives this exact state
machine with a virtual clock at 1,000-worker width.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

try:
    from ..utils import telemetry, tracing
    from ..utils.clock import WALL
except ImportError:        # file-path load (jax-free lint probe): absolute
    from theanompi_tpu.utils import telemetry, tracing
    from theanompi_tpu.utils.clock import WALL

# The membership transition vocabulary — consumed by
# scripts/telemetry_report.py (instant markers in the Perfetto export) and
# pinned by the tpulint schema-drift checker.  A readmitted straggler
# re-enters via ``worker_join`` with ``reason='readmit'``.
MEMBERSHIP_EVENTS = ("worker_join", "worker_leave", "worker_demote")

# The center-outage event pair (round 14): the supervisor emits
# ``center_down`` when the supervised center process dies (or its lease
# expires while wedged) and ``center_restored`` when the respawned center
# answers on its fixed port again — the chaos gate matches the pair, the
# workers ride the gap out on wire retries (parallel/wire.py).
CENTER_EVENTS = ("center_down", "center_restored")

# Heartbeat gauge keys a WorkerLease.beat mirrors into the telemetry
# stream (rendered as a per-rank counter track by the trace export).
HEARTBEAT_GAUGES = ("heartbeat.iter",)


# -- leases ------------------------------------------------------------------

def lease_path(lease_dir: str, worker_id: int) -> str:
    return os.path.join(lease_dir, f"lease_w{int(worker_id)}.json")


def read_leases(lease_dir: str) -> Dict[int, dict]:
    """All parseable lease docs, keyed by worker id.  A torn write can't
    occur (writes are atomic) but a foreign/garbage file is skipped."""
    out: Dict[int, dict] = {}
    if not lease_dir or not os.path.isdir(lease_dir):
        return out
    for name in os.listdir(lease_dir):
        if not (name.startswith("lease_w") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(lease_dir, name)) as f:
                doc = json.load(f)
            out[int(doc["worker"])] = doc
        except (ValueError, KeyError, OSError):
            continue
    return out


class WorkerLease:
    """Worker-side half of the lease contract: ``beat()`` refreshes the
    lease file atomically and mirrors the step into the telemetry stream
    (one ``gauges`` event carrying :data:`HEARTBEAT_GAUGES`); ``release()``
    marks a CLEAN departure so the controller reports ``finished`` instead
    of a lease expiry.

    Safe to call every iteration: a beat within ``min_interval_s`` of the
    last write is one ``time.time()`` check and nothing else (no file
    write, no event), so the hot loop can beat wherever it already beats
    the watchdog without a per-step I/O cost.  Status changes always
    write."""

    def __init__(self, lease_dir: str, worker_id: int, telemetry_=None,
                 min_interval_s: float = 2.0, clock=None):
        self.lease_dir = str(lease_dir)
        self.worker_id = int(worker_id)
        self.telemetry = telemetry_ if telemetry_ is not None \
            else telemetry.active()
        self.min_interval_s = float(min_interval_s)
        self.clock = clock or WALL
        os.makedirs(self.lease_dir, exist_ok=True)
        self._step = 0
        # -inf, not 0.0: under a virtual clock the epoch IS ~0, and a
        # 0.0 sentinel would throttle away the very first beat
        self._last_write = -float("inf")

    def beat(self, step: Optional[int] = None, status: str = "live",
             **extra) -> None:
        if step is not None:
            self._step = int(step)
        now = self.clock.now()
        if status == "live" and not extra and \
                now - self._last_write < self.min_interval_s:
            return
        self._last_write = now
        # full-precision ts: the controller's dead_ts guard compares this
        # against a later time.time() — rounding could order an immediate
        # respawn's first beat "before" the death it follows
        doc = {"worker": self.worker_id, "pid": os.getpid(),
               "ts": now, "step": self._step,
               "status": status}
        doc.update(extra)
        path = lease_path(self.lease_dir, self.worker_id)
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            pass                       # a heartbeat must never kill training
        tm = self.telemetry
        if tm.enabled:
            tm.gauge("heartbeat.iter", self._step)
            tm.event("gauges", **{"heartbeat.iter": self._step})

    def release(self) -> None:
        self.beat(status="left")


# -- backoff / crash-loop breaker -------------------------------------------

class Backoff:
    """Bounded exponential backoff + jitter (the bench probe-recovery
    pattern, PR 2): ``base·factor^attempt`` capped at ``cap``, scaled by a
    uniform ``1 ± jitter`` draw so fleet-mates restarting against the same
    dead resource don't retry in lockstep.

    The jitter draw is reproducible two ways: ``seed`` makes this
    instance's stream deterministic on its own, and ``rng`` injects a
    SHARED ``random.Random`` so a whole rehearsal (simfleet, the chaos
    tests) draws every respawn delay from one seeded stream.  Default
    (neither): a fresh unseeded stream — behavior unchanged."""

    def __init__(self, base: float = 1.0, factor: float = 2.0,
                 cap: float = 30.0, jitter: float = 0.25, seed=None,
                 rng=None):
        import random
        self.base = float(base)
        self.factor = float(factor)
        self.cap = float(cap)
        self.jitter = float(jitter)
        assert rng is None or seed is None, \
            "Backoff takes seed= OR rng=, not both"
        self._rng = rng if rng is not None else random.Random(seed)

    def delay(self, attempt: int) -> float:
        d = min(self.base * (self.factor ** max(0, int(attempt))), self.cap)
        return d * (1.0 - self.jitter + 2.0 * self.jitter * self._rng.random())


class CrashLoopBreaker:
    """``limit`` failures inside a trailing ``window_s`` window mean the
    failure is systemic (bad config, poisoned checkpoint, dead backend) —
    retrying forever just hides it.  ``record_failure()`` returns True when
    the breaker trips; the caller exits nonzero with the flight-recorder
    tail printed."""

    def __init__(self, limit: int = 5, window_s: float = 300.0,
                 clock=None):
        self.limit = int(limit)
        self.window_s = float(window_s)
        self.clock = clock or WALL
        self._times: deque = deque()

    def record_failure(self, now: Optional[float] = None) -> bool:
        now = self.clock.now() if now is None else now
        self._times.append(now)
        while self._times and now - self._times[0] > self.window_s:
            self._times.popleft()
        return len(self._times) >= self.limit


def flight_tail_lines(record_dir: str, n: int = 12) -> List[str]:
    """The last ``n`` events of the NEWEST flight recording under
    ``record_dir`` (crash sweeps included), formatted one per line — what a
    crash-loop exit prints so the death isn't silent."""
    import glob
    paths = (glob.glob(os.path.join(record_dir, "flight_rank*.jsonl")) +
             glob.glob(os.path.join(record_dir, "crash_*",
                                    "flight_rank*.jsonl")))
    if not paths:
        return []
    newest = max(paths, key=os.path.getmtime)
    lines: List[str] = [f"flight tail ({newest}):"]
    try:
        with open(newest) as f:
            raw = [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return []
    for ln in raw[-n:]:
        try:
            ev = json.loads(ln)
        except ValueError:
            continue
        detail = {k: v for k, v in ev.items()
                  if k not in ("ts", "run", "rank", "ev")}
        lines.append(f"  ts={ev.get('ts')} rank={ev.get('rank')} "
                     f"{ev.get('ev')} {detail}")
    return lines


# -- reactors (the rule reaction matrix) ------------------------------------

class Reactor:
    """Rule-side hooks the controller drives on each transition.  The base
    is all no-ops so a controller can run observation-only."""

    def on_join(self, worker: int, info: dict) -> None:
        pass

    def on_leave(self, worker: int, info: dict) -> None:
        pass

    def on_demote(self, worker: int, info: dict) -> None:
        pass

    def on_readmit(self, worker: int, info: dict) -> None:
        pass


class CenterReactor(Reactor):
    """EASGD/ASGD shrink without stopping: a left/demoted island's pushes
    are DROPPED at the center (zombie pushes from a half-dead process can't
    pollute it) while pulls still serve — the island keeps training locally
    and, on readmit/rejoin, restores from the center and re-enters.

    Works against an in-process :class:`~.async_easgd.ElasticCenter` or a
    :class:`~.center_server.RemoteCenter`.  A remote op failing because
    the center is DOWN (the supervisor may be mid-respawn of that very
    center) is remembered, not raised — the supervisor's tick calls
    :meth:`flush_pending` so the latest intended state lands once the
    center answers again."""

    def __init__(self, center):
        self.center = center
        self._pending: Dict[int, str] = {}    # island -> demote | readmit

    def _call(self, island: int, what: str) -> None:
        try:
            if what == "demote":
                self.center.demote_island(island)
            else:
                self.center.readmit_island(island)
            self._pending.pop(island, None)
        except ConnectionError as e:           # incl. wire.WireGiveUp
            if self._pending.get(island) != what:  # log intent once, not
                print(f"membership: center {what}({island}) deferred — "
                      f"center unreachable ({e!r})", file=sys.stderr,
                      flush=True)                  # every flush retry
            self._pending[island] = what       # latest intent wins

    def flush_pending(self) -> None:
        for island, what in list(self._pending.items()):
            self._call(island, what)

    def on_leave(self, worker, info):
        self._call(worker, "demote")

    def on_demote(self, worker, info):
        self._call(worker, "demote")

    def on_join(self, worker, info):
        self._call(worker, "readmit")

    def on_readmit(self, worker, info):
        self._call(worker, "readmit")


class MeshReactor(Reactor):
    """In-mesh (SPMD) shrink: regenerate the exchanger's peer topology
    without the demoted rank — GoSGD gossip draws route only among active
    ranks, EASGD/ASGD mask the demoted rank out of the center collective.
    When the exchange cadence is fused into the multi-step dispatch the
    model is recompiled so the in-scan body picks up the new topology (an
    AOT-cache hit makes that seconds, PR 3)."""

    def __init__(self, exchanger, model=None):
        self.exchanger = exchanger
        self.model = model
        self.demoted: set = set()

    def _apply(self) -> None:
        size = getattr(self.exchanger, "size", None)
        assert size, "MeshReactor needs a prepared exchanger"
        active = [r for r in range(size) if r not in self.demoted]
        self.exchanger.set_active_ranks(active)
        if getattr(self.exchanger, "fused", False):
            # the in-scan fused cadence embeds the OLD topology until the
            # model recompiles — skipping it silently would keep mixing
            # the demoted rank with no error
            assert self.model is not None, (
                "MeshReactor on a fused-cadence exchanger needs the model "
                "(MeshReactor(exchanger, model=...)) so the in-scan "
                "exchange body can be recompiled for the new active set")
            self.model.compile_iter_fns(self.exchanger)

    def on_demote(self, worker, info):
        self.demoted.add(int(worker))
        self._apply()

    def on_leave(self, worker, info):
        self.demoted.add(int(worker))
        self._apply()

    def on_join(self, worker, info):
        self.demoted.discard(int(worker))
        self._apply()

    def on_readmit(self, worker, info):
        self.demoted.discard(int(worker))
        self._apply()


# -- the controller ----------------------------------------------------------

_REPORT_MODULE: Any = None          # module-level cache: exec once/process


def _load_report_module():
    """``scripts/telemetry_report.py`` by FILE path (a script, not a
    package module; stdlib-only by contract) — the ONE windowed straggler
    ranking, not a re-implementation.  Cached after the first load (the
    supervisor polls it); None — with ONE stderr warning, since a silent
    None quietly disables straggler demotion — when absent/broken."""
    global _REPORT_MODULE
    if _REPORT_MODULE is not None:
        return _REPORT_MODULE if _REPORT_MODULE is not False else None
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "telemetry_report.py")
    import importlib.util
    try:
        spec = importlib.util.spec_from_file_location(
            "_membership_telemetry_report", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception as e:
        print(f"membership: scripts/telemetry_report.py unavailable "
              f"({e!r}) — straggler demotion disabled", file=sys.stderr)
        _REPORT_MODULE = False
        return None
    _REPORT_MODULE = mod
    return mod


class MembershipController:
    """The worker-state machine behind the elastic runtime.

    States per worker: ``live`` → (``demoted`` ⇄ ``live``) → ``dead`` /
    ``left``; every transition emits its :data:`MEMBERSHIP_EVENTS` event
    (tagged with the worker id and a reason) and fans out to the
    ``reactors``.  The controller is transport-agnostic: the
    :class:`ElasticSupervisor` feeds it process observations, ``poll()``
    folds in lease files, and in-process (SPMD) use drives
    ``demote``/``readmit`` directly from the straggler ranking."""

    def __init__(self, lease_dir: Optional[str] = None,
                 lease_timeout: float = 15.0, telemetry_=None,
                 reactors: Sequence[Reactor] = (),
                 record_dir: Optional[str] = None,
                 straggle_windows: int = 3,
                 straggle_window_s: float = 5.0,
                 min_active: int = 1, clock=None,
                 lease_source: Optional[Callable[[], Dict[int, dict]]]
                 = None):
        self.lease_dir = lease_dir
        # ``lease_source`` overrides WHERE lease docs come from, not what
        # they mean: poll() folds whatever mapping it returns with the
        # exact file-dir semantics.  simfleet feeds an in-memory table so
        # 1,000 virtual workers heartbeat without 1,000 files; real runs
        # leave it None and read lease_dir.
        self.lease_source = lease_source
        self.clock = clock or WALL
        self.lease_timeout = float(lease_timeout)
        self.telemetry = telemetry_ if telemetry_ is not None \
            else telemetry.active()
        self.reactors = list(reactors)
        self.record_dir = record_dir
        self.straggle_windows = int(straggle_windows)
        self.straggle_window_s = float(straggle_window_s)
        self.min_active = max(1, int(min_active))
        # worker id -> {"status", "last_ts", "step", "pid", "joins"}
        self.workers: Dict[int, dict] = {}
        self.transitions: List[Tuple[str, int, dict]] = []

    # -- transition plumbing ------------------------------------------------

    def _emit(self, event: str, worker: int, hook: str, **info) -> None:
        self.transitions.append((event, worker, info))
        tm = self.telemetry
        if tm.enabled:
            tm.event(event, worker=int(worker), **info)
        for r in self.reactors:
            getattr(r, hook)(worker, info)

    def _entry(self, worker: int) -> dict:
        return self.workers.setdefault(int(worker), {
            "status": "new", "last_ts": 0.0, "step": 0, "pid": None,
            "joins": 0})

    # -- explicit transitions (supervisor / in-mesh callers) ----------------

    def join(self, worker: int, pid: Optional[int] = None,
             reason: str = "spawn", now: Optional[float] = None) -> None:
        st = self._entry(worker)
        rejoin = st["joins"] > 0
        st.update(status="live",
                  last_ts=self.clock.now() if now is None else now,
                  pid=pid, joins=st["joins"] + 1)
        self._emit("worker_join", worker, "on_join",
                   reason=reason, rejoin=rejoin, pid=pid)

    def leave(self, worker: int, reason: str = "exit",
              now: Optional[float] = None, **info) -> None:
        st = self._entry(worker)
        if st["status"] in ("dead", "left"):
            return
        st["status"] = "left" if reason == "finished" else "dead"
        # lease docs written BEFORE this death must not resurrect the
        # worker (a killed process's last beat can still be 'fresh')
        st["dead_ts"] = self.clock.now() if now is None else now
        self._emit("worker_leave", worker, "on_leave", reason=reason, **info)

    def demote(self, worker: int, reason: str = "straggler", **info) -> bool:
        st = self._entry(worker)
        if st["status"] != "live":
            return False
        if len(self.active_ranks()) - 1 < self.min_active:
            return False           # never demote below the active floor
        st["status"] = "demoted"
        self._emit("worker_demote", worker, "on_demote",
                   reason=reason, **info)
        return True

    def readmit(self, worker: int, reason: str = "readmit") -> None:
        st = self._entry(worker)
        if st["status"] != "demoted":
            return
        st["status"] = "live"
        # readmission forgives history: the cumulative ranking kept
        # charging this worker while it was demoted, so the NEXT
        # check_stragglers must re-baseline before judging it — without
        # this a readmitted worker is instantly re-demoted on stale
        # evidence (flapping, first demonstrated by a 1,000-worker
        # simfleet rehearsal)
        st["straggle_forgive"] = True
        self._emit("worker_join", worker, "on_readmit",
                   reason=reason, rejoin=True, pid=st.get("pid"))

    # -- center outage pair (the center is not a worker: no state-machine
    # entry, no reactor fan-out — just the audited event pair) -------------

    def center_down(self, reason: str = "crashed", **info) -> None:
        self.transitions.append(("center_down", -1, dict(info,
                                                         reason=reason)))
        tm = self.telemetry
        if tm.enabled:
            tm.event("center_down", reason=reason, **info)

    def center_restored(self, **info) -> None:
        self.transitions.append(("center_restored", -1, dict(info)))
        tm = self.telemetry
        if tm.enabled:
            tm.event("center_restored", **info)

    # -- lease polling ------------------------------------------------------

    def poll(self, now: Optional[float] = None) -> List[Tuple[str, int, dict]]:
        """Fold the lease files into transitions: a fresh lease from an
        unknown (or previously dead) worker is a join; a ``left`` status is
        a clean finish; a lease older than ``lease_timeout`` is a death —
        covers both crashed AND wedged (SIGSTOPped) workers, which stop
        beating without exiting.  Returns the transitions this poll made."""
        if not (self.lease_dir or self.lease_source):
            return []
        now = self.clock.now() if now is None else now
        leases = self.lease_source() if self.lease_source is not None \
            else read_leases(self.lease_dir)
        before = len(self.transitions)
        for wid, doc in sorted(leases.items()):
            st = self.workers.get(wid)
            fresh = now - float(doc.get("ts", 0)) <= self.lease_timeout
            if doc.get("status") == "left":
                if st is not None and st["status"] in ("live", "demoted"):
                    self.leave(wid, reason="finished", now=now)
                continue
            if st is None or st["status"] in ("dead", "left", "new"):
                if fresh and (st is None or
                              float(doc.get("ts", 0)) > st.get("dead_ts", 0)):
                    self.join(wid, pid=doc.get("pid"), reason="lease",
                              now=now)
                continue
            if fresh:
                st["last_ts"] = float(doc["ts"])
                st["step"] = int(doc.get("step", st["step"]))
        for wid, st in self.workers.items():
            if st["status"] in ("live", "demoted") and \
                    now - st["last_ts"] > self.lease_timeout:
                self.leave(wid, reason="lease_expired", now=now,
                           age=round(now - st["last_ts"], 1))
        return self.transitions[before:]

    # -- straggler loop -----------------------------------------------------

    def straggler_ranking(self) -> List[dict]:
        """The windowed ranking from ``scripts/telemetry_report.py`` over
        this run's merged per-rank streams (``record_dir``).  When the
        streams carry causal-tracing ``span`` events (§17), the report's
        root-cause table is computed from the SAME parse and stashed for
        :meth:`check_stragglers` to cite in its demote events."""
        mod = _load_report_module()
        if mod is None or not self.record_dir:
            return []
        events = mod.load_events(self.record_dir)
        rc_fn = getattr(mod, "straggler_root_cause", None)
        if rc_fn is not None:
            try:
                # RECENT windows only: the citation must name what
                # dominated the rounds that are TRIGGERING the demotion,
                # not the whole run's average (a worker can be compute-
                # bound for an hour, then queue-bound into its demotion)
                # — and assembling only the recent slice bounds the
                # per-poll trace cost on long runs.  The cumulative
                # ranking below still reads the FULL stream by contract
                # (windows_straggled/straggle_base count since run
                # start).
                horizon = self.clock.now() - \
                    4 * max(1, self.straggle_windows) * \
                    self.straggle_window_s
                recent = [e for e in events
                          if e.get("ts", 0) >= horizon]
                self._root_cause = rc_fn(recent, self.straggle_window_s)
            except Exception:
                self._root_cause = {}
        return mod.straggler_ranking(events, self.straggle_window_s)

    def check_stragglers(self, ranking: Optional[List[dict]] = None
                         ) -> List[int]:
        """Demote every live rank charged ≥ ``straggle_windows`` straggles
        by the windowed ranking (injectable for tests; sourced from the
        telemetry streams otherwise).  Single-rank rankings are ignored —
        with no peer to compare against, 'slowest' is meaningless.

        When the run carries distributed traces, each demote event cites
        the straggler root-cause table's verdict for that worker — WHICH
        component (compute | stage | wire | queue | apply) dominated its
        rounds — so 'demoted: straggler' comes with a cause, not just a
        symptom."""
        ranking = self.straggler_ranking() if ranking is None else ranking
        if len(ranking) < 2:
            return []
        root_cause = getattr(self, "_root_cause", {}) or {}
        demoted: List[int] = []
        for row in ranking:
            wid = int(row["rank"])
            ws = int(row.get("windows_straggled", 0))
            # the ranking is CUMULATIVE over the run: judge a worker on the
            # windows straggled SINCE its last demotion, or a readmitted
            # (recovered) worker would be instantly re-demoted forever on
            # the evidence that got it demoted the first time
            st = self.workers.get(wid, {})
            if st.get("straggle_forgive"):
                st["straggle_base"] = ws
                st["straggle_forgive"] = False
            base = st.get("straggle_base", 0)
            if ws - base < self.straggle_windows:
                continue
            cause = root_cause.get(wid) or root_cause.get(str(wid)) or {}
            if self.demote(wid, reason="straggler", windows_straggled=ws,
                           mean_train_secs=row.get("mean_train_secs"),
                           component=cause.get("dominant")):
                self.workers[wid]["straggle_base"] = ws
                demoted.append(wid)
        return demoted

    # -- views --------------------------------------------------------------

    def active_ranks(self) -> List[int]:
        return sorted(w for w, st in self.workers.items()
                      if st["status"] == "live")

    def status(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {"live": [], "demoted": [], "dead": [],
                                     "left": []}
        for w, st in sorted(self.workers.items()):
            out.setdefault(st["status"], []).append(w)
        return out


# -- the elastic supervisor --------------------------------------------------

class ElasticSupervisor:
    """Spawn/monitor/respawn elastic worker subprocesses around a
    :class:`MembershipController`.

    ``cmd_for(worker_id, attempt)`` builds the worker's argv (attempt 0 is
    the first spawn; respawns pass the attempt count so the command can add
    e.g. ``resume=true``).  A worker exiting 0 is finished; any other death
    (nonzero exit, signal, lease expiry while the process is wedged) is a
    ``worker_leave`` followed — after :class:`Backoff` — by a respawn and
    ``worker_join``, up to ``max_restarts`` per worker.  Failures clustering
    inside the :class:`CrashLoopBreaker` window stop the world with the
    flight-recorder tail printed."""

    #: the supervised center's id in chaos schedules and lease files —
    #: worker ids are 1-based in ``run_elastic``, so 0 is free
    CENTER_ID = 0

    def __init__(self, cmd_for: Callable[[int, int], List[str]],
                 worker_ids: Sequence[int], lease_dir: str, *,
                 record_dir: Optional[str] = None,
                 lease_timeout: float = 15.0, poll_s: float = 0.25,
                 backoff: Optional[Backoff] = None, max_restarts: int = 3,
                 crash_limit: int = 5, crash_window_s: float = 120.0,
                 telemetry_=None, reactors: Sequence[Reactor] = (),
                 straggle_windows: int = 0, straggle_poll_s: float = 10.0,
                 center_cmd_for: Optional[Callable[[int], List[str]]] = None,
                 center_addr: Optional[str] = None,
                 center_max_restarts: int = 5,
                 center_lease_dir: Optional[str] = None,
                 verbose: bool = True, clock=None, fleetmon=None):
        self.cmd_for = cmd_for
        self.worker_ids = [int(w) for w in worker_ids]
        self.lease_dir = lease_dir
        self.record_dir = record_dir
        self.poll_s = float(poll_s)
        self.clock = clock or WALL
        self.backoff = backoff or Backoff()
        self.max_restarts = int(max_restarts)
        self.breaker = CrashLoopBreaker(crash_limit, crash_window_s,
                                        clock=self.clock)
        self.verbose = verbose
        self.controller = MembershipController(
            lease_dir=lease_dir, lease_timeout=lease_timeout,
            telemetry_=telemetry_, reactors=reactors,
            record_dir=record_dir, straggle_windows=straggle_windows or 3,
            clock=self.clock)
        self._straggle_enabled = straggle_windows > 0
        self._straggle_poll_s = float(straggle_poll_s)
        self._last_straggle_check = 0.0
        self.procs: Dict[int, subprocess.Popen] = {}
        self.attempts: Dict[int, int] = {w: 0 for w in self.worker_ids}
        self.done: set = set()
        self.failed: set = set()
        self._pending: List[Tuple[float, int]] = []   # (due_ts, worker)
        # -- supervised center process (round 14): the center is respawned
        # from its snapshot like a worker — lease + backoff + breaker —
        # while the clients ride the outage out on wire retries
        self.center_cmd_for = center_cmd_for
        self.center_addr = center_addr
        self.center_max_restarts = int(center_max_restarts)
        # the center's lease lives in its OWN dir: controller.poll() folds
        # every lease under lease_dir into WORKER transitions, and the
        # center is not a worker
        self.center_lease_dir = center_lease_dir
        self.center_proc: Optional[subprocess.Popen] = None
        self.center_attempts = 0
        self._center_due: Optional[float] = None      # pending respawn ts
        self._center_probe = False                    # awaiting restored?
        self._center_downs = 0
        # -- fleet health plane (round 18, docs/design.md §20): a
        # FleetMonServer whose collector's actionable alerts this loop
        # drains — the alert-driven half of supervision
        self.fleetmon = fleetmon
        self.alert_demotions: List[Tuple[str, int]] = []
        self.flight_dumps_requested = 0

    # chaos harness hook: the CURRENT pid of a worker (None between lives);
    # target CENTER_ID resolves the supervised center process
    def pid_of(self, worker_id: int) -> Optional[int]:
        if int(worker_id) == self.CENTER_ID and \
                self.center_cmd_for is not None:
            p = self.center_proc
            return p.pid if p is not None and p.poll() is None else None
        p = self.procs.get(int(worker_id))
        return p.pid if p is not None and p.poll() is None else None

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"elastic: {msg}", file=sys.stderr, flush=True)

    def _spawn(self, wid: int) -> None:
        attempt = self.attempts[wid]
        cmd = self.cmd_for(wid, attempt)
        self.procs[wid] = subprocess.Popen(cmd)
        self.attempts[wid] = attempt + 1
        self.controller.join(wid, pid=self.procs[wid].pid,
                             reason="respawn" if attempt else "spawn")
        self._log(f"worker {wid} spawned (pid {self.procs[wid].pid}, "
                  f"attempt {attempt})")

    # -- center supervision (round 14) --------------------------------------

    def _spawn_center(self) -> None:
        cmd = self.center_cmd_for(self.center_attempts)
        self.center_proc = subprocess.Popen(cmd)
        self.center_attempts += 1
        self._center_due = None
        self._center_probe = True      # emit center_restored on first answer
        self._log(f"center spawned (pid {self.center_proc.pid}, "
                  f"attempt {self.center_attempts - 1})")

    def _center_answers(self) -> bool:
        """Non-blocking-ish probe: does the center accept on its fixed
        port?  Called once per tick only while awaiting a restore."""
        import socket
        host, port = str(self.center_addr).rsplit(":", 1)
        try:
            socket.create_connection((host, int(port)), timeout=0.2).close()
            return True
        except OSError:
            return False

    def _tick_center(self) -> bool:
        """One supervision tick for the center process.  True when the
        center crash-looped past its budget (caller stops the world)."""
        if self.center_cmd_for is None:
            return False
        now = self.clock.now()
        p = self.center_proc
        # a WEDGED center (alive, not beating — SIGSTOP, hung handler) is
        # as gone as a dead one: kill it, the death branch below respawns
        if p is not None and p.poll() is None and self.center_lease_dir \
                and not self._center_probe:
            doc = read_leases(self.center_lease_dir).get(self.CENTER_ID)
            if doc is not None and \
                    now - float(doc.get("ts", 0)) > \
                    self.controller.lease_timeout:
                self._log("center lease expired while wedged — killing it")
                self._center_wedged = True
                try:
                    p.kill()
                    p.wait(timeout=30)
                except Exception:
                    pass
        if p is not None and p.poll() is not None:
            rc = p.returncode
            self.center_proc = None
            self._center_downs += 1
            reason = "wedged" if getattr(self, "_center_wedged", False) \
                else "crashed"
            self._center_wedged = False
            self.controller.center_down(
                reason=reason, rc=rc, downs=self._center_downs)
            if self.breaker.record_failure():
                self._log("center crash tripped the crash-loop breaker "
                          "— stopping the world")
                return True
            if self.center_attempts > self.center_max_restarts:
                self._log(f"center exhausted {self.center_max_restarts} "
                          f"restarts — stopping the world")
                return True
            delay = self.backoff.delay(self.center_attempts - 1)
            self._log(f"center died (rc={rc}); respawn from snapshot "
                      f"in {delay:.1f}s — clients ride it out on wire "
                      f"retries")
            self._center_due = now + delay
        if self._center_due is not None and now >= self._center_due:
            self._spawn_center()
        if self._center_probe and self.center_proc is not None and \
                self.center_addr and self._center_answers():
            self._center_probe = False
            # first spawn is not a restoration — the pair the chaos gate
            # audits is down → restored
            if self._center_downs:
                self.controller.center_restored(
                    attempt=self.center_attempts - 1)
                self._log("center restored — serving again")
        # let deferred demote/readmit intents land on the revived center —
        # only once it answers (each flush attempt against a dead center
        # blocks this loop for the reactor client's retry budget)
        if not self._center_probe:
            for r in self.controller.reactors:
                flush = getattr(r, "flush_pending", None)
                if flush is not None:
                    flush()
        return False

    # -- alert-driven supervision (round 18) ---------------------------------

    def _tick_fleetmon(self) -> None:
        """Drain the collector's actionable alerts: a per-rank ``demote``
        alert feeds the EXISTING demotion path with the firing rule
        cited in the ``worker_demote`` event (``fleetmon.apply_alert``),
        and a fleet-scoped ``flight_dump`` alert asks every statusz
        endpoint for its flight ring.  The supervisor also ingests its
        own liveness sample, so the fleet view includes it."""
        fm = self.fleetmon
        if fm is None:
            return
        from ..utils import fleetmon as _fleetmon
        fm.collector.ingest({"steps": float(len(self.done))}, rank=-2,
                            role="supervisor")
        for alert in fm.collector.pop_actions():
            if alert.get("action") == "demote":
                if _fleetmon.apply_alert(self.controller, alert):
                    self.alert_demotions.append(
                        (str(alert.get("rule")), int(alert["rank"])))
                    self._log(f"alert {alert['rule']} "
                              f"(value {alert.get('value')}) demoted "
                              f"worker {alert['rank']}")
            elif alert.get("action") == "flight_dump" and self.record_dir:
                paths = _fleetmon.fleet_flight_dump(
                    self.record_dir, reason=f"alert {alert.get('rule')}")
                self.flight_dumps_requested += 1
                self._log(f"alert {alert['rule']}: fleet-wide flight "
                          f"dump ({len(paths)} ring(s) written)")

    def _stop_center(self) -> None:
        p = self.center_proc
        if p is None:
            return
        try:
            if p.poll() is None:
                p.terminate()          # SIGTERM: final snapshot + lease
                try:
                    p.wait(timeout=15)
                except Exception:
                    p.kill()
                    p.wait(timeout=15)
        except OSError:
            pass
        self.center_proc = None

    def _kill_all(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        for p in self.procs.values():
            try:
                p.wait(timeout=30)
            except Exception:
                pass
        self._stop_center()

    def _on_death(self, wid: int, rc: Optional[int], reason: str) -> bool:
        """Record a death; schedule the respawn.  True when the crash-loop
        breaker tripped (caller stops the world)."""
        self.controller.leave(wid, reason=reason, rc=rc)
        if self.breaker.record_failure():
            self._log(f"crash-loop breaker tripped "
                      f"({self.breaker.limit} failures within "
                      f"{self.breaker.window_s:.0f}s) — stopping the world")
            if self.record_dir:
                for line in flight_tail_lines(self.record_dir):
                    print(line, file=sys.stderr, flush=True)
            return True
        if self.attempts[wid] > self.max_restarts:
            self._log(f"worker {wid} exhausted {self.max_restarts} restarts "
                      f"— giving up on it")
            self.failed.add(wid)
            return False
        delay = self.backoff.delay(self.attempts[wid] - 1)
        self._log(f"worker {wid} {reason} (rc={rc}); respawn in {delay:.1f}s")
        self._pending.append((self.clock.now() + delay, wid))
        return False

    def run(self, timeout_s: float = 600.0) -> int:
        """Run the elastic world until every worker finished (rc 0): 0 — or
        nonzero on breaker trip / restart exhaustion / timeout."""
        t0 = self.clock.now()
        # live ops endpoint (§17): the supervisor is a long-lived process
        # too — fleetz shows its view of the fleet next to the workers'
        statusz = None
        if self.record_dir:
            statusz = tracing.StatuszServer(
                "supervisor", ident=0, run_dir=self.record_dir,
                telemetry_=self.controller.telemetry,
                extra=lambda: {"workers": self.controller.status(),
                               "done": sorted(self.done),
                               "failed": sorted(self.failed),
                               "center_downs": self._center_downs,
                               "alert_demotions": len(self.alert_demotions)})
            statusz.start()
        if self.center_cmd_for is not None:
            self._spawn_center()
        for wid in self.worker_ids:
            self._spawn(wid)
        try:
            while True:
                # 0. the supervised center: death → center_down → backoff
                # respawn-from-snapshot → center_restored when it answers
                if self._tick_center():
                    self._kill_all()
                    return 1
                # 1. process deaths
                for wid, p in list(self.procs.items()):
                    if wid in self.done or wid in self.failed:
                        continue
                    rc = p.poll()
                    if rc is None:
                        continue
                    del self.procs[wid]
                    if rc == 0:
                        self.done.add(wid)
                        self.controller.leave(wid, reason="finished")
                        self._log(f"worker {wid} finished")
                    elif self._on_death(wid, rc, "crashed"):
                        self._kill_all()
                        return 1
                # 2. lease expiry of WEDGED workers (process alive, no
                # heartbeats — SIGSTOP, hung collective): kill + respawn
                for ev, wid, info in self.controller.poll():
                    if ev == "worker_leave" and \
                            info.get("reason") == "lease_expired" and \
                            wid in self.procs:
                        p = self.procs.pop(wid)
                        try:
                            p.kill()
                            p.wait(timeout=30)
                        except Exception:
                            pass
                        self._log(f"worker {wid} lease expired while "
                                  f"wedged — killed")
                        if self._on_death(wid, p.returncode, "wedged"):
                            self._kill_all()
                            return 1
                # 3. persistent-straggler demotion (off unless enabled;
                # throttled — the ranking re-reads the whole record_dir,
                # which grows with the run: not per-0.25s-tick work)
                if self._straggle_enabled and \
                        self.clock.now() - self._last_straggle_check > \
                        self._straggle_poll_s:
                    self._last_straggle_check = self.clock.now()
                    self.controller.check_stragglers()
                # 3b. alert-driven supervision: drain the fleet-health
                # collector's actionable alerts (rule-cited demotions,
                # fleet-wide flight dumps)
                self._tick_fleetmon()
                # 4. due respawns
                now = self.clock.now()
                due = [w for ts, w in self._pending if ts <= now]
                self._pending = [(ts, w) for ts, w in self._pending
                                 if ts > now]
                for wid in due:
                    self._spawn(wid)
                # 5. exit conditions
                if len(self.done | self.failed) == len(self.worker_ids):
                    return 0 if not self.failed else 1
                if self.clock.now() - t0 > timeout_s:
                    self._log(f"timeout after {timeout_s:.0f}s — "
                              f"stopping the world")
                    self._kill_all()
                    return 1
                self.clock.sleep(self.poll_s)
        finally:
            self._kill_all()
            if statusz is not None:
                # exception-unwinding supervisor keeps its roster entry
                statusz.stop(deregister=sys.exc_info()[0] is None)


# -- elastic worker CLI ------------------------------------------------------

def parse_kv(items: Sequence[str]) -> Dict[str, Any]:
    """``key=value`` config parsing with the worker CLI's coercions."""
    config: Dict[str, Any] = {}
    for kv in items:
        k, _, v = kv.partition("=")
        try:
            config[k] = int(v)
        except ValueError:
            try:
                config[k] = float(v)
            except ValueError:
                config[k] = {"true": True, "false": False}.get(v.lower(), v)
    return config


def elastic_worker_main(argv: Optional[Sequence[str]] = None) -> int:
    """One elastic island worker: ``python -m
    theanompi_tpu.parallel.membership <rule> <modelfile> <modelclass>
    [key=value ...]``.

    Keys: ``center_addr`` (the EASGD/ASGD center server), ``island``
    (worker id, also the telemetry rank), ``lease_dir`` (heartbeats),
    ``steps`` (local-step goal → exit 0), ``host_devices`` (CPU-venue
    simulated chip count — set BEFORE jax imports), plus the usual model
    config.  On (re)join the island restores params from the center
    (``center_restore``, default true) and re-enters at its own pace —
    the asynchronous algebra needs no barrier with the others."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 3:
        print("usage: python -m theanompi_tpu.parallel.membership "
              "<rule> <modelfile> <modelclass> [key=value ...]")
        return 2
    rule, modelfile, modelclass = argv[:3]
    cfg = parse_kv(argv[3:])

    hd = int(cfg.pop("host_devices", 0) or 0)
    if hd:
        # simulated chips are a CPU-venue concept: forcing the host
        # platform device count implies the cpu backend
        cfg.setdefault("platform", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={hd}"
            ).strip()
    island = int(cfg.get("island", 0))
    steps_goal = int(cfg.get("steps", 32))
    deadline = time.time() + float(cfg.get("max_seconds", 600))
    tm = telemetry.init({"record_dir": cfg.get("record_dir"),
                         "rank": island, "run_id": cfg.get("run_id"),
                         "telemetry": cfg.get("telemetry")})
    # causal tracing (§17): tracing=true mints a trace per exchange round
    # in the island loop and propagates it over the wire to the center
    tracing.init(cfg)
    lease = WorkerLease(cfg["lease_dir"], island, telemetry_=tm) \
        if cfg.get("lease_dir") else None
    if lease:
        # alive BEFORE the seconds-long jax import + warmup: everything
        # above this line is stdlib, so the spawn-to-first-beat window
        # can't outlive a lease on a cold-cache host
        lease.beat(0)

    import importlib

    import jax
    plat = cfg.get("platform")
    if plat:
        # explicit pin only — defaulting to cpu here would silently train
        # every elastic worker on CPU on a real TPU host
        jax.config.update("jax_platforms", str(plat))

    mod = importlib.import_module(modelfile)
    cls = getattr(mod, modelclass)

    def factory(c):
        c = dict(c)
        c.setdefault("verbose", False)
        return cls(c)

    from .async_easgd import AsyncEASGDTrainer
    cfg.setdefault("async_islands", 1)
    cfg.setdefault("island_base", island)
    cfg.setdefault("center_restore", True)
    trainer_cfg = dict(cfg)
    # this CLI owns the lease (it beats through compile, from before the
    # trainer exists); don't let the island thread double-register it
    trainer_cfg.pop("lease_dir", None)
    trainer = AsyncEASGDTrainer(factory, trainer_cfg, rule=rule)
    trainer.start()
    statusz = None
    if tm.enabled and cfg.get("record_dir") and cfg.get("statusz", True):
        statusz = tracing.StatuszServer(
            "worker", ident=island, run_dir=cfg["record_dir"],
            telemetry_=tm,
            extra=lambda: {
                "steps": trainer.islands[0].steps_done,
                "exchanges": trainer.islands[0].exchanges_done,
                "skipped": trainer.islands[0].exchanges_skipped})
        statusz.start()
    # fleet health plane (§20): stream this island's metric snapshots
    # to the run's FleetCollector — the snapshot stream doubles as the
    # health heartbeat (a kill/wedge silences it with no cooperation)
    streamer = None
    if cfg.get("metrics_addr"):
        from ..utils.fleetmon import MetricStreamer
        streamer = MetricStreamer(
            str(cfg["metrics_addr"]), rank=island, role="worker",
            interval_s=float(cfg.get("metrics_interval_s", 1.0)),
            telemetry_=tm,
            extra=lambda: {"steps": trainer.islands[0].steps_done})
        streamer.start()
    rc = 0
    try:
        while True:
            isl = trainer.islands[0]
            if lease:
                lease.beat(isl.steps_done)
            if isl.error is not None:
                rc = 1
                break
            if isl.steps_done >= steps_goal:
                break
            if time.time() > deadline:
                rc = 3
                break
            time.sleep(0.1)
        trainer.stop_and_join(timeout=120)
    except BaseException:
        rc = 1
        raise
    finally:
        if streamer is not None:
            # a clean exit sends one final `left` sample so the collector
            # retires this rank instead of alerting on its silence
            streamer.stop(final=(rc == 0))
        if statusz is not None:
            # a crashed/failed worker keeps its discovery doc: fleetz
            # must list it DOWN, not lose it from the roster
            statusz.stop(deregister=(rc == 0))
        if lease:
            if rc == 0:
                lease.release()
            else:
                lease.beat(status="dying", rc=rc)
        if tm.enabled:
            tm.event("train_end", steps=trainer.islands[0].steps_done,
                     exchanges=trainer.islands[0].exchanges_done)
            tm.close()
    return rc


# -- launcher-facing composition --------------------------------------------

def _free_port(host: str = "127.0.0.1") -> int:
    """A port the center process can bind — chosen ONCE so clients
    reconnect to the same address across center restarts."""
    import socket
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_elastic(rule: str, modelfile: str, modelclass: str,
                config: Dict[str, Any], n_workers: int, *,
                record_dir: Optional[str] = None, steps: int = 32,
                host_devices: int = 0, supervisor_kw: Optional[dict] = None,
                chaos_schedule=None, net_chaos_schedule=None,
                center_proc: bool = False, timeout_s: float = 600.0,
                verbose: bool = True) -> int:
    """One elastic run: center server + ``n_workers`` island subprocesses
    under an :class:`ElasticSupervisor` (``launcher --elastic`` and
    ``scripts/chaos_run.py`` both land here).  ``host_devices > 0`` is the
    CPU venue (each worker simulates that many chips and pins the cpu
    backend); 0 (default) leaves platform selection to the real hardware.
    BSP has no shrink algebra — use ``launcher --supervise`` (the
    reaction matrix, design.md §14).

    ``center_proc=True`` runs the center as its OWN supervised process
    (fixed port, crash-atomic snapshots, respawn-from-snapshot with
    backoff; its death/rebirth is the audited ``center_down`` /
    ``center_restored`` pair) — required when ``chaos_schedule`` targets
    worker 0, i.e. the center itself.  ``net_chaos_schedule`` puts the
    :class:`~theanompi_tpu.utils.chaos.ChaosProxy` between the workers
    and the center, injecting wire-level drop/delay/dup/corrupt/partition
    faults on the schedule (docs/design.md §15)."""
    rule = rule.lower()
    if rule not in ("easgd", "asgd"):
        raise ValueError(
            f"elastic process membership needs a center-based rule "
            f"(easgd/asgd), got {rule!r} — BSP preemption tolerance is "
            f"`launcher --supervise` (world restart at the committed "
            f"window cursor); GoSGD demotion is in-mesh "
            f"(Exchanger.set_active_ranks)")
    from .center_server import (CenterServer, RemoteCenter, load_snapshot,
                                snapshot_path)
    record_dir = record_dir or config.get("record_dir")
    lease_dir = config.get("lease_dir") or (
        os.path.join(record_dir, "membership") if record_dir else None)
    assert lease_dir, "run_elastic needs record_dir or lease_dir"
    run_id = config.get("run_id") or f"elastic{int(time.time())}"
    tm = telemetry.init({"record_dir": record_dir, "rank": 0,
                         "run_id": run_id}) if record_dir else \
        telemetry.active()

    alpha = float(config.get("alpha", 0.5))
    chost = str(config.get("center_host", "127.0.0.1"))
    srv = None
    center_kw: Dict[str, Any] = {}
    snap_dir = None
    if center_proc:
        assert record_dir, "center_proc needs a record_dir (snapshots)"
        port = int(config.get("center_port", 0)) or _free_port(chost)
        addr = f"{chost}:{port}"
        snap_dir = os.path.join(record_dir, "center_snap")
        center_lease_dir = os.path.join(lease_dir, "center")

        def center_cmd_for(attempt: int) -> List[str]:
            cmd = [sys.executable, "-m",
                   "theanompi_tpu.parallel.center_server",
                   "--host", chost, "--port", str(port),
                   "--alpha", str(alpha),
                   "--snapshot-dir", snap_dir,
                   "--snapshot-every",
                   str(config.get("center_snapshot_every_s", 1.0)),
                   "--lease-dir", center_lease_dir,
                   "--lease-id", str(ElasticSupervisor.CENTER_ID),
                   "--run-id", str(run_id)]
            if record_dir:
                cmd += ["--record-dir", record_dir]
            if metrics_addr:
                # bound at spawn time: the fleetmon server starts before
                # the supervisor spawns anything
                cmd += ["--metrics-addr", metrics_addr]
            return cmd

        # the supervisor's own client: SHORT deadline — reactor calls and
        # probes must never stall the supervision loop that is busy
        # respawning the very center they are waiting for
        center_handle = RemoteCenter(addr, alpha=alpha,
                                     client_id="supervisor",
                                     op_timeout_s=5.0, max_retries=2,
                                     deadline_s=8.0, telemetry_=tm)
        center_kw = dict(center_cmd_for=center_cmd_for, center_addr=addr,
                         center_lease_dir=center_lease_dir)
    else:
        srv = CenterServer(alpha=alpha)
        host, port = srv.start(chost, int(config.get("center_port", 0)))
        addr = f"{host}:{port}"
        center_handle = srv.center

    # wire-level chaos: the proxy sits between the WORKERS and the center
    # (the supervisor's membership ops take the direct road — the faults
    # under test are the training wire's)
    # every landed fault (process AND wire level) appends to the run's
    # realized-schedule log — the replay/diff artifact simfleet's
    # fidelity cross-check consumes.  Truncate any previous run's file:
    # the writers append, and a merged two-run history would replay
    # every fault twice
    realized = os.path.join(record_dir, "chaos_realized.jsonl") \
        if record_dir else None
    if realized and os.path.exists(realized):
        try:
            os.remove(realized)
        except OSError:
            realized = None
    # `corrupt` faults land as trigger files here; each island polls the
    # dir at its exchange rounds (async_easgd) and perturbs its own live
    # params — the §25 numerics plane must then catch the desync
    corrupt_dir = os.path.join(record_dir, "chaos") \
        if (record_dir and chaos_schedule) else None
    proxy = None
    worker_addr = addr
    if net_chaos_schedule:
        from ..utils.chaos import ChaosProxy
        proxy = ChaosProxy(addr, net_chaos_schedule, telemetry_=tm,
                           realized_path=realized)
        worker_addr = proxy.start()

    # fleet health plane (round 18, docs/design.md §20): a FleetCollector
    # service every process streams metric snapshots to; its rule engine
    # emits `alert` events into the run's telemetry stream and queues
    # actionable alerts the supervisor loop drains.  The metrics wire is
    # DIRECT (never through the chaos proxy): observability must survive
    # the faults it reports on.
    fleetmon_srv = None
    metrics_addr = None
    if record_dir and config.get("fleetmon"):
        from ..utils.fleetmon import FleetMonServer, default_rules
        divergence = config.get("fleetmon_divergence")
        rules = config.get("fleetmon_rules") or default_rules(
            heartbeat_s=float(config.get("fleetmon_heartbeat_s", 10.0)),
            step_p99_s=config.get("fleetmon_step_p99_s"),
            step_window_s=float(config.get("fleetmon_step_window_s", 10.0)),
            divergence=None if divergence is None else float(divergence))
        fleetmon_srv = FleetMonServer(
            rules=rules, run_dir=record_dir,
            snapshot_dir=os.path.join(record_dir, "fleetmon_snap"),
            eval_window_s=float(config.get("fleetmon_eval_s", 2.0)),
            telemetry_=tm)
        fh, fp = fleetmon_srv.start()
        metrics_addr = f"{fh}:{fp}"

    base_kv = dict(config)
    for drop in ("lease_dir", "record_dir", "run_id", "center_addr",
                 "rule", "n_workers", "fleetmon", "fleetmon_rules",
                 "fleetmon_heartbeat_s", "fleetmon_step_p99_s",
                 "fleetmon_step_window_s", "fleetmon_eval_s",
                 "fleetmon_divergence"):
        base_kv.pop(drop, None)

    def cmd_for(wid: int, attempt: int) -> List[str]:
        kv = dict(base_kv)
        kv.update(island=wid, center_addr=worker_addr, lease_dir=lease_dir,
                  steps=steps, host_devices=host_devices, run_id=run_id)
        if record_dir:
            kv["record_dir"] = record_dir
        if corrupt_dir:
            kv["chaos_dir"] = corrupt_dir
        if metrics_addr:
            kv["metrics_addr"] = metrics_addr
        return [sys.executable, "-m", "theanompi_tpu.parallel.membership",
                rule, modelfile, modelclass] + \
            [f"{k}={v}" for k, v in sorted(kv.items())]

    kw = dict(record_dir=record_dir, telemetry_=tm,
              reactors=(CenterReactor(center_handle),), verbose=verbose,
              fleetmon=fleetmon_srv)
    kw.update(center_kw)
    kw.update(supervisor_kw or {})
    sup = ElasticSupervisor(cmd_for, list(range(1, n_workers + 1)),
                            lease_dir, **kw)
    # progress-gated chaos: fault times are relative to the run MAKING
    # PROGRESS (first lease beat with step >= 1), not to process spawn —
    # a loaded box's slow first compile must not eat the schedule's whole
    # window before training even exists (the kill-lands-mid-run
    # guarantee the chaos tests assert).  Bounded fallback: if no step
    # ever beats, the monkey starts anyway so no-pid drops still resolve.
    monkey_box: List[Any] = []
    gate_halt = threading.Event()
    if chaos_schedule:
        from ..utils.chaos import ChaosMonkey

        def _gated_start():
            deadline = time.time() + min(120.0, float(timeout_s))
            while time.time() < deadline and not gate_halt.is_set():
                if any(int(doc.get("step", 0)) >= 1
                       for doc in read_leases(lease_dir).values()):
                    break
                time.sleep(0.1)
            if gate_halt.is_set():
                return
            m = ChaosMonkey(chaos_schedule, pid_of=sup.pid_of,
                            telemetry_=tm, realized_path=realized,
                            corrupt_dir=corrupt_dir)
            monkey_box.append(m)
            m.start()

        threading.Thread(target=_gated_start, daemon=True,
                         name="chaos-gate").start()
    try:
        rc = sup.run(timeout_s=timeout_s)
    finally:
        gate_halt.set()
        for m in monkey_box:
            m.stop()
        if proxy is not None:
            proxy.stop()
        # persist the final center + its bookkeeping for offline eval
        # (chaos_run's loss gate and applied-once audit)
        try:
            import numpy as np
            leaves = None
            stats = None
            if center_proc:
                # sup.run's exit SIGTERMed the center, which wrote a
                # final crash-atomic snapshot — the authoritative final
                # state whether the run ended cleanly or under chaos
                if snap_dir and os.path.exists(snapshot_path(snap_dir)):
                    leaves, meta = load_snapshot(snapshot_path(snap_dir))
                    dd = meta.get("dedup") or {}
                    stats = {"n_updates": meta.get("n_updates", 0),
                             "by_island": meta.get("updates_by_island",
                                                   {}),
                             "demoted": meta.get("demoted", []),
                             "dropped_by_island":
                                 meta.get("dropped_by_island", {}),
                             "dedup_hits": dd.get("hits", 0),
                             "seq_hwm": dd.get("hwm", {})}
            else:
                leaves = srv.center.pull_leaves()
                stats = {"ok": True, **srv.center.stats_snapshot(),
                         "dedup_hits": srv.dedup.hits,
                         "seq_hwm": srv.dedup.hwm_snapshot()}
            if record_dir and leaves is not None:
                with open(os.path.join(record_dir, "center_final.npz"),
                          "wb") as f:
                    np.savez(f, **{f"leaf{i}": x
                                   for i, x in enumerate(leaves)})
            if record_dir and stats is not None:
                stats = {k: v for k, v in stats.items()
                         if k not in ("ok", "v", "crc", "tok")}
                stats["center_downs"] = sup._center_downs
                if proxy is not None:
                    # frames the proxy actually faulted per kind — the
                    # audit tells 'dup window opened but no traffic
                    # passed' apart from 'duplicates were re-applied'
                    stats["net_frames_faulted"] = \
                        dict(proxy.frames_faulted)
                with open(os.path.join(record_dir, "center_stats.json"),
                          "w") as f:
                    json.dump(stats, f, indent=1, sort_keys=True)
        except Exception:
            pass
        if center_proc:
            sup._stop_center()
            try:
                center_handle.close()
            except Exception:
                pass
        if srv is not None:
            srv.stop()
        if fleetmon_srv is not None:
            fleetmon_srv.stop()
        if tm.enabled:
            tm.event("elastic_end", rc=rc,
                     status=sup.controller.status())
            tm.close()
    return rc


if __name__ == "__main__":
    raise SystemExit(elastic_worker_main())
