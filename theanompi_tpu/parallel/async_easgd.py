"""Genuinely asynchronous EASGD — worker islands around a host-side center.

The reference's EASGD (SURVEY.md §3.2) ran a dedicated *server process*
holding center parameters; each worker exchanged with it over MPI Send/Recv
at its own pace — the defining property being that a straggler never blocks
the others.  The in-step :class:`~.exchanger.EASGD_Exchanger` keeps the
update algebra but runs at a synchronous cadence (every chip participates in
one lockstep program), so that property has no analogue there.

This module restores it TPU-natively: the device mesh is partitioned into
**islands** — disjoint sub-meshes, each running its OWN compiled SPMD train
step from its own host thread — and the center lives host-side behind a
lock (:class:`ElasticCenter`, ≙ the reference's server).  Every
``sync_freq`` local steps an island pulls the center, applies the elastic
pairwise update on-device, and pushes its α-scaled delta back.  Islands
never rendezvous with each other: a deliberately slowed island lags while
the rest keep training (tested in ``tests/test_async_easgd.py``).

Update algebra per island exchange (EASGD paper, round-robin form):

    delta_i  = worker_i − center_snapshot        (per worker in the island)
    worker_i ← worker_i − α·delta_i
    center   ← center + α·mean_i delta_i         (atomic, possibly stale)

The center absorbs the island-MEAN delta (the same pmean algebra as the
synchronous exchanger): the reference applied each worker's α·delta one at
a time, which for an island of k workers against one snapshot would give an
effective gain of k·α and diverge for k·α > 1.

Staleness of ``center_snapshot`` between pull and push is inherent to — and
the point of — asynchronous EASGD.

Config surface (run via :class:`AsyncEASGDTrainer` or the ``EASGD`` rule
with ``easgd_mode='async'``): ``async_islands`` (number of islands),
``alpha``, ``sync_freq``.

Round-4 extensions:

* **ASGD islands** (``ASGD`` rule, ``asgd_mode='async'`` — or
  ``rule='asgd'`` here): downpour semantics — the island accumulates
  ``sync_freq`` local steps from an anchor, ships the delta, and resets to
  the fresh center returned by one atomic ``push_pull`` (the reference's
  accumulated-gradient round-trip, SURVEY.md §2.2 — asynchrony is ASGD's
  defining property there).
* **Cross-process centers** (``parallel.center_server``): ``center_serve``
  exposes this process's center over TCP; ``center_addr='host:port'``
  joins a remote one — islands in launcher-supervised subprocesses or on
  other hosts exchange with ONE center, the reference's server-rank
  topology.  ``island_base`` offsets island ids (and data streams) so
  processes don't collide.
* Throughput: ``scripts/async_vs_sync_easgd.py`` records island-mode vs
  sync-cadence aggregate samples/sec on the same devices.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import tracing
from .mesh import WORKER_AXIS


class ElasticCenter:
    """Host-side center parameter store (≙ the reference's EASGD server).

    Thread-safe: islands call :meth:`pull` / :meth:`push_delta` at their own
    cadence; the lock serializes center updates exactly like the reference
    server serving one worker at a time.

    The store is CANONICALLY a flat leaf list (plus the treedef captured
    from the first tree-shaped caller), so in-process islands (pytree
    interface) and remote clients (leaf-list wire protocol,
    ``parallel.center_server``) can share one center in any join order.
    """

    def __init__(self, params=None, alpha: float = 0.5):
        self.alpha = float(alpha)
        self._leaves: Optional[List[np.ndarray]] = None
        self._treedef = None
        # REENTRANT: the center server's handler takes this lock FIRST to
        # measure queue wait (lock wait = center queueing, §17 time
        # split), then calls the op, which re-enters it for free
        self._lock = threading.RLock()
        self.n_updates = 0            # exchanges absorbed (all islands)
        self.updates_by_island: Dict[int, int] = {}
        # elastic membership (parallel/membership.py): a demoted island's
        # pushes are DROPPED (counted below) while its pulls still serve —
        # it keeps training locally, can't pollute the center, and on
        # readmit its next pull restores it from the consensus
        self.demoted: set = set()
        self.dropped_by_island: Dict[int, int] = {}
        if params is not None:
            self.ensure_init(params)

    # -- membership (elastic demote/readmit) --------------------------------

    def demote_island(self, island: int) -> None:
        with self._lock:
            self.demoted.add(int(island))

    def readmit_island(self, island: int) -> None:
        with self._lock:
            self.demoted.discard(int(island))

    def stats_snapshot(self) -> Dict[str, object]:
        """Consistent copy of the bookkeeping under the lock — the socket
        server's ``stats`` op serializes this while other handler threads
        mutate the live sets."""
        with self._lock:
            return {"n_updates": self.n_updates,
                    "by_island": dict(self.updates_by_island),
                    "demoted": sorted(self.demoted),
                    "dropped_by_island": dict(self.dropped_by_island)}

    def _drop_if_demoted(self, island: int) -> bool:
        """Caller holds the lock.  True = this push is from a demoted (or
        departed-and-not-readmitted) island and must be dropped."""
        if int(island) in self.demoted:
            self.dropped_by_island[int(island)] = \
                self.dropped_by_island.get(int(island), 0) + 1
            return True
        return False

    # -- pytree interface (in-process islands) -----------------------------

    # ``trace`` mirrors RemoteCenter's surface (IslandRunner passes its
    # round-span context to whichever center it holds); the in-process
    # store has no wire to propagate it over, so it is accepted and
    # ignored — the round's critical path then shows zero wire time,
    # which is the truth.

    def ensure_init(self, params, trace=None) -> None:
        """Lazy init from the first island to arrive — all islands share the
        model seed, so their initial params (and hence the center) agree;
        avoids building a throwaway probe model just to read its params."""
        leaves, treedef = jax.tree.flatten(params)
        with self._lock:
            if self._leaves is None:
                self._leaves = [np.array(x, np.float32) for x in leaves]
            if self._treedef is None:     # a remote client may have seeded
                self._treedef = treedef   # the leaves before any local tree

    def pull(self, trace=None):
        with self._lock:
            assert self._leaves is not None, "center not initialized yet"
            assert self._treedef is not None, \
                "pull() needs a tree-shaped ensure_init first"
            return jax.tree.unflatten(self._treedef,
                                      [np.array(x) for x in self._leaves])

    def push_delta(self, delta_mean, island: int, trace=None) -> None:
        """center += α·mean_i delta_i for one island's workers."""
        self.push_delta_leaves(jax.tree.leaves(delta_mean), island)

    def push_pull(self, delta_mean, island: int, trace=None):
        """ASGD downpour round-trip (≙ the reference server absorbing a
        worker's accumulated gradients and replying with fresh params):
        center += mean_i delta_i, return the new center — one atomic op."""
        leaves = self.push_pull_leaves(jax.tree.leaves(delta_mean), island)
        assert self._treedef is not None
        return jax.tree.unflatten(self._treedef, leaves)

    # -- leaf-list interface (the socket server's wire format) --------------

    def ensure_init_leaves(self, leaves: List[np.ndarray]) -> None:
        with self._lock:
            if self._leaves is None:
                self._leaves = [np.array(x, np.float32) for x in leaves]

    def pull_leaves(self) -> List[np.ndarray]:
        with self._lock:
            assert self._leaves is not None, "center not initialized yet"
            return [np.array(x) for x in self._leaves]

    def _check_leaves(self, deltas) -> None:
        # a client with a mismatched model config must fail LOUDLY here —
        # zip would silently truncate the shared store and crash every
        # other island at its next pull, far from the offender
        assert self._leaves is not None, "center not initialized yet"
        assert len(deltas) == len(self._leaves), (
            f"push of {len(deltas)} leaves against a {len(self._leaves)}"
            "-leaf center — mismatched model configs across islands?")

    def push_delta_leaves(self, deltas: List[np.ndarray],
                          island: int) -> None:
        a = self.alpha
        with self._lock:
            if self._drop_if_demoted(island):
                return
            self._check_leaves(deltas)
            self._leaves = [c + a * np.asarray(d, np.float32)
                            for c, d in zip(self._leaves, deltas)]
            self.n_updates += 1
            self.updates_by_island[island] = \
                self.updates_by_island.get(island, 0) + 1

    def push_pull_leaves(self, deltas: List[np.ndarray],
                         island: int) -> List[np.ndarray]:
        with self._lock:
            if self._drop_if_demoted(island):
                # the pull half still serves: the demoted island resets to
                # the (unpolluted) center and keeps training locally
                self._check_leaves(deltas)
                return [np.array(x) for x in self._leaves]
            self._check_leaves(deltas)
            self._leaves = [c + np.asarray(d, np.float32)
                            for c, d in zip(self._leaves, deltas)]
            self.n_updates += 1
            self.updates_by_island[island] = \
                self.updates_by_island.get(island, 0) + 1
            return [np.array(x) for x in self._leaves]


class IslandRunner(threading.Thread):
    """One island: a sub-mesh, its own compiled train step, its own pace.

    ``model_factory(config) -> model`` builds the island's model; the island
    config carries its sub-``mesh``, its ``size``, and a distinct ``seed`` so
    islands consume different data streams (the reference's workers likewise
    each walked their own shard).
    """

    def __init__(self, island_id: int, model_factory: Callable, config: dict,
                 center: ElasticCenter, sync_freq: int,
                 stop_event: threading.Event,
                 throttle_s: float = 0.0, rule: str = "easgd",
                 lease=None):
        super().__init__(daemon=True)
        self.island_id = island_id
        self.config = config
        self.center = center
        self.sync_freq = int(sync_freq)
        self.stop_event = stop_event
        self.throttle_s = float(throttle_s)   # test hook: deliberate straggler
        self.rule = rule                      # 'easgd' elastic | 'asgd' downpour
        self.lease = lease                    # membership.WorkerLease | None
        self.steps_done = 0
        self.exchanges_done = 0
        # center outages survived mid-run: the island kept training locally
        # (EASGD/ASGD tolerate missed exchanges) and resynced on reconnect
        self.exchanges_skipped = 0
        self.error: Optional[BaseException] = None
        self._model_factory = model_factory

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:      # surfaced by AsyncEASGDTrainer.join
            self.error = e

    def _run(self) -> None:
        from .exchanger import Exchanger
        from .wire import CenterUninitialized, WireGiveUp

        model = self._model_factory(self.config)
        try:
            self.center.ensure_init(jax.device_get(model.params))
        except WireGiveUp as e:
            raise RuntimeError(
                f"island {self.island_id}: center unreachable at startup "
                f"— cannot seed/join the center store.  Is the center "
                f"server up (or its supervisor respawning it)?  "
                f"Underlying wire error: {e}") from e
        # Local-only updates inside the island: the base Exchanger's
        # step_update is exactly the local optimizer step.
        exch = Exchanger(self.config)
        model.compile_iter_fns(exch)
        model.data.shuffle_data(int(self.config.get("data_seed", 0)))
        mesh = model.mesh
        n = mesh.shape[WORKER_AXIS]
        alpha = self.center.alpha

        def _rebox_center(center):
            return jax.tree.map(
                lambda c: np.broadcast_to(np.asarray(c, np.float32)[None],
                                          (n,) + np.shape(c)), center)

        def _set_params_from(center):
            model.step_state["params"] = jax.tree.map(
                lambda x, like: jax.device_put(
                    np.asarray(x, like.dtype), like.sharding),
                _rebox_center(center), model.step_state["params"])

        if self.config.get("center_restore", False):
            # elastic rejoin (membership.py): a (re)joining worker restores
            # its replica from the live center — on a FRESH center this is
            # an identity (ensure_init seeded it from these very params),
            # on a rejoin it replaces the stale/initial replica with the
            # consensus the surviving workers kept training.  The pull is
            # BOUNDED (the wire client's timeout + backoff + deadline): a
            # dead center at spawn time must fail the rejoin loudly so the
            # supervisor's backoff gets another shot, not hang the worker
            # the supervisor just paid to respawn.
            try:
                _set_params_from(self.center.pull())
            except WireGiveUp as e:
                raise RuntimeError(
                    f"island {self.island_id}: center_restore failed — the "
                    f"center stayed unreachable through the wire client's "
                    f"retry budget, so the rejoining worker cannot restore "
                    f"its replica.  Giving up (the supervisor's backoff "
                    f"owns the next attempt).  Underlying wire error: {e}"
                ) from e

        # Jitted elastic update: (boxed params, replicated center) ->
        # (boxed new params, boxed per-worker deltas summed on host later).
        def elastic(params_boxed, center):
            delta = jax.tree.map(lambda p, c: p - c[None], params_boxed, center)
            new_params = jax.tree.map(lambda p, d: p - alpha * d,
                                      params_boxed, delta)
            delta_mean = jax.tree.map(lambda d: jnp.mean(d, axis=0), delta)
            return new_params, delta_mean

        elastic_fn = jax.jit(elastic)

        # ASGD downpour (reference asgd_worker, SURVEY.md §3.2): the island
        # accumulates sync_freq local steps from an anchor (the center as of
        # its last exchange), ships the accumulated delta, and resets to the
        # fresh center the server returns — one atomic push_pull round-trip.
        def worker_mean(params_boxed):
            return jax.tree.map(lambda x: jnp.mean(x, axis=0), params_boxed)

        mean_fn = jax.jit(worker_mean)
        # ASGD anchor is captured at START (== the init center), not lazily
        # at the first exchange: a concurrent island's push landing before
        # this island's first exchange would otherwise be subtracted away
        # and erased from the center
        anchor = self.center.pull() if self.rule == "asgd" else None

        # causal tracing (docs/design.md §17): one trace per exchange
        # round — minted at the round's first local step, ended after its
        # exchange.  The round span's context rides the wire into the
        # center's handler span, so the report can join client and server
        # sides and split the round into compute|stage|wire|queue|apply.
        # ONE `enabled` check per site; disabled tracing costs nothing.
        tr = tracing.active()
        from ..utils import telemetry
        tm = telemetry.active()
        rec = None
        if tr.enabled or tm.enabled:
            # a real recorder under the island steps: train_iter brackets
            # load/stage/train, giving the round span a MEASURED stage_s
            # (data wait + host staging — without it a staging-starved
            # island would be misattributed to 'compute' in the §17
            # root-cause table) and, with telemetry on, the phase.train
            # events the windowed straggler ranking reads
            from ..utils.recorder import Recorder
            rec = Recorder({"verbose": False, "rank": self.island_id})
            rec.telemetry = tm
        rnd = None
        stage_base = 0.0
        # chaos `corrupt` trigger (utils/chaos.py): the monkey drops a
        # per-island trigger file; this island consumes it at its next
        # exchange round and perturbs its OWN live replica — corruption
        # from the inside, past every wire CRC
        corrupt_path = None
        chaos_dir = self.config.get("chaos_dir")
        if chaos_dir:
            corrupt_path = os.path.join(
                str(chaos_dir), f"corrupt_w{self.island_id}.json")
        count = 0
        while not self.stop_event.is_set():
            count += 1
            if rnd is None and tr.enabled:
                rnd = tr.begin("round", island=self.island_id, count=count)
                if rec is not None:
                    stage_base = rec.t_sec_total["load"] + \
                        rec.t_sec_total["stage"]
            model.train_iter(count, rec)
            self.steps_done += 1
            if self.lease is not None:
                self.lease.beat(self.steps_done)
            if self.throttle_s:
                time.sleep(self.throttle_s)
            if count % self.sync_freq == 0:
                if corrupt_path is not None and \
                        os.path.exists(corrupt_path):
                    try:
                        with open(corrupt_path) as f:
                            doc = json.load(f)
                        os.remove(corrupt_path)
                        scale = float(doc.get("scale", 0.0)) or 1e-3
                    except (OSError, ValueError):
                        scale = None
                    if scale is not None:
                        leaves, td = jax.tree.flatten(
                            model.step_state["params"])
                        leaves[0] = leaves[0] + jnp.asarray(
                            scale, leaves[0].dtype)
                        model.step_state["params"] = \
                            jax.tree.unflatten(td, leaves)
                ctx = None
                if rnd is not None:
                    # local-step wall time — the round residual beyond
                    # stage and the wire ops is compute; stage_s is the
                    # MEASURED data-wait + host-staging time of this
                    # round's steps (recorder load+stage buckets)
                    rnd.note(train_s=round(time.time() - rnd.t0, 6),
                             steps=self.sync_freq, rule=self.rule)
                    if rec is not None:
                        rnd.note(stage_s=round(
                            rec.t_sec_total["load"] +
                            rec.t_sec_total["stage"] - stage_base, 6))
                    ctx = rnd.ctx()
                # A center outage mid-run is SURVIVABLE: the island skips
                # the exchange and keeps training locally (the EASGD/ASGD
                # algebra tolerates missed exchanges by design) — the next
                # successful pull/push_pull resyncs it against whatever
                # the center became (restored from snapshot, advanced by
                # the other islands) while the supervisor respawns it.
                outcome = "exchanged"
                dist = None
                try:
                    if self.rule == "asgd":
                        if anchor is None:
                            # resync after an outage: the interrupted
                            # round's push_pull may have LANDED with its
                            # reply lost — pushing a delta against the
                            # stale anchor would apply that movement a
                            # SECOND time under a fresh token the dedup
                            # window cannot match.  Re-anchor to the
                            # current center and restart the local
                            # accumulation (the abandoned round is a
                            # missed exchange, which downpour absorbs).
                            anchor = self.center.pull(trace=ctx)
                            _set_params_from(anchor)
                        else:
                            mean_p = jax.device_get(mean_fn(
                                model.step_state["params"]))
                            delta = jax.tree.map(np.subtract, mean_p,
                                                 anchor)
                            anchor = self.center.push_pull(
                                delta, self.island_id, trace=ctx)
                            _set_params_from(anchor)
                            dist = float(np.sqrt(sum(
                                float(np.sum(np.square(
                                    np.asarray(x, np.float64))))
                                for x in jax.tree.leaves(delta))))
                    else:
                        center = self.center.pull(trace=ctx)
                        new_params, delta_mean = elastic_fn(
                            model.step_state["params"], center)
                        model.step_state["params"] = new_params
                        dm = jax.device_get(delta_mean)
                        self.center.push_delta(dm, self.island_id,
                                               trace=ctx)
                        dist = float(np.sqrt(sum(
                            float(np.sum(np.square(
                                np.asarray(x, np.float64))))
                            for x in jax.tree.leaves(dm))))
                    self.exchanges_done += 1
                except WireGiveUp:
                    outcome = "skipped"
                    self.exchanges_skipped += 1
                    if self.rule == "asgd":
                        # the in-flight push_pull's fate is UNKNOWN (it
                        # may have landed, reply lost): the anchor can no
                        # longer be trusted — mark it for resync above
                        anchor = None
                    tm = telemetry.active()
                    if tm.enabled:
                        tm.counter("wire.exchange_skipped")
                except CenterUninitialized:
                    # the center respawned with NO usable snapshot (killed
                    # before its first one landed): re-seed the consensus
                    # from this island's CURRENT params and carry on — the
                    # lost center history is a missed exchange, which the
                    # async algebra absorbs.  Crashing here instead would
                    # cascade into the world restart the design forbids.
                    outcome = "reseeded"
                    self.exchanges_skipped += 1
                    tm = telemetry.active()
                    if tm.enabled:
                        tm.counter("wire.center_reseed")
                    try:
                        self.center.ensure_init(
                            jax.device_get(mean_fn(
                                model.step_state["params"])))
                        if self.rule == "asgd":
                            anchor = self.center.pull()
                    except (WireGiveUp, CenterUninitialized):
                        pass           # next exchange gets another shot
                if dist is not None and tm.enabled:
                    # the §25 signals at HOST level for the elastic venue:
                    # islands are separate processes with no cross-process
                    # collective, so this island's ‖w−c‖ distance IS its
                    # replica-divergence proxy — a corrupt perturbation
                    # spikes it within one exchange round, and fleetmon's
                    # replica_divergence rule reads the streamed gauge
                    tm.gauge("numerics.dist_center", dist)
                    tm.gauge("numerics.divergence", dist)
                if rnd is not None:
                    rnd.end(outcome=outcome)
                    rnd = None


class AsyncEASGDTrainer:
    """Partition the visible devices into islands and train asynchronously.

    ≙ the reference's ``EASGD`` launcher topology (server + independent
    workers), with islands of chips instead of single GPUs and a host-side
    center instead of a server rank.
    """

    def __init__(self, model_factory: Callable, config: Optional[dict] = None,
                 rule: str = "easgd"):
        from .mesh import worker_mesh
        self.config = dict(config or {})
        self.rule = str(self.config.get("async_rule", rule))
        self.n_islands = int(self.config.get("async_islands", 2))
        self.alpha = float(self.config.get("alpha", 0.5))
        self.sync_freq = int(self.config.get("sync_freq", 4))
        devices = self.config.get("devices")
        if devices is None:
            devices = jax.devices()
            n_workers = self.config.get("n_workers")
            if n_workers:
                devices = devices[:int(n_workers)]
        assert len(devices) % self.n_islands == 0, (
            f"{len(devices)} devices not divisible into {self.n_islands} islands")
        per = len(devices) // self.n_islands
        self._island_devices = [devices[i * per:(i + 1) * per]
                                for i in range(self.n_islands)]
        self.model_factory = model_factory
        self.stop_event = threading.Event()
        self.islands: List[IslandRunner] = []

        # Center topology (round-4, verdict #5 — cross-process asynchrony):
        #   default: in-memory center, islands are threads in THIS process.
        #   center_serve=true: ALSO serve that center over TCP so islands in
        #     OTHER processes (launcher-supervised, other hosts) join it.
        #   center_addr='host:port': no local center — this process's
        #     islands exchange with the remote server (≙ a reference worker
        #     node talking to the server rank over MPI).
        self._server = None
        addr = self.config.get("center_addr")
        if addr:
            from .center_server import RemoteCenter
            # wire resilience knobs (docs/design.md §15): per-op timeout,
            # bounded-backoff retries with reconnect, give-up deadline —
            # client identity keys the server's dedup window, so island
            # ids must stay unique across processes (island_base)
            self.center = RemoteCenter(
                str(addr), alpha=self.alpha,
                client_id=f"w{self._island_base}",
                op_timeout_s=float(self.config.get("wire_timeout", 20.0)),
                max_retries=int(self.config.get("wire_retries", 8)),
                deadline_s=float(self.config.get("wire_deadline", 60.0)))
        else:
            # Center initializes lazily from the first island's params
            # (ensure_init): all islands share the model seed, so their
            # initial params — and hence the center — agree at t=0.
            self.center = ElasticCenter(alpha=self.alpha)
            if self.config.get("center_serve"):
                from .center_server import CenterServer
                self._server = CenterServer(center=self.center)
                host, port = self._server.start(
                    str(self.config.get("center_host", "127.0.0.1")),
                    int(self.config.get("center_port", 0)))
                self.center_address = f"{host}:{port}"

    def _island_config(self, i: int) -> dict:
        from jax.sharding import Mesh
        devs = np.asarray(self._island_devices[i])
        cfg = dict(self.config)
        cfg["mesh"] = Mesh(devs, (WORKER_AXIS,))
        cfg["size"] = len(devs)
        cfg["rank"] = 0
        # distinct data stream per island — ACROSS processes too
        # (island_base offsets ids when several processes share one remote
        # center); identical param init (model seeds params from 'seed' via
        # the factory — keep that shared)
        cfg["data_seed"] = int(cfg.get("seed", 0)) + self._island_base + i
        return cfg

    @property
    def _island_base(self) -> int:
        return int(self.config.get("island_base", 0))

    def start(self, throttle: Optional[Dict[int, float]] = None) -> None:
        throttle = throttle or {}
        lease_dir = self.config.get("lease_dir")
        for i in range(self.n_islands):
            lease = None
            if lease_dir:
                # per-island heartbeat lease (parallel/membership.py) — the
                # membership controller's liveness signal; island ids are
                # the worker ids so they stay unique across processes
                from .membership import WorkerLease
                lease = WorkerLease(lease_dir, self._island_base + i)
            r = IslandRunner(self._island_base + i, self.model_factory,
                             self._island_config(i),
                             self.center, self.sync_freq, self.stop_event,
                             throttle_s=throttle.get(i, 0.0), rule=self.rule,
                             lease=lease)
            self.islands.append(r)
            r.start()

    def stop_and_join(self, timeout: float = 60.0) -> None:
        self.stop_event.set()
        for r in self.islands:
            r.join(timeout=timeout)
        if hasattr(self.center, "close"):   # RemoteCenter: snapshot the
            try:                            # stats, then drop the socket
                self._center_updates_final = self.center.n_updates
            except Exception:
                pass
            self.center.close()
        if self._server is not None and not self.config.get(
                "center_keep_serving"):
            self._server.stop()
        for r in self.islands:
            if r.error is not None:
                raise r.error

    def run_for(self, seconds: float,
                throttle: Optional[Dict[int, float]] = None) -> None:
        self.start(throttle)
        time.sleep(seconds)
        self.stop_and_join()

    @property
    def center_params(self):
        return self.center.pull()

    # -- recorder-compatible surface ----------------------------------------
    # ``EASGD(...).wait()`` returns this trainer in async mode; session
    # scripts that call ``rec.save(record_dir)`` / read ``epoch_records``
    # keep working (they get island/center progress stats instead of
    # per-iteration curves — the islands run headless threads).

    def stats(self) -> dict:
        cu = getattr(self, "_center_updates_final", None)
        if cu is None:
            cu = self.center.n_updates
        return {"islands": [{"island": r.island_id, "steps": r.steps_done,
                             "exchanges": r.exchanges_done,
                             "exchanges_skipped": r.exchanges_skipped}
                            for r in self.islands],
                "center_updates": cu}

    @property
    def epoch_records(self):
        return [self.stats()]

    def save(self, record_dir: Optional[str] = None) -> None:
        import json
        import os
        d = record_dir or self.config.get("record_dir", "./inc")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "async_easgd_stats.jsonl"), "w") as f:
            f.write(json.dumps(self.stats()) + "\n")
