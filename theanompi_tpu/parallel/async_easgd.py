"""Genuinely asynchronous EASGD — worker islands around a host-side center.

The reference's EASGD (SURVEY.md §3.2) ran a dedicated *server process*
holding center parameters; each worker exchanged with it over MPI Send/Recv
at its own pace — the defining property being that a straggler never blocks
the others.  The in-step :class:`~.exchanger.EASGD_Exchanger` keeps the
update algebra but runs at a synchronous cadence (every chip participates in
one lockstep program), so that property has no analogue there.

This module restores it TPU-natively: the device mesh is partitioned into
**islands** — disjoint sub-meshes, each running its OWN compiled SPMD train
step from its own host thread — and the center lives host-side behind a
lock (:class:`ElasticCenter`, ≙ the reference's server).  Every
``sync_freq`` local steps an island pulls the center, applies the elastic
pairwise update on-device, and pushes its α-scaled delta back.  Islands
never rendezvous with each other: a deliberately slowed island lags while
the rest keep training (tested in ``tests/test_async_easgd.py``).

Update algebra per island exchange (EASGD paper, round-robin form):

    delta_i  = worker_i − center_snapshot        (per worker in the island)
    worker_i ← worker_i − α·delta_i
    center   ← center + α·mean_i delta_i         (atomic, possibly stale)

The center absorbs the island-MEAN delta (the same pmean algebra as the
synchronous exchanger): the reference applied each worker's α·delta one at
a time, which for an island of k workers against one snapshot would give an
effective gain of k·α and diverge for k·α > 1.

Staleness of ``center_snapshot`` between pull and push is inherent to — and
the point of — asynchronous EASGD.

Config surface (run via :class:`AsyncEASGDTrainer` or the ``EASGD`` rule
with ``easgd_mode='async'``): ``async_islands`` (number of islands),
``alpha``, ``sync_freq``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import WORKER_AXIS


class ElasticCenter:
    """Host-side center parameter store (≙ the reference's EASGD server).

    Thread-safe: islands call :meth:`pull` / :meth:`push_delta` at their own
    cadence; the lock serializes center updates exactly like the reference
    server serving one worker at a time.
    """

    def __init__(self, params=None, alpha: float = 0.5):
        self.alpha = float(alpha)
        self._center = None if params is None else \
            jax.tree.map(lambda x: np.array(x, np.float32), params)
        self._lock = threading.Lock()
        self.n_updates = 0            # exchanges absorbed (all islands)
        self.updates_by_island: Dict[int, int] = {}

    def ensure_init(self, params) -> None:
        """Lazy init from the first island to arrive — all islands share the
        model seed, so their initial params (and hence the center) agree;
        avoids building a throwaway probe model just to read its params."""
        with self._lock:
            if self._center is None:
                self._center = jax.tree.map(
                    lambda x: np.array(x, np.float32), params)

    def pull(self):
        with self._lock:
            assert self._center is not None, "center not initialized yet"
            return jax.tree.map(np.array, self._center)

    def push_delta(self, delta_mean, island: int) -> None:
        """center += α·mean_i delta_i for one island's workers."""
        a = self.alpha
        with self._lock:
            self._center = jax.tree.map(
                lambda c, d: c + a * np.asarray(d, np.float32),
                self._center, delta_mean)
            self.n_updates += 1
            self.updates_by_island[island] = \
                self.updates_by_island.get(island, 0) + 1


class IslandRunner(threading.Thread):
    """One island: a sub-mesh, its own compiled train step, its own pace.

    ``model_factory(config) -> model`` builds the island's model; the island
    config carries its sub-``mesh``, its ``size``, and a distinct ``seed`` so
    islands consume different data streams (the reference's workers likewise
    each walked their own shard).
    """

    def __init__(self, island_id: int, model_factory: Callable, config: dict,
                 center: ElasticCenter, sync_freq: int,
                 stop_event: threading.Event,
                 throttle_s: float = 0.0):
        super().__init__(daemon=True)
        self.island_id = island_id
        self.config = config
        self.center = center
        self.sync_freq = int(sync_freq)
        self.stop_event = stop_event
        self.throttle_s = float(throttle_s)   # test hook: deliberate straggler
        self.steps_done = 0
        self.exchanges_done = 0
        self.error: Optional[BaseException] = None
        self._model_factory = model_factory

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:      # surfaced by AsyncEASGDTrainer.join
            self.error = e

    def _run(self) -> None:
        from .exchanger import Exchanger

        model = self._model_factory(self.config)
        self.center.ensure_init(jax.device_get(model.params))
        # Local-only updates inside the island: the base Exchanger's
        # step_update is exactly the local optimizer step.
        exch = Exchanger(self.config)
        model.compile_iter_fns(exch)
        model.data.shuffle_data(int(self.config.get("data_seed", 0)))
        mesh = model.mesh
        n = mesh.shape[WORKER_AXIS]
        alpha = self.center.alpha

        # Jitted elastic update: (boxed params, replicated center) ->
        # (boxed new params, boxed per-worker deltas summed on host later).
        def elastic(params_boxed, center):
            delta = jax.tree.map(lambda p, c: p - c[None], params_boxed, center)
            new_params = jax.tree.map(lambda p, d: p - alpha * d,
                                      params_boxed, delta)
            delta_mean = jax.tree.map(lambda d: jnp.mean(d, axis=0), delta)
            return new_params, delta_mean

        elastic_fn = jax.jit(elastic)

        count = 0
        while not self.stop_event.is_set():
            count += 1
            model.train_iter(count, None)
            self.steps_done += 1
            if self.throttle_s:
                time.sleep(self.throttle_s)
            if count % self.sync_freq == 0:
                center = self.center.pull()
                new_params, delta_mean = elastic_fn(
                    model.step_state["params"], center)
                model.step_state["params"] = new_params
                self.center.push_delta(jax.device_get(delta_mean),
                                       self.island_id)
                self.exchanges_done += 1


class AsyncEASGDTrainer:
    """Partition the visible devices into islands and train asynchronously.

    ≙ the reference's ``EASGD`` launcher topology (server + independent
    workers), with islands of chips instead of single GPUs and a host-side
    center instead of a server rank.
    """

    def __init__(self, model_factory: Callable, config: Optional[dict] = None):
        from .mesh import worker_mesh
        self.config = dict(config or {})
        self.n_islands = int(self.config.get("async_islands", 2))
        self.alpha = float(self.config.get("alpha", 0.5))
        self.sync_freq = int(self.config.get("sync_freq", 4))
        devices = self.config.get("devices")
        if devices is None:
            devices = jax.devices()
            n_workers = self.config.get("n_workers")
            if n_workers:
                devices = devices[:int(n_workers)]
        assert len(devices) % self.n_islands == 0, (
            f"{len(devices)} devices not divisible into {self.n_islands} islands")
        per = len(devices) // self.n_islands
        self._island_devices = [devices[i * per:(i + 1) * per]
                                for i in range(self.n_islands)]
        self.model_factory = model_factory
        self.stop_event = threading.Event()
        self.islands: List[IslandRunner] = []

        # Center initializes lazily from the first island's params
        # (ElasticCenter.ensure_init): all islands share the model seed, so
        # their initial params — and hence the center — agree at t=0.
        self.center = ElasticCenter(alpha=self.alpha)

    def _island_config(self, i: int) -> dict:
        from jax.sharding import Mesh
        devs = np.asarray(self._island_devices[i])
        cfg = dict(self.config)
        cfg["mesh"] = Mesh(devs, (WORKER_AXIS,))
        cfg["size"] = len(devs)
        cfg["rank"] = 0
        # distinct data stream per island; identical param init (model seeds
        # params from 'seed' via the factory — keep that shared)
        cfg["data_seed"] = int(cfg.get("seed", 0)) + i
        return cfg

    def start(self, throttle: Optional[Dict[int, float]] = None) -> None:
        throttle = throttle or {}
        for i in range(self.n_islands):
            r = IslandRunner(i, self.model_factory, self._island_config(i),
                             self.center, self.sync_freq, self.stop_event,
                             throttle_s=throttle.get(i, 0.0))
            self.islands.append(r)
            r.start()

    def stop_and_join(self, timeout: float = 60.0) -> None:
        self.stop_event.set()
        for r in self.islands:
            r.join(timeout=timeout)
        for r in self.islands:
            if r.error is not None:
                raise r.error

    def run_for(self, seconds: float,
                throttle: Optional[Dict[int, float]] = None) -> None:
        self.start(throttle)
        time.sleep(seconds)
        self.stop_and_join()

    @property
    def center_params(self):
        return self.center.pull()

    # -- recorder-compatible surface ----------------------------------------
    # ``EASGD(...).wait()`` returns this trainer in async mode; session
    # scripts that call ``rec.save(record_dir)`` / read ``epoch_records``
    # keep working (they get island/center progress stats instead of
    # per-iteration curves — the islands run headless threads).

    def stats(self) -> dict:
        return {"islands": [{"island": r.island_id, "steps": r.steps_done,
                             "exchanges": r.exchanges_done}
                            for r in self.islands],
                "center_updates": self.center.n_updates}

    @property
    def epoch_records(self):
        return [self.stats()]

    def save(self, record_dir: Optional[str] = None) -> None:
        import json
        import os
        d = record_dir or self.config.get("record_dir", "./inc")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "async_easgd_stats.jsonl"), "w") as f:
            f.write(json.dumps(self.stats()) + "\n")
