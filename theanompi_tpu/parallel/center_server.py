"""Cross-process elastic center — the reference's EASGD/ASGD *server* over
a socket.

The reference ran a dedicated MPI server RANK holding center parameters;
workers on other nodes exchanged with it over ``MPI.Send/Recv`` at their own
pace (SURVEY.md §3.2).  ``async_easgd.ElasticCenter`` restores the algebra
for islands inside ONE process; this module takes it across processes — the
launcher's supervised subprocesses, or genuinely different hosts — with:

* :class:`CenterServer` — a TCP server wrapping an :class:`ElasticCenter`,
  one thread per client connection, the center lock serializing updates
  exactly like the reference server serving one worker at a time.
* :class:`RemoteCenter` — a client with the SAME duck-typed surface as
  ``ElasticCenter`` (``ensure_init`` / ``pull`` / ``push_delta`` /
  ``push_pull``), so :class:`~.async_easgd.IslandRunner` works unchanged
  whether its center is in-memory or remote.

Wire format (no pickle — arrays only): each message is
``[4-byte header len][JSON header][4-byte body len][npz body]`` where the
npz holds the pytree's leaves keyed by flatten order (``leaf0``, ``leaf1``,
…).  Both ends run the same model config, so the treedef is shared
knowledge; the server never needs it (its algebra is leafwise).

Ops: ``init`` (idempotent center seed), ``pull`` → center leaves,
``push`` (EASGD: center += α·delta_mean), ``push_pull`` (ASGD downpour:
center += delta_mean, returns the fresh center atomically — the reference's
accumulated-gradient round-trip), ``demote``/``readmit`` (elastic
membership: a demoted island's pushes are dropped, pulls still serve —
``parallel/membership.py``), ``stats``.
"""

from __future__ import annotations

import io
import json
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from .async_easgd import ElasticCenter


# -- framing ----------------------------------------------------------------

def _pack_leaves(leaves: List[np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf{i}": np.asarray(x, np.float32)
                     for i, x in enumerate(leaves)})
    return buf.getvalue()


def _unpack_leaves(body: bytes) -> List[np.ndarray]:
    if not body:
        return []
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        return [z[f"leaf{i}"] for i in range(len(z.files))]


def _send_msg(sock: socket.socket, header: dict, body: bytes = b"") -> None:
    h = json.dumps(header).encode()
    sock.sendall(struct.pack("!I", len(h)) + h
                 + struct.pack("!I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("center connection closed mid-message")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    (hlen,) = struct.unpack("!I", _recv_exact(sock, 4))
    header = json.loads(_recv_exact(sock, hlen))
    (blen,) = struct.unpack("!I", _recv_exact(sock, 4))
    return header, _recv_exact(sock, blen) if blen else b""


# -- server -----------------------------------------------------------------

class CenterServer:
    """Serve an :class:`ElasticCenter` over TCP (≙ the reference's server
    rank).  ``start()`` binds and returns ``(host, port)``; serving happens
    on daemon threads, one per connection."""

    def __init__(self, alpha: float = 0.5,
                 center: Optional[ElasticCenter] = None):
        # pass an existing center to ALSO serve in-process islands' store
        # (AsyncEASGDTrainer center_serve mode) — leaf-list wire ops and
        # pytree local ops share the canonical flat store
        self.center = center if center is not None \
            else ElasticCenter(alpha=alpha)
        self._srv: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        center = self.center

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):          # one connection: a request loop
                try:
                    while True:
                        header, body = _recv_msg(self.request)
                        try:
                            self._dispatch(header, body)
                        except (ConnectionError, OSError):
                            raise
                        except Exception as e:
                            # op-level failures (shape/leaf-count mismatch,
                            # pull-before-init) reply with the REAL cause —
                            # a bare connection close would surface to the
                            # client as an opaque network error
                            _send_msg(self.request,
                                      {"ok": False, "error": repr(e)})
                except (ConnectionError, OSError):
                    return             # client went away — fine

            def _dispatch(self, header, body):
                op = header.get("op")
                if op == "init":
                    center.ensure_init_leaves(_unpack_leaves(body))
                    _send_msg(self.request, {"ok": True})
                elif op == "pull":
                    _send_msg(self.request, {"ok": True},
                              _pack_leaves(center.pull_leaves()))
                elif op == "push":
                    center.push_delta_leaves(_unpack_leaves(body),
                                             int(header["island"]))
                    _send_msg(self.request, {"ok": True})
                elif op == "push_pull":
                    leaves = center.push_pull_leaves(
                        _unpack_leaves(body), int(header["island"]))
                    _send_msg(self.request, {"ok": True},
                              _pack_leaves(leaves))
                elif op == "demote":
                    # elastic membership (parallel/membership.py): further
                    # pushes from this island are dropped at the center
                    center.demote_island(int(header["island"]))
                    _send_msg(self.request, {"ok": True})
                elif op == "readmit":
                    center.readmit_island(int(header["island"]))
                    _send_msg(self.request, {"ok": True})
                elif op == "stats":
                    _send_msg(self.request,
                              {"ok": True, **center.stats_snapshot()})
                else:
                    _send_msg(self.request,
                              {"ok": False, "error": f"unknown op {op!r}"})

        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._srv = socketserver.ThreadingTCPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._srv.server_address[:2]

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None


# -- client -----------------------------------------------------------------

class RemoteCenter:
    """``ElasticCenter``-shaped client: every call is one request/response
    round-trip on a persistent connection (a lock serializes this process's
    callers; the SERVER's lock serializes across processes)."""

    def __init__(self, addr: str, alpha: float = 0.5,
                 connect_timeout: float = 30.0):
        host, port = addr.rsplit(":", 1)
        self.alpha = float(alpha)      # kept for IslandRunner's elastic math
        self._treedef = None
        self._lock = threading.Lock()
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)

    def _roundtrip(self, header: dict, body: bytes = b"") -> Tuple[dict, bytes]:
        with self._lock:
            _send_msg(self._sock, header, body)
            resp, rbody = _recv_msg(self._sock)
        if not resp.get("ok"):
            raise RuntimeError(f"center server error: {resp.get('error')}")
        return resp, rbody

    def _leaves(self, tree) -> Tuple[List[np.ndarray], object]:
        leaves, treedef = jax.tree.flatten(tree)
        return [np.asarray(x, np.float32) for x in leaves], treedef

    def ensure_init(self, params) -> None:
        leaves, self._treedef = self._leaves(params)
        self._roundtrip({"op": "init"}, _pack_leaves(leaves))

    def pull(self):
        _, body = self._roundtrip({"op": "pull"})
        leaves = _unpack_leaves(body)
        assert self._treedef is not None, "pull before ensure_init"
        return jax.tree.unflatten(self._treedef, leaves)

    def push_delta(self, delta_mean, island: int) -> None:
        leaves, _ = self._leaves(delta_mean)
        self._roundtrip({"op": "push", "island": island},
                        _pack_leaves(leaves))

    def push_pull(self, delta_mean, island: int):
        leaves, _ = self._leaves(delta_mean)
        _, body = self._roundtrip({"op": "push_pull", "island": island},
                                  _pack_leaves(leaves))
        assert self._treedef is not None, "push_pull before ensure_init"
        return jax.tree.unflatten(self._treedef, _unpack_leaves(body))

    def demote_island(self, island: int) -> None:
        self._roundtrip({"op": "demote", "island": int(island)})

    def readmit_island(self, island: int) -> None:
        self._roundtrip({"op": "readmit", "island": int(island)})

    def stats(self) -> dict:
        resp, _ = self._roundtrip({"op": "stats"})
        return resp

    @property
    def n_updates(self) -> int:
        return int(self.stats()["n_updates"])

    @property
    def updates_by_island(self) -> Dict[int, int]:
        return {int(k): v for k, v in self.stats()["by_island"].items()}

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
