"""Cross-process elastic center — the reference's EASGD/ASGD *server* over
a socket, now crash-recoverable behind the resilient wire layer.

The reference ran a dedicated MPI server RANK holding center parameters;
workers on other nodes exchanged with it over ``MPI.Send/Recv`` at their own
pace (SURVEY.md §3.2).  ``async_easgd.ElasticCenter`` restores the algebra
for islands inside ONE process; this module takes it across processes — the
launcher's supervised subprocesses, or genuinely different hosts — with:

* :class:`CenterServer` — a TCP server wrapping an :class:`ElasticCenter`,
  one thread per client connection, the center lock serializing updates
  exactly like the reference server serving one worker at a time.  Round 14
  adds the ``parallel/wire.py`` contract (docs/design.md §15): version/CRC
  framing, per-connection idle timeouts (a wedged client can't pin a
  handler thread forever), a :class:`~.wire.DedupWindow` so a retried
  ``push`` that actually landed is applied EXACTLY once, and periodic
  crash-atomic snapshots (params + membership + dedup state) the center
  restores from after a SIGKILL — the supervisor respawns it like a worker
  and the clients ride out the outage on wire retries.
* :class:`RemoteCenter` — a client with the SAME duck-typed surface as
  ``ElasticCenter`` (``ensure_init`` / ``pull`` / ``push_delta`` /
  ``push_pull``), so :class:`~.async_easgd.IslandRunner` works unchanged
  whether its center is in-memory or remote.  Built on
  :class:`~.wire.WireClient`: per-op timeouts, bounded-backoff retries
  with reconnect, idempotency tokens.

Wire format (no pickle — arrays only): each message is
``[4B header len][4B header CRC][JSON header][4B body len][npz body]``
where the npz holds the pytree's leaves keyed by flatten order
(``leaf0``, ``leaf1``, …).  Both ends run the same model config, so the treedef is shared
knowledge; the server never needs it (its algebra is leafwise).

Ops: ``init`` (idempotent center seed), ``pull`` → center leaves,
``push`` (EASGD: center += α·delta_mean), ``push_pull`` (ASGD downpour:
center += delta_mean, returns the fresh center atomically — the reference's
accumulated-gradient round-trip), ``demote``/``readmit`` (elastic
membership: a demoted island's pushes are dropped, pulls still serve —
``parallel/membership.py``), ``stats``.

jax imports lazily (client-side tree flatten only): the center server
process is numpy-level work, and a light import keeps its supervised
respawn-from-snapshot inside the clients' retry window.
"""

from __future__ import annotations

import json
import os
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import wire
from .wire import (ConnectionClosed, CorruptPayload, DedupWindow,
                   TruncatedMessage, VersionMismatch, WireClient,
                   pack_leaves, unpack_leaves)

try:
    from ..utils import telemetry, tracing
except ImportError:        # file-path load (jax-free tooling): absolute
    from theanompi_tpu.utils import telemetry, tracing

# back-compat aliases — the framing now lives in parallel/wire.py
_pack_leaves = pack_leaves
_unpack_leaves = unpack_leaves
_send_msg = wire.send_msg
_recv_msg = wire.recv_msg


def snapshot_path(snapshot_dir: str) -> str:
    return os.path.join(snapshot_dir, "center_state.npz")


def load_snapshot(path: str):
    """``(leaves, meta)`` from one center snapshot file — the ONE parser
    of the on-disk format (``CenterServer.restore`` and ``run_elastic``'s
    offline final-state read both go through it, so the layout can't
    drift between writer and readers).  Raises on a missing/torn file."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(bytes(z["_meta"]).decode())
        n = len([k for k in z.files if k.startswith("leaf")])
        leaves = [z[f"leaf{i}"] for i in range(n)]
    return leaves, meta


# -- server -----------------------------------------------------------------

class CenterServer:
    """Serve an :class:`ElasticCenter` over TCP (≙ the reference's server
    rank).  ``start()`` binds and returns ``(host, port)``; serving happens
    on daemon threads, one per connection.

    ``snapshot_dir`` enables crash recovery: the full center state —
    params, membership (``demoted``/``dropped_by_island``), update
    counters, and the dedup window's token high-water marks — is written
    every ``snapshot_every_s`` seconds (only when it changed) as ONE
    crash-atomic npz (the ``utils/checkpoint.py`` write-tmp → fsync →
    ``os.replace`` discipline: a SIGKILL mid-save leaves the previous
    complete snapshot, never a torn one).  ``restore()`` reloads it, so a
    supervisor can respawn the center and clients — riding the outage on
    wire retries — resume against the recovered state with their retried
    pushes still deduplicated."""

    def __init__(self, alpha: float = 0.5, center=None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every_s: float = 2.0,
                 idle_timeout_s: float = 120.0,
                 dedup_depth: int = 128):
        from .async_easgd import ElasticCenter

        # pass an existing center to ALSO serve in-process islands' store
        # (AsyncEASGDTrainer center_serve mode) — leaf-list wire ops and
        # pytree local ops share the canonical flat store
        self.center = center if center is not None \
            else ElasticCenter(alpha=alpha)
        self.dedup = DedupWindow(depth=dedup_depth)
        self.snapshot_dir = snapshot_dir
        self.snapshot_every_s = float(snapshot_every_s)
        self.idle_timeout_s = float(idle_timeout_s)
        self._srv: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._snap_thread: Optional[threading.Thread] = None
        self._snap_halt = threading.Event()
        self._snap_mark: Optional[tuple] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    # -- crash-recovery snapshots -------------------------------------------

    def _state_mark(self) -> tuple:
        """Cheap change detector — snapshot only when the state moved.
        The dedup HWMs are read through the locked accessor: handler
        threads mutate them concurrently with this snapshot-loop read."""
        st = self.center.stats_snapshot()
        return (st["n_updates"], tuple(st["demoted"]),
                sum(st["dropped_by_island"].values()),
                sum(self.dedup.hwm_snapshot().values()))

    def snapshot(self) -> Optional[str]:
        """One crash-atomic snapshot file (single npz: leaves + a JSON
        meta blob), or None when the center is uninitialized / no dir."""
        if not self.snapshot_dir:
            return None
        with self.center._lock:
            if self.center._leaves is None:
                return None
            leaves = [np.array(x) for x in self.center._leaves]
            meta = {"alpha": self.center.alpha,
                    "n_updates": self.center.n_updates,
                    "updates_by_island":
                        {str(k): v for k, v in
                         self.center.updates_by_island.items()},
                    "demoted": sorted(self.center.demoted),
                    "dropped_by_island":
                        {str(k): v for k, v in
                         self.center.dropped_by_island.items()},
                    "dedup": self.dedup.snapshot(),
                    "ts": time.time()}
        from ..utils.checkpoint import _fsync_write
        os.makedirs(self.snapshot_dir, exist_ok=True)
        path = snapshot_path(self.snapshot_dir)
        blob = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
        _fsync_write(path, lambda f: np.savez(
            f, _meta=blob, **{f"leaf{i}": x for i, x in enumerate(leaves)}))
        return path

    def restore(self, snapshot_dir: Optional[str] = None) -> bool:
        """Reload the newest snapshot (if any): params, counters,
        membership, and the dedup token high-water marks — a client
        retrying a push that landed BEFORE the crash is still answered
        from the window, not reapplied."""
        d = snapshot_dir or self.snapshot_dir
        if not d:
            return False
        path = snapshot_path(d)
        if not os.path.exists(path):
            return False
        try:
            leaves, meta = load_snapshot(path)
        except Exception as e:
            import sys
            print(f"center: snapshot {path} unreadable ({e!r}) — "
                  f"starting fresh", file=sys.stderr, flush=True)
            return False
        c = self.center
        with c._lock:
            c._leaves = [np.array(x, np.float32) for x in leaves]
            c.alpha = float(meta.get("alpha", c.alpha))
            c.n_updates = int(meta.get("n_updates", 0))
            c.updates_by_island = {int(k): int(v) for k, v in
                                   meta.get("updates_by_island",
                                            {}).items()}
            c.demoted = set(int(x) for x in meta.get("demoted", ()))
            c.dropped_by_island = {int(k): int(v) for k, v in
                                   meta.get("dropped_by_island",
                                            {}).items()}
        self.dedup.restore(meta.get("dedup") or {})
        return True

    def _snapshot_loop(self) -> None:
        while not self._snap_halt.wait(self.snapshot_every_s):
            try:
                mark = self._state_mark()
                if mark != self._snap_mark:
                    self.snapshot()
                    self._snap_mark = mark
            except Exception:
                pass               # a snapshot must never kill serving

    # -- serving ------------------------------------------------------------

    def start(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        import socket as _socket
        center = self.center
        dedup = self.dedup
        idle_timeout = self.idle_timeout_s
        socket_timeout_errors = (_socket.timeout, TimeoutError)

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):          # one connection: a request loop
                # a wedged/SIGSTOPped client must not pin this handler
                # thread forever — idle past the timeout closes it
                self.request.settimeout(idle_timeout)
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        try:
                            header, body = wire.recv_msg(self.request)
                        except VersionMismatch as e:
                            # deliberately loud, with both versions —
                            # reply, then drop the connection (nothing
                            # else this peer sends can be trusted)
                            wire.send_msg(self.request,
                                          {"ok": False, "error": str(e)})
                            return
                        except CorruptPayload as e:
                            # bytes, not the op, are bad: framing stayed
                            # aligned, so ask the client to retry the
                            # SAME token on this connection
                            tm = telemetry.active()
                            if tm.enabled:
                                tm.counter("wire.corrupt")
                            wire.send_msg(self.request,
                                          {"ok": False, "error": str(e),
                                           "retry": True})
                            continue
                        try:
                            self._dispatch(header, body)
                        except (ConnectionError, OSError):
                            raise
                        except Exception as e:
                            # op-level failures (shape/leaf-count
                            # mismatch, pull-before-init) reply with the
                            # REAL cause — a bare connection close would
                            # surface to the client as an opaque network
                            # error
                            wire.send_msg(self.request,
                                          {"ok": False, "error": repr(e)})
                except socket_timeout_errors:
                    return             # idle/wedged client — free the thread
                except (ConnectionClosed, TruncatedMessage):
                    return             # client went away — fine
                except (ConnectionError, OSError):
                    return
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

            def _dispatch(self, header, body):
                op = header.get("op")
                tok = header.get("tok")
                trc = header.get("trace")     # v2 causal-tracing context
                t_acc = time.time()           # request accepted (parsed)

                def reply(hdr, rbody=b"", srv=None, dedup_reply=False):
                    """Send one reply; when the request carried trace
                    context, stamp the server's ``center.<op>`` span into
                    the telemetry stream (parented to the client's
                    ``wire.<op>`` span — the cross-process join).  A
                    deduplicated twin is tagged so the trace assembly
                    never double-counts it on the critical path."""
                    h = dict(hdr)
                    if srv is not None:
                        h["srv"] = srv
                    wire.send_msg(self.request, h, rbody)
                    tm = telemetry.active()
                    if trc and tm.enabled:
                        tracing.emit_server_span(
                            tm, trc, str(op), t0=t_acc,
                            dt=time.time() - t_acc,
                            q=(srv or {}).get("q"), a=(srv or {}).get("a"),
                            island=header.get("island"),
                            dedup=dedup_reply, ok=bool(h.get("ok")))

                def timed(fn):
                    """Run ``fn`` under the center lock, splitting server
                    time into ``q`` (lock wait — the center serializes
                    every client here, so lock wait IS the center queue)
                    and ``a`` (the apply under the lock).  The center's
                    own methods re-enter the RLock for free."""
                    t_q = time.time()
                    with center._lock:
                        q = time.time() - t_q
                        t_a = time.time()
                        out = fn()
                        return out, {"q": round(q, 6),
                                     "a": round(time.time() - t_a, 6)}

                if op in ("push", "push_pull"):
                    dup, cached = dedup.check(tok, op)
                    if dup:
                        if cached is wire.INFLIGHT:
                            # the original is mid-application on another
                            # handler thread — it may yet FAIL and release
                            # the claim, so the twin must not be acked:
                            # tell the client to retry the same token
                            reply({"ok": False, "retry": True,
                                   "busy": True,
                                   "error": "request in flight — retry"},
                                  dedup_reply=True)
                            return
                        # a retry of a request that already LANDED: reply
                        # without reapplying — exactly-once application.
                        # The dedup marker rides the reply so the CLIENT
                        # side (a retry whose original landed) tags its
                        # span too; a chaos-proxy duplicate's twin reply
                        # is swallowed by the proxy, and only this
                        # server-side tag remains — which is the one the
                        # critical path reads.
                        hdr = dict(cached[0]) if cached is not None \
                            else {"ok": True}
                        hdr["dedup"] = True
                        if cached is not None and cached[1] is not None:
                            reply(hdr, cached[1], dedup_reply=True)
                        elif op == "push":
                            reply(hdr, dedup_reply=True)
                        else:
                            # push_pull replay: the CURRENT center is the
                            # synthesized body — a valid (fresher) anchor
                            reply(hdr, pack_leaves(center.pull_leaves()),
                                  dedup_reply=True)
                        return
                if op in ("pull", "push", "push_pull") and \
                        center._leaves is None:
                    # a respawned center with no usable snapshot: tell the
                    # clients STRUCTURALLY (they re-seed via ensure_init
                    # and carry on) instead of an opaque assertion repr
                    if op in ("push", "push_pull"):
                        dedup.release(tok, op)     # claim withdrawn
                    reply({"ok": False, "uninit": True,
                           "error": "center not initialized (no "
                                    "snapshot survived?) — "
                                    "re-seed with ensure_init"})
                    return
                try:
                    if op == "init":
                        leaves_in = unpack_leaves(body)
                        _, srv = timed(
                            lambda: center.ensure_init_leaves(leaves_in))
                        reply({"ok": True}, srv=srv)
                    elif op == "pull":
                        leaves, srv = timed(center.pull_leaves)
                        reply({"ok": True}, pack_leaves(leaves), srv=srv)
                    elif op == "push":
                        leaves_in = unpack_leaves(body)
                        _, srv = timed(lambda: center.push_delta_leaves(
                            leaves_in, int(header["island"])))
                        dedup.record(tok, op, {"ok": True, "srv": srv})
                        reply({"ok": True}, srv=srv)
                    elif op == "push_pull":
                        leaves_in = unpack_leaves(body)
                        leaves, srv = timed(lambda: center.push_pull_leaves(
                            leaves_in, int(header["island"])))
                        # record the token but not the (model-sized) body:
                        # a replay is answered with the CURRENT center,
                        # which the downpour algebra accepts as its fresh
                        # anchor — exactly-once application is what matters
                        dedup.record(tok, op, {"ok": True, "srv": srv},
                                     reply_body=None)
                        reply({"ok": True}, pack_leaves(leaves), srv=srv)
                    elif op == "demote":
                        # elastic membership (parallel/membership.py):
                        # further pushes from this island are dropped
                        center.demote_island(int(header["island"]))
                        reply({"ok": True})
                    elif op == "readmit":
                        center.readmit_island(int(header["island"]))
                        reply({"ok": True})
                    elif op == "stats":
                        # hwm_snapshot: another handler thread may be
                        # mid-record — a bare dict(dedup.seq_hwm) races
                        reply({"ok": True, **center.stats_snapshot(),
                               "dedup_hits": dedup.hits,
                               "seq_hwm": dedup.hwm_snapshot()})
                    else:
                        reply({"ok": False,
                               "error": f"unknown op {op!r}"})
                except Exception:
                    if op in ("push", "push_pull"):
                        dedup.release(tok, op)   # failed: claim withdrawn
                    raise

        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._srv = socketserver.ThreadingTCPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        if self.snapshot_dir:
            self._snap_thread = threading.Thread(target=self._snapshot_loop,
                                                 daemon=True)
            self._snap_thread.start()
        return self._srv.server_address[:2]

    def stop(self, final_snapshot: bool = True) -> None:
        self._snap_halt.set()
        if self._snap_thread is not None:
            self._snap_thread.join(timeout=10)
            self._snap_thread = None
        if final_snapshot and self.snapshot_dir:
            try:
                self.snapshot()
            except Exception:
                pass
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
            # a real center death severs every in-flight connection; an
            # in-process stop must too, or handler threads keep serving a
            # 'dead' center (and tests of the outage path test nothing)
            with self._conns_lock:
                conns = list(self._conns)
                self._conns.clear()
            for c in conns:
                try:
                    c.close()
                except OSError:
                    pass
        # bounded join of the serve thread: shutdown() returns once the
        # serve_forever loop EXITS, but the thread can still be unwinding
        # — a stop() immediately followed by a same-port restart (the
        # supervised-respawn tests) must not race it (tpulint
        # daemon-discipline)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


# -- client -----------------------------------------------------------------

class RemoteCenter:
    """``ElasticCenter``-shaped client on the resilient wire: every call is
    one tokened request/response round-trip, retried with bounded backoff
    and reconnect through timeouts, drops, corruption, and center
    restarts.  Gives up with a clear :class:`~.wire.WireGiveUp` (attempts,
    elapsed, last error) when the center stays unreachable past the
    deadline — callers decide whether that is fatal (startup restore) or
    survivable (a missed exchange; the island keeps training locally)."""

    def __init__(self, addr: str, alpha: float = 0.5,
                 client_id=None, connect_timeout: float = 5.0,
                 op_timeout_s: float = 20.0, max_retries: int = 8,
                 deadline_s: float = 120.0, telemetry_=None):
        self.alpha = float(alpha)      # kept for IslandRunner's elastic math
        self._treedef = None
        self._wire = WireClient(addr, client_id=client_id,
                                op_timeout_s=op_timeout_s,
                                connect_timeout_s=connect_timeout,
                                max_retries=max_retries,
                                deadline_s=deadline_s,
                                telemetry_=telemetry_)

    def _roundtrip(self, header: dict, body: bytes = b"",
                   trace: Optional[dict] = None) -> Tuple[dict, bytes]:
        return self._wire.request(header, body, trace=trace)

    def _leaves(self, tree) -> Tuple[List[np.ndarray], object]:
        import jax
        leaves, treedef = jax.tree.flatten(tree)
        return [np.asarray(x, np.float32) for x in leaves], treedef

    # ``trace`` on every op: the caller's span context (Span.ctx()) —
    # propagated through the wire header so the server's handler span
    # joins the client's round (docs/design.md §17).  None (the default,
    # and the whole surface pre-v2) traces nothing.

    def ensure_init(self, params, trace: Optional[dict] = None) -> None:
        leaves, self._treedef = self._leaves(params)
        self._roundtrip({"op": "init"}, pack_leaves(leaves), trace=trace)

    def pull(self, trace: Optional[dict] = None):
        # jax only AFTER the wire round-trip: the reply is what needs
        # unflattening, and the jax-free protocol probe (schema-drift
        # §21) drives this surface against a stubbed wire
        _, body = self._roundtrip({"op": "pull"}, trace=trace)
        import jax
        leaves = unpack_leaves(body)
        assert self._treedef is not None, "pull before ensure_init"
        return jax.tree.unflatten(self._treedef, leaves)

    def pull_leaves(self, trace: Optional[dict] = None) -> List[np.ndarray]:
        _, body = self._roundtrip({"op": "pull"}, trace=trace)
        return unpack_leaves(body)

    def push_delta(self, delta_mean, island: int,
                   trace: Optional[dict] = None) -> None:
        leaves, _ = self._leaves(delta_mean)
        self._roundtrip({"op": "push", "island": island},
                        pack_leaves(leaves), trace=trace)

    def push_pull(self, delta_mean, island: int,
                  trace: Optional[dict] = None):
        leaves, _ = self._leaves(delta_mean)
        _, body = self._roundtrip({"op": "push_pull", "island": island},
                                  pack_leaves(leaves), trace=trace)
        import jax
        assert self._treedef is not None, "push_pull before ensure_init"
        return jax.tree.unflatten(self._treedef, unpack_leaves(body))

    def demote_island(self, island: int) -> None:
        self._roundtrip({"op": "demote", "island": int(island)})

    def readmit_island(self, island: int) -> None:
        self._roundtrip({"op": "readmit", "island": int(island)})

    def stats(self) -> dict:
        resp, _ = self._roundtrip({"op": "stats"})
        return resp

    @property
    def n_updates(self) -> int:
        return int(self.stats()["n_updates"])

    @property
    def updates_by_island(self) -> Dict[int, int]:
        return {int(k): v for k, v in self.stats()["by_island"].items()}

    def close(self) -> None:
        self._wire.close()


# -- center process CLI ------------------------------------------------------

def center_main(argv: Optional[List[str]] = None) -> int:
    """Run the center as its OWN supervised process:
    ``python -m theanompi_tpu.parallel.center_server --port P ...``.

    The elastic supervisor (``membership.ElasticSupervisor``) spawns this
    like a worker: it beats a lease (id ``--lease-id``, default 0) so a
    wedged center is detected, restores from ``--snapshot-dir`` on
    (re)start, snapshots periodically, and serves until SIGTERM.  Clients
    ride a restart out on wire retries; the supervisor emits the
    ``center_down``/``center_restored`` event pair around it."""
    import argparse
    import signal
    import sys

    ap = argparse.ArgumentParser(description=center_main.__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True,
                    help="fixed port — clients reconnect here across "
                         "center restarts")
    ap.add_argument("--alpha", type=float, default=0.5)
    ap.add_argument("--snapshot-dir", default=None)
    ap.add_argument("--snapshot-every", type=float, default=2.0)
    ap.add_argument("--idle-timeout", type=float, default=120.0)
    ap.add_argument("--lease-dir", default=None)
    ap.add_argument("--lease-id", type=int, default=0)
    ap.add_argument("--record-dir", default=None)
    ap.add_argument("--run-id", default=None)
    ap.add_argument("--metrics-addr", default=None,
                    help="fleet-health collector address (utils/fleetmon"
                         ") — the center streams metric snapshots there")
    ap.add_argument("--max-seconds", type=float, default=0.0,
                    help="self-terminate after this long (0 = forever)")
    args = ap.parse_args(argv)

    # flush_every=2: the center emits low-rate, high-value events (server
    # spans, dedup audits) and dies by SIGKILL in the chaos gates — a
    # 64-event write buffer would lose the very spans the trace assembly
    # joins (≥95% join-rate acceptance, docs/design.md §17)
    tm = telemetry.init({"record_dir": args.record_dir,
                         "rank": -1, "run_id": args.run_id,
                         "telemetry_flush_every": 2}) \
        if args.record_dir else telemetry.active()

    srv = CenterServer(alpha=args.alpha, snapshot_dir=args.snapshot_dir,
                       snapshot_every_s=args.snapshot_every,
                       idle_timeout_s=args.idle_timeout)
    restored = srv.restore()
    host, port = srv.start(args.host, args.port)
    print(f"center: serving on {host}:{port} "
          f"({'restored from snapshot' if restored else 'fresh'})",
          file=sys.stderr, flush=True)

    statusz = None
    if args.record_dir:
        # live ops endpoint (docs/design.md §17): health/uptime/last-N
        # queries over the wire framing; scripts/fleetz.py aggregates
        statusz = tracing.StatuszServer(
            "center", ident=args.lease_id, run_dir=args.record_dir,
            telemetry_=tm,
            extra=lambda: {"n_updates": srv.center.n_updates,
                           "dedup_hits": srv.dedup.hits,
                           "addr": f"{host}:{port}"})
        statusz.start()

    lease = None
    if args.lease_dir:
        from .membership import WorkerLease
        lease = WorkerLease(args.lease_dir, args.lease_id, telemetry_=tm)
        lease.beat(srv.center.n_updates)

    # fleet health plane (§20): the center is a long-lived process too —
    # its snapshot stream (rank −1, role `center`) puts its apply rate
    # and liveness on the same fleet dashboard as the workers'
    streamer = None
    if args.metrics_addr:
        from ..utils.fleetmon import MetricStreamer
        streamer = MetricStreamer(
            args.metrics_addr, rank=-1, role="center", telemetry_=tm,
            extra=lambda: {"steps": srv.center.n_updates})
        streamer.start()

    halt = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: halt.set())
    try:
        signal.signal(signal.SIGINT, lambda *_: halt.set())
    except (ValueError, OSError):
        pass
    t0 = time.time()
    while not halt.wait(1.0):
        if lease is not None:
            lease.beat(srv.center.n_updates)
        if args.max_seconds and time.time() - t0 > args.max_seconds:
            break
    srv.stop(final_snapshot=True)
    if streamer is not None:
        streamer.stop(final=True)     # clean exit: retire, don't alert
    if statusz is not None:
        statusz.stop()
    if lease is not None:
        lease.release()
    if tm.enabled:
        tm.event("train_end", center=True,
                 n_updates=srv.center.n_updates,
                 dedup_hits=srv.dedup.hits)
        tm.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(center_main())
