"""ZeRO-1: optimizer state sharded over the data-parallel workers.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 —
replicates optimizer state per GPU, like every pre-ZeRO framework): under
BSP every worker applies the SAME reduced gradient, so the momentum /
second-moment buffers are identical replicas — pure memory waste.  ZeRO
stage 1 (Rajbhandari et al. 2020) shards them: each worker keeps 1/N of the
flattened optimizer state, updates only ITS parameter chunk, and one
``all_gather`` rebuilds the full parameters for the next forward pass.

Since the leaf-wise update-plane schema landed
(``parallel/update_sharding.py``, docs/design.md §23), this module is a
THIN CONFIGURATION of that wrapper: :func:`zero1` is
``update_sharding.flat_shard_opt`` — the flat-chunk-everything layout,
which additionally carries the tensor/pipeline composition
(``model_shards``/``pspecs``).  Config ``zero_opt=true`` behaves exactly
as before, cache keys included (``compile_cache.key_extra`` stamps
nothing new unless ``update_sharding`` is on).  Bit-equivalence with the
unsharded optimizer holds exactly (elementwise update math on disjoint
chunks; no reduction-order change) and is pinned in ``tests/test_zero.py``,
ragged param counts (P=10, N=4 — explicit ``padded_size`` padding)
included.
"""

from __future__ import annotations

from ..utils.opt import OptPair
from .mesh import WORKER_AXIS
from .update_sharding import chunk_size, flat_shard_opt, padded_size

__all__ = ["chunk_size", "padded_size", "rechunk_boxed", "zero1"]


def rechunk_boxed(arr, n_new: int, shards: int, local_total: int):
    """Re-partition a saved boxed ZeRO state leaf ``[n_saved, shards·chunk_s]``
    onto ``[n_new, shards·chunk_new]`` (worker-count-portable resume).

    Dim 1 is laid out one chunk per model-group rank (``state_partition_
    specs`` shards it over the model axes), so model rank r's local flat
    vector is the concatenation over workers of column block r — reassemble
    each rank's flat, trim its padding, re-pad and re-slice for the new
    worker count.  The model-axes sizes themselves must match (``shards``
    and ``local_total`` are properties of the model layout, not of N).
    """
    import numpy as np
    n_s = int(arr.shape[0])
    assert arr.ndim == 2 and arr.shape[1] % shards == 0, arr.shape
    chunk_s = arr.shape[1] // shards
    # [n_s, shards, chunk_s] -> [shards, n_s·chunk_s] -> trim pad
    per_rank = np.transpose(np.asarray(arr).reshape(n_s, shards, chunk_s),
                            (1, 0, 2)).reshape(shards, -1)[:, :local_total]
    chunk_n = chunk_size(local_total, n_new)
    per_rank = np.pad(per_rank, ((0, 0), (0, padded_size(local_total, n_new)
                                          - local_total)))
    return np.transpose(per_rank.reshape(shards, n_new, chunk_n),
                        (1, 0, 2)).reshape(n_new, shards * chunk_n)


def zero1(opt: OptPair, n_workers: int, params_template,
          axis: str = WORKER_AXIS, model_shards: int = 1,
          pspecs=None, model_axes: tuple = ()) -> OptPair:
    """Wrap ``opt`` so its state lives flat-chunked over ``axis`` — the
    ZeRO-1 special case of the update-sharding wrapper.  See
    :func:`update_sharding.flat_shard_opt` for the layout contract."""
    return flat_shard_opt(opt, n_workers, params_template, axis=axis,
                          model_shards=model_shards, pspecs=pspecs,
                          model_axes=model_axes)
