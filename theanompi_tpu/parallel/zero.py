"""ZeRO-1: optimizer state sharded over the data-parallel workers.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 —
replicates optimizer state per GPU, like every pre-ZeRO framework): under
BSP every worker applies the SAME reduced gradient, so the momentum /
second-moment buffers are identical replicas — pure memory waste.  ZeRO
stage 1 (Rajbhandari et al. 2020) shards them: each worker keeps 1/N of the
flattened optimizer state, updates only ITS parameter chunk, and one
``all_gather`` rebuilds the full parameters for the next forward pass.

TPU-native mapping: this drops straight into the existing boxed-state
machinery as an OPTIMIZER WRAPPER.  The wrapped ``init`` allocates state
for one ``ceil(P/N)`` chunk (so the boxed ``[n_workers, chunk]`` layout IS
the ZeRO partition — per-chip optimizer memory shrinks N×), and ``update``
runs inside the same compiled SPMD step as everything else:

    flat_g   = flatten(reduced grads)           # grads already psum'd (BSP)
    my_g     = dynamic_slice(flat_g,  rank·C)   # my chunk
    my_p     = dynamic_slice(flat_p,  rank·C)
    my_p'    = opt.update(my_g, my_state, my_p) # any wrapped optimizer
    params'  = unflatten(all_gather(my_p'))     # one allgather, rides ICI

Bit-equivalence with the unsharded optimizer holds exactly (elementwise
update math on disjoint chunks; no reduction-order change) and is pinned in
``tests/test_zero.py``.  Config: ``zero_opt=true`` on any BSP session.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import helper_funcs
from ..utils.opt import OptPair
from .mesh import WORKER_AXIS


def chunk_size(n_total: int, n_workers: int) -> int:
    """ceil(P/N) — the per-worker chunk length of an N-way flat partition."""
    return -(-n_total // n_workers)


def rechunk_boxed(arr, n_new: int, shards: int, local_total: int):
    """Re-partition a saved boxed ZeRO state leaf ``[n_saved, shards·chunk_s]``
    onto ``[n_new, shards·chunk_new]`` (worker-count-portable resume).

    Dim 1 is laid out one chunk per model-group rank (``state_partition_
    specs`` shards it over the model axes), so model rank r's local flat
    vector is the concatenation over workers of column block r — reassemble
    each rank's flat, trim its padding, re-pad and re-slice for the new
    worker count.  The model-axes sizes themselves must match (``shards``
    and ``local_total`` are properties of the model layout, not of N).
    """
    import numpy as np
    n_s = int(arr.shape[0])
    assert arr.ndim == 2 and arr.shape[1] % shards == 0, arr.shape
    chunk_s = arr.shape[1] // shards
    # [n_s, shards, chunk_s] -> [shards, n_s·chunk_s] -> trim pad
    per_rank = np.transpose(np.asarray(arr).reshape(n_s, shards, chunk_s),
                            (1, 0, 2)).reshape(shards, -1)[:, :local_total]
    chunk_n = chunk_size(local_total, n_new)
    per_rank = np.pad(per_rank,
                      ((0, 0), (0, chunk_n * n_new - local_total)))
    return np.transpose(per_rank.reshape(shards, n_new, chunk_n),
                        (1, 0, 2)).reshape(n_new, shards * chunk_n)


def zero1(opt: OptPair, n_workers: int, params_template,
          axis: str = WORKER_AXIS, model_shards: int = 1,
          pspecs=None, model_axes: tuple = ()) -> OptPair:
    """Wrap ``opt`` so its state lives sharded over ``axis``.

    ``params_template`` fixes the flat layout (chunk size = ceil(P/N)); the
    wrapped pair plugs into the standard step machinery unchanged — the
    boxed ``[n_workers, ...]`` state axis is the ZeRO partition.

    Model parallelism (round-4): under tensor/pipeline param specs the
    per-device params are already the LOCAL shard, so ``params_template``
    must be the local template (``steps.local_param_template``) and
    ``update`` composes unchanged — flatten local, slice my worker chunk,
    all-gather over workers rebuilds the local flat.  Only ``init`` differs:
    the HOST state template must be global-shaped, ``model_shards`` × the
    chunk (one chunk per model-group rank), laid out so the boxed spec
    ``P(workers, <model axes>)`` hands each device exactly its chunk
    (``steps.state_partition_specs``).
    """
    n_total = helper_funcs.tree_size(params_template)
    chunk = chunk_size(n_total, n_workers)
    padded = chunk * n_workers

    def init(params):
        # per-worker view: state for ONE chunk per model-group rank (boxed
        # to [n_workers, model_shards·chunk] by the step machinery and
        # sharded so each chip holds exactly its [chunk] shard)
        return {"opt": opt.init(
            jnp.zeros((model_shards * chunk,), jnp.float32))}

    def update(grads, st, params, lr):
        flat_g = helper_funcs.flatten_tree(grads, pad_to_multiple_of=padded)
        flat_p = helper_funcs.flatten_tree(params, pad_to_multiple_of=padded)
        rank = lax.axis_index(axis)
        my_g = lax.dynamic_slice(flat_g, (rank * chunk,), (chunk,))
        my_p = lax.dynamic_slice(flat_p, (rank * chunk,), (chunk,))
        my_p_new, opt_state = opt.update(my_g, st["opt"], my_p, lr)
        full = lax.all_gather(my_p_new, axis, tiled=True)       # [padded]
        new_params = helper_funcs.unflatten_like(params, full)
        if model_axes and pspecs is not None:
            # the flat concat JOINS every leaf's varying-mesh-axes set, so
            # leaves replicated over a model axis (LN scales, biases)
            # come back statically unprovable as invariant even though
            # their values are (grads of replicated leaves are psum'd over
            # model in the tp backward).  Re-anchor each leaf bit-exactly
            # (steps.anchor_invariant) over exactly the model axes its spec
            # does NOT shard — per axis, so a 3-D mesh leaf sharded over
            # 'pipe' but replicated over 'model' anchors on 'model' only.
            from .steps import _is_spec, anchor_invariant, spec_mentions

            def anchor(s, v):
                axes = tuple(a for a in model_axes
                             if not spec_mentions(s, (a,)))
                return anchor_invariant(v, axes)

            new_params = jax.tree.map(anchor, pspecs, new_params,
                                      is_leaf=_is_spec)
        return new_params, {"opt": opt_state}

    return OptPair(init, update)
