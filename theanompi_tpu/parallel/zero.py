"""ZeRO-1: optimizer state sharded over the data-parallel workers.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 —
replicates optimizer state per GPU, like every pre-ZeRO framework): under
BSP every worker applies the SAME reduced gradient, so the momentum /
second-moment buffers are identical replicas — pure memory waste.  ZeRO
stage 1 (Rajbhandari et al. 2020) shards them: each worker keeps 1/N of the
flattened optimizer state, updates only ITS parameter chunk, and one
``all_gather`` rebuilds the full parameters for the next forward pass.

TPU-native mapping: this drops straight into the existing boxed-state
machinery as an OPTIMIZER WRAPPER.  The wrapped ``init`` allocates state
for one ``ceil(P/N)`` chunk (so the boxed ``[n_workers, chunk]`` layout IS
the ZeRO partition — per-chip optimizer memory shrinks N×), and ``update``
runs inside the same compiled SPMD step as everything else:

    flat_g   = flatten(reduced grads)           # grads already psum'd (BSP)
    my_g     = dynamic_slice(flat_g,  rank·C)   # my chunk
    my_p     = dynamic_slice(flat_p,  rank·C)
    my_p'    = opt.update(my_g, my_state, my_p) # any wrapped optimizer
    params'  = unflatten(all_gather(my_p'))     # one allgather, rides ICI

Bit-equivalence with the unsharded optimizer holds exactly (elementwise
update math on disjoint chunks; no reduction-order change) and is pinned in
``tests/test_zero.py``.  Config: ``zero_opt=true`` on any BSP session.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..utils import helper_funcs
from ..utils.opt import OptPair
from .mesh import WORKER_AXIS


def zero1(opt: OptPair, n_workers: int, params_template,
          axis: str = WORKER_AXIS) -> OptPair:
    """Wrap ``opt`` so its state lives sharded over ``axis``.

    ``params_template`` fixes the flat layout (chunk size = ceil(P/N)); the
    wrapped pair plugs into the standard step machinery unchanged — the
    boxed ``[n_workers, ...]`` state axis is the ZeRO partition.
    """
    n_total = helper_funcs.tree_size(params_template)
    chunk = -(-n_total // n_workers)            # ceil
    padded = chunk * n_workers

    def init(params):
        # per-worker view: state for ONE chunk (boxed to [n_workers, chunk]
        # by the step machinery, i.e. each chip holds exactly its shard)
        return {"opt": opt.init(jnp.zeros((chunk,), jnp.float32))}

    def update(grads, st, params, lr):
        flat_g = helper_funcs.flatten_tree(grads, pad_to_multiple_of=padded)
        flat_p = helper_funcs.flatten_tree(params, pad_to_multiple_of=padded)
        rank = lax.axis_index(axis)
        my_g = lax.dynamic_slice(flat_g, (rank * chunk,), (chunk,))
        my_p = lax.dynamic_slice(flat_p, (rank * chunk,), (chunk,))
        my_p_new, opt_state = opt.update(my_g, st["opt"], my_p, lr)
        full = lax.all_gather(my_p_new, axis, tiled=True)       # [padded]
        new_params = helper_funcs.unflatten_like(params, full)
        return new_params, {"opt": opt_state}

    return OptPair(init, update)
