"""Resilient RPC wire layer for the center server — survive the network.

The reference ran its EASGD/ASGD server as a bare MPI peer: one lost
message ended the run.  ``parallel/center_server.py``'s first socket port
inherited that shape — blocking sockets, no timeouts, no retries, no
payload integrity — so a dropped packet, a wedged peer, or a center
restart was fatal to every island talking to it.  This module is the
shared wire contract both ends now speak (docs/design.md §15):

* **Framing** — ``[4B header len][JSON header][4B body len][body]``.
  The header always carries the protocol version (``v``) and, when a
  body is present, its CRC32 (``crc``).  A version mismatch fails
  LOUDLY with both versions in the message; a CRC mismatch is
  :class:`CorruptPayload` (retryable — the bytes, not the op, are bad).
* **Close taxonomy** — a clean close *between* messages is
  :class:`ConnectionClosed` (the peer went away at a frame boundary:
  nothing was lost, retry freely); a close *mid-message* is
  :class:`TruncatedMessage` (payload lost in flight).  The old code
  raised one ``ConnectionError`` for both, so a client could not tell
  "retry safely" from "half a push evaporated".
* **Idempotency tokens** — every mutating request carries
  ``tok = {w: <client>, seq: <n>}``; the server's :class:`DedupWindow`
  remembers recently applied ``(client, op, seq)`` tokens with their
  replies, so a retried ``push`` that actually landed is applied
  EXACTLY once (the retry gets the original reply back).
* **:class:`WireClient`** — a persistent connection with per-op socket
  timeouts, bounded exponential-backoff retries
  (``membership.Backoff``), and transparent reconnect.  Every attempt
  feeds telemetry: ``wire.rtt`` histograms, the :data:`WIRE_COUNTERS`
  counters, and an outage-duration gauge + ``wire`` event when a
  connection heals after failures.

Module scope is stdlib + the telemetry shim (numpy only inside the leaf
helpers) — the tpulint schema-drift checker loads this file jax-free to
probe the declared telemetry vocabulary against the live report.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

try:
    from ..utils import telemetry, tracing
    from ..utils.clock import WALL
except ImportError:        # file-path load (jax-free lint probe): absolute
    from theanompi_tpu.utils import telemetry, tracing
    from theanompi_tpu.utils.clock import WALL

#: Protocol version stamped into every header.  Bump on any framing or
#: semantics change; both ends refuse a mismatch loudly (never silently
#: misparse a peer from another release).
#:
#: v2 (round 16, docs/design.md §17): requests MAY carry a ``trace``
#: header field (``{"t": trace_id, "s": span_id}`` — cross-process
#: causal-tracing context) and replies MAY carry ``srv``
#: (``{"q": queue_wait_s, "a": apply_s}`` — the server's time split).
#: Both fields are OPTIONAL within v2: absent ⇒ exactly the v1 behavior,
#: so tracing can be enabled per-process without config coordination.
#: The bump marks the header-contract change itself — a v1 peer would
#: silently drop both fields, and silent is what version checks forbid.
WIRE_VERSION = 2

# -- telemetry vocabulary (probed live by the schema-drift checker) ----------

#: Counters the wire layer's machinery ticks (client side unless noted).
#: ``wire.dedup_hit`` is server side; ``wire.exchange_skipped`` and
#: ``wire.center_reseed`` are emitted by the EASGD/ASGD islands
#: (``async_easgd.IslandRunner``) when an exchange is skipped through an
#: outage or the center had to be re-seeded after a snapshotless respawn
#: — declared here so the schema governance covers the whole wire story.
WIRE_COUNTERS = ("wire.retry", "wire.timeout", "wire.corrupt",
                 "wire.reconnect", "wire.giveup",
                 "wire.dedup_hit", "wire.exchange_skipped",
                 "wire.center_reseed")
#: Histograms: per-request round-trip seconds on success, plus the
#: server's reply-header time split (``srv`` field, v2) — queue wait at
#: the center lock and apply time under it — so client RTT is
#: decomposable into wire transit vs center queueing vs center apply
#: even with tracing disabled.
WIRE_HISTS = ("wire.rtt", "wire.server_queue", "wire.server_apply")
#: Gauges: seconds the last outage lasted, set when a connection heals —
#: streamed in a ``gauges`` event so the Perfetto export renders an
#: outage-duration counter track.
WIRE_GAUGES = ("wire.outage_s",)
#: The wire event kind (``kind`` ∈ outage/giveup) — instant markers in
#: the report/trace next to the membership transitions they explain.
WIRE_EVENT = "wire"

# sanity bounds: a corrupted length prefix must not allocate the
# universe.  Body ≤ 2 GiB (a u32 can express up to 4 GiB−1, so the bound
# must sit BELOW the field's range to ever trigger); violations are
# FramingError — the stream is desynced, the connection must be dropped
_MAX_HEADER = 16 << 20
_MAX_BODY = 2 << 30


# -- errors ------------------------------------------------------------------

class WireError(ConnectionError):
    """Base for transport-level wire failures (all retryable)."""


class ConnectionClosed(WireError):
    """Clean close at a frame boundary — no request/reply in flight was
    lost; safe to reconnect and retry."""


class TruncatedMessage(WireError):
    """The peer vanished MID-message: the frame being read is lost.
    Retrying is still safe for center ops (idempotency tokens make the
    server dedup a retry of anything that landed), but the distinction
    matters for telemetry and for protocols without tokens."""


class CorruptPayload(WireError):
    """Body bytes failed their CRC32 — the wire, not the op, is bad."""


class VersionMismatch(RuntimeError):
    """Peer speaks a different wire protocol version.  NOT retryable —
    deliberately loud, with both versions in the message."""


class WireGiveUp(ConnectionError):
    """Retries/deadline exhausted.  Carries what was tried and the last
    underlying error so the give-up is diagnosable, not opaque."""


class RemoteOpError(RuntimeError):
    """The server executed the request and replied with an op-level
    failure (shape mismatch, unknown op).  NOT retryable: the op, not
    the wire, is wrong."""


class CenterUninitialized(RemoteOpError):
    """The center has no params yet — a respawn with no usable snapshot.
    Not a wire fault and not retryable as-is, but RECOVERABLE: the
    caller re-seeds via ``ensure_init`` with its current params and
    carries on (an island doing so restarts the consensus from its own
    state — the missed center history is a missed exchange, which the
    async algebra absorbs)."""


class FramingError(WireError):
    """A length prefix failed its sanity bound — the byte stream itself
    is corrupted/desynced, so unlike a CRC mismatch the connection CANNOT
    be reused: both ends must drop it (the next 'length' read would be
    arbitrary payload bytes)."""


#: Sentinel cached-reply for a token whose ORIGINAL request is still
#: being applied on another handler thread: the retry must be told to
#: come back (retryable busy reply), not acked — the original may yet
#: fail and release the claim.
INFLIGHT = object()


# -- framing -----------------------------------------------------------------

def send_msg(sock: socket.socket, header: dict, body: bytes = b"") -> None:
    """One framed message: ``[4B hlen][4B header CRC][header JSON]
    [4B blen][body]`` — version-stamped header, CRC on BOTH parts.  The
    header CRC is what makes every other integrity verdict trustworthy:
    without it a flipped header byte reads as garbage JSON (or a spurious
    unretryable version mismatch) instead of a detected wire fault."""
    h = dict(header)
    h["v"] = WIRE_VERSION
    if body:
        h["crc"] = zlib.crc32(body) & 0xFFFFFFFF
    hb = json.dumps(h).encode()
    sock.sendall(struct.pack("!I", len(hb))
                 + struct.pack("!I", zlib.crc32(hb) & 0xFFFFFFFF) + hb
                 + struct.pack("!I", len(body)) + body)


def recv_exact(sock: socket.socket, n: int, *,
               at_boundary: bool = False) -> bytes:
    """Read exactly ``n`` bytes.  A clean close before the FIRST byte of
    a message (``at_boundary``) raises :class:`ConnectionClosed`; a close
    anywhere else raises :class:`TruncatedMessage` — the caller can tell
    "peer left between requests" from "payload lost mid-flight"."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        c = sock.recv(min(n - got, 1 << 20))
        if not c:
            if at_boundary and got == 0:
                raise ConnectionClosed(
                    "peer closed the connection at a message boundary")
            raise TruncatedMessage(
                f"connection closed mid-message ({got}/{n} bytes read)")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


def recv_msg(sock: socket.socket,
             check_version: bool = True) -> Tuple[dict, bytes]:
    """One framed message back: verifies the header CRC (a mismatch is
    :class:`FramingError` — a flipped header OR length byte cannot be
    told apart, so the only safe verdict is a desynced stream: drop the
    connection), then the protocol version (loud — and now TRUSTWORTHY —
    :class:`VersionMismatch` with both versions), then the body CRC
    (:class:`CorruptPayload`: the header proved the stream aligned, so
    the op can be retried on this same connection)."""
    (hlen,) = struct.unpack("!I", recv_exact(sock, 4, at_boundary=True))
    if hlen > _MAX_HEADER:
        raise FramingError(f"header length {hlen} exceeds bound "
                           f"{_MAX_HEADER} — corrupted length prefix, "
                           f"stream desynced: drop the connection")
    (hcrc,) = struct.unpack("!I", recv_exact(sock, 4))
    hb = recv_exact(sock, hlen)
    if (zlib.crc32(hb) & 0xFFFFFFFF) != hcrc:
        raise FramingError(
            f"header CRC mismatch ({hlen} bytes): header or length "
            f"prefix corrupted — stream integrity unknown, drop the "
            f"connection")
    try:
        header = json.loads(hb)
    except ValueError:
        raise FramingError("header passed its CRC but is not JSON — "
                           "peer speaks a different framing; drop the "
                           "connection") from None
    (blen,) = struct.unpack("!I", recv_exact(sock, 4))
    if blen > _MAX_BODY:
        raise FramingError(f"body length {blen} exceeds bound "
                           f"{_MAX_BODY} — corrupted length prefix, "
                           f"stream desynced: drop the connection")
    body = recv_exact(sock, blen) if blen else b""
    if check_version:
        got = header.get("v")
        if got != WIRE_VERSION:
            raise VersionMismatch(
                f"wire protocol version mismatch: peer speaks "
                f"v{got!r}, this end speaks v{WIRE_VERSION} — both ends "
                f"must run the same release")
    crc = header.get("crc")
    if body and crc is not None and (zlib.crc32(body) & 0xFFFFFFFF) != crc:
        raise CorruptPayload(
            f"payload CRC mismatch ({len(body)} bytes): body corrupted "
            f"in flight")
    return header, body


def encode_frame(header: dict, body: bytes = b"") -> bytes:
    """The exact bytes :func:`send_msg` would emit — WITHOUT stamping the
    version, so tests and probes can craft mismatched/raw frames."""
    hb = json.dumps(header).encode()
    return (struct.pack("!I", len(hb))
            + struct.pack("!I", zlib.crc32(hb) & 0xFFFFFFFF) + hb
            + struct.pack("!I", len(body)) + body)


# -- leaf packing (numpy lives only here) ------------------------------------

def pack_leaves(leaves) -> bytes:
    """Flat leaf list → npz bytes keyed by flatten order (no pickle)."""
    import io

    import numpy as np
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf{i}": np.asarray(x, np.float32)
                     for i, x in enumerate(leaves)})
    return buf.getvalue()


def unpack_leaves(body: bytes):
    import io

    import numpy as np
    if not body:
        return []
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        return [z[f"leaf{i}"] for i in range(len(z.files))]


# -- server-side dedup window ------------------------------------------------

class DedupWindow:
    """Exactly-once application for retried mutating ops.

    Remembers the last ``depth`` applied ``(client, op, seq)`` tokens per
    client together with the reply that was sent, so a retry of a request
    that already landed is answered from the cache instead of applied
    again.  ``seq`` high-water marks are kept per client for snapshots:
    after a center restart a replayed token at-or-below the restored HWM
    is still recognized even though its cached reply is gone (the server
    then synthesizes a fresh reply — the op is NOT reapplied).
    """

    def __init__(self, depth: int = 128, telemetry_=None):
        self.depth = int(depth)
        self.telemetry = telemetry_
        self._lock = threading.Lock()
        # client -> OrderedDict[(op, seq) -> (header, body) | None]
        self._seen: Dict[str, OrderedDict] = {}
        self.seq_hwm: Dict[str, int] = {}
        self.hits = 0

    def _tm(self):
        return self.telemetry if self.telemetry is not None \
            else telemetry.active()

    def check(self, token: Optional[dict], op: str
              ) -> Tuple[bool, Any]:
        """``(is_duplicate, cached_reply)`` for a request's token.  A
        tokenless request is never a duplicate (legacy/test clients).
        For a duplicate, ``cached_reply`` is the recorded ``(header,
        body|None)`` (``None`` body = applied but not cached — reply
        must be synthesized), plain ``None`` for a post-restart/evicted
        replay of an APPLIED request, or the :data:`INFLIGHT` sentinel
        when the original is still being applied on another thread —
        the caller must answer that one with a retryable busy reply,
        never an ack (the original may yet fail and release the claim).

        A FRESH token is atomically CLAIMED (placeholder entry) before
        returning, so a retry arriving while the original is still being
        applied — a slow server past the client's op timeout — reads as
        a duplicate instead of a second application.  :meth:`release`
        withdraws the claim when the op fails."""
        if not token:
            return False, None
        w, seq = str(token.get("w")), int(token.get("seq", -1))
        with self._lock:
            window = self._seen.get(w)
            if window is not None and (op, seq) in window:
                self.hits += 1
                entry = window[(op, seq)]
                hit = INFLIGHT if entry is None else entry
            elif seq <= self.seq_hwm.get(w, -1):
                # at-or-below the high-water mark but outside the cached
                # window: an OLD retry (or a post-restart replay) of a
                # request that landed before — never reapply.  HWMs only
                # advance in record(), so this is always APPLIED, never
                # in-flight
                self.hits += 1
                hit = None
            else:
                if window is None:
                    window = self._seen[w] = OrderedDict()
                window[(op, seq)] = None        # claim
                while len(window) > self.depth:
                    window.popitem(last=False)
                return False, None
        tm = self._tm()
        if tm.enabled:
            tm.counter("wire.dedup_hit")
        return True, hit

    def record(self, token: Optional[dict], op: str,
               reply_header: dict, reply_body: Optional[bytes] = b"",
               max_cached_body: int = 1 << 20) -> None:
        """Remember an APPLIED request's reply (bounded per client).
        ``reply_body=None`` means the body is deliberately NOT cached
        (model-sized push_pull replies — the window must stay small); a
        replay then gets a synthesized body."""
        if not token:
            return
        w, seq = str(token.get("w")), int(token.get("seq", -1))
        # (header, None) = applied but body not cached (too big / opted
        # out) — a replay synthesizes it; distinct from the bare-None claim
        cached = (dict(reply_header),
                  bytes(reply_body) if reply_body is not None
                  and len(reply_body) <= max_cached_body else None)
        with self._lock:
            window = self._seen.setdefault(w, OrderedDict())
            window[(op, seq)] = cached
            while len(window) > self.depth:
                window.popitem(last=False)
            if seq > self.seq_hwm.get(w, -1):
                self.seq_hwm[w] = seq

    def release(self, token: Optional[dict], op: str) -> None:
        """Withdraw a :meth:`check` claim after the op FAILED — a later
        retry of the same token must be allowed to apply."""
        if not token:
            return
        w, seq = str(token.get("w")), int(token.get("seq", -1))
        with self._lock:
            window = self._seen.get(w)
            if window is not None and window.get((op, seq)) is None \
                    and (op, seq) in window:
                del window[(op, seq)]

    def hwm_snapshot(self) -> Dict[str, int]:
        """Locked copy of the per-client seq high-water marks — the ONE
        way other threads may read them.  ``seq_hwm`` mutates under
        ``_lock`` on handler threads; an unlocked ``dict()``/``sum()``
        over the live dict (the center's snapshot loop, the ``stats``
        op) can throw ``dictionary changed size during iteration``
        mid-flight (tpulint shared-state-race)."""
        with self._lock:
            return dict(self.seq_hwm)

    # -- snapshot plumbing (center crash recovery) --------------------------

    def snapshot(self) -> dict:
        """APPLIED tokens + HWMs only — cached reply bodies (whole center
        pulls) would bloat the snapshot, and in-flight claims must NOT
        persist (a crash mid-apply followed by a restore would otherwise
        dedup a retry of an op that never landed).  A post-restart replay
        is recognized by token and answered with a synthesized reply."""
        with self._lock:
            return {"hwm": dict(self.seq_hwm),
                    "tokens": {w: [[op, seq] for (op, seq), v
                                   in window.items() if v is not None]
                               for w, window in self._seen.items()},
                    "hits": self.hits}

    def restore(self, snap: dict) -> None:
        with self._lock:
            self.seq_hwm = {str(w): int(s)
                            for w, s in (snap.get("hwm") or {}).items()}
            self._seen = {}
            for w, toks in (snap.get("tokens") or {}).items():
                window = self._seen[str(w)] = OrderedDict()
                for op, seq in toks:
                    # applied-before-the-restart marker (reply bodies are
                    # not snapshotted): a replay gets a synthesized reply
                    window[(str(op), int(seq))] = \
                        ({"ok": True, "dedup": True}, None)
            self.hits = int(snap.get("hits", 0))


# -- client ------------------------------------------------------------------

class WireClient:
    """Persistent framed connection with per-op timeouts, bounded
    exponential-backoff retries, transparent reconnect, and idempotency
    tokens — the client half of the §15 wire contract.

    ``client_id`` keys the server's dedup window (island id, or any
    stable string); ``op_timeout_s`` bounds each send+recv; a failed
    attempt reconnects and retries up to ``max_retries`` times within
    ``deadline_s``, then raises :class:`WireGiveUp` carrying the attempt
    count and last error.  Thread-safe: one lock serializes this
    process's callers (the SERVER's lock serializes across processes).
    """

    def __init__(self, addr: str, client_id: Any = None, *,
                 op_timeout_s: float = 20.0, connect_timeout_s: float = 5.0,
                 max_retries: int = 8, deadline_s: float = 120.0,
                 backoff=None, telemetry_=None, clock=None):
        host, port = str(addr).rsplit(":", 1)
        self.addr = (host, int(port))
        # retry deadlines, backoff sleeps, outage spans, and the seq seed
        # are DECISION times — behind the clock seam (utils/clock.py) so
        # simfleet can rehearse the retry algebra in virtual time.  The
        # per-request RTT observation stays wall time: it measures the
        # wire, not a decision.
        self.clock = clock or WALL
        self.client_id = str(client_id) if client_id is not None else \
            f"c{id(self) & 0xFFFFFF:x}"
        self.op_timeout_s = float(op_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.max_retries = int(max_retries)
        self.deadline_s = float(deadline_s)
        if backoff is None:
            from .membership import Backoff
            backoff = Backoff(base=0.2, factor=2.0, cap=5.0)
        self.backoff = backoff
        self.telemetry = telemetry_
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        # seq starts at wall-clock milliseconds, NOT 0: a respawned worker
        # reuses its client_id (island ids are stable across incarnations),
        # and the server's seq high-water mark survives both window
        # eviction and center restarts — a fresh incarnation restarting
        # from 0 would have every push silently deduped as an 'old retry'.
        # Clock-based seeding keeps each incarnation strictly above the
        # last (respawns are seconds apart; the counter is per-client)
        self._seq = int(self.clock.now() * 1000)
        self._outage_t0: Optional[float] = None
        self._last_attempts = 1       # attempts of the LAST request (for
        # the span's retry count; read under the same lock request holds)

    # -- plumbing -----------------------------------------------------------

    def _tm(self):
        return self.telemetry if self.telemetry is not None \
            else telemetry.active()

    def _connect(self) -> socket.socket:
        s = socket.create_connection(self.addr,
                                     timeout=self.connect_timeout_s)
        s.settimeout(self.op_timeout_s)
        return s

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _note_ok(self, dt: float) -> None:
        tm = self._tm()
        if self._outage_t0 is not None:
            outage = self.clock.now() - self._outage_t0
            self._outage_t0 = None
            if tm.enabled:
                tm.gauge("wire.outage_s", round(outage, 3))
                # streamed as a gauges event so the Perfetto export draws
                # the outage-duration counter track; the wire event is the
                # human-readable instant marker
                tm.event("gauges", **{"wire.outage_s": round(outage, 3)})
                tm.event(WIRE_EVENT, kind="outage", w=self.client_id,
                         secs=round(outage, 3))
        if tm.enabled:
            tm.observe("wire.rtt", dt)

    def _note_fail(self, counter: Optional[str] = None) -> None:
        if self._outage_t0 is None:
            self._outage_t0 = self.clock.now()
        tm = self._tm()
        if counter and tm.enabled:
            tm.counter(counter)

    # -- the request loop ---------------------------------------------------

    def request(self, header: dict, body: bytes = b"",
                trace: Optional[dict] = None) -> Tuple[dict, bytes]:
        """One request/response round-trip, retried through failures.

        Center ops are idempotent under retry BY CONSTRUCTION: the token
        stamped here makes the server's dedup window apply a re-sent
        mutating op exactly once and replay the original reply.

        ``trace`` (optional, v2) is the caller's span context
        (``Span.ctx()``): ONE ``wire.<op>`` span id is minted here and
        stamped into the header — every retry of this request re-sends
        the same ids, so the server spans they produce all join the one
        client span (and a chaos-duplicated frame's twin is joined too,
        tagged ``dedup`` server-side).  The span event carries the total
        dt, the successful attempt's server ``q``/``a`` split, and the
        retry count; a give-up still ends the span (``ok=false``)."""
        h = dict(header)
        op = str(header.get("op"))
        with self._lock:
            h["tok"] = {"w": self.client_id, "seq": self._seq}
            self._seq += 1
            sid = None
            if trace is not None:
                sid = tracing.new_span_id()
                h["trace"] = {"t": trace.get("t"), "s": sid}
            t_req = time.time()
            try:
                resp, rbody = self._request_locked(h, body)
            except BaseException as e:
                tm = self._tm()
                if trace is not None and tm.enabled:
                    tracing.emit_wire_span(
                        tm, trace, op, span=sid, t0=t_req,
                        dt=time.time() - t_req, ok=False,
                        err=repr(e)[:120],
                        retries=self._last_attempts - 1)
                raise
            if trace is not None:
                tm = self._tm()
                if tm.enabled:
                    srv = resp.get("srv") or {}
                    tracing.emit_wire_span(
                        tm, trace, op, span=sid, t0=t_req,
                        dt=time.time() - t_req, q=srv.get("q"),
                        a=srv.get("a"), dedup=bool(resp.get("dedup")),
                        ok=True, retries=self._last_attempts - 1)
            return resp, rbody

    def _request_locked(self, header: dict, body: bytes
                        ) -> Tuple[dict, bytes]:
        t_start = self.clock.now()
        last_err: Optional[BaseException] = None
        attempts = 0
        for attempt in range(self.max_retries + 1):
            attempts = attempt + 1
            self._last_attempts = attempts
            if attempt:
                self._note_fail("wire.retry")
                delay = self.backoff.delay(attempt - 1)
                if self.clock.now() + delay - t_start > self.deadline_s:
                    break
                self.clock.sleep(delay)
            try:
                if self._sock is None:
                    self._sock = self._connect()
                    if attempt or self._outage_t0 is not None:
                        tm = self._tm()
                        if tm.enabled:
                            tm.counter("wire.reconnect")
                t0 = time.time()
                send_msg(self._sock, header, body)
                resp, rbody = recv_msg(self._sock)
                if not resp.get("ok"):
                    if resp.get("retry"):
                        # retryable server-side verdict: same token, try
                        # again — a CRC mismatch counts as corruption, an
                        # in-flight-twin busy reply does not
                        last_err = WireError(str(resp.get("error")))
                        if not resp.get("busy"):
                            self._note_fail("wire.corrupt")
                        continue
                    if resp.get("uninit"):
                        raise CenterUninitialized(
                            f"center server error: {resp.get('error')}")
                    raise RemoteOpError(
                        f"center server error: {resp.get('error')}")
                self._note_ok(time.time() - t0)
                srv = resp.get("srv")
                if srv:
                    # the v2 reply-header time split: RTT decomposable
                    # into wire transit vs center queue vs center apply
                    # even with tracing disabled (§17 satellite)
                    tm = self._tm()
                    if tm.enabled:
                        if srv.get("q") is not None:
                            tm.observe("wire.server_queue", float(srv["q"]))
                        if srv.get("a") is not None:
                            tm.observe("wire.server_apply", float(srv["a"]))
                return resp, rbody
            except socket.timeout as e:
                # the reply may still be in flight — the stream is no
                # longer frame-aligned, so the connection must be dropped
                last_err = e
                self._note_fail("wire.timeout")
                self._drop()
            except CorruptPayload as e:
                # response body corrupted in flight; framing stayed
                # aligned, the connection is reusable
                last_err = e
                self._note_fail("wire.corrupt")
            except VersionMismatch:
                self._drop()
                raise                  # deliberately loud, never retried
            except (WireError, OSError) as e:
                # wire.retry ticks at the loop top — only mark the outage
                last_err = e
                self._note_fail()
                self._drop()
            if self.clock.now() - t_start > self.deadline_s:
                break
        self._drop()
        tm = self._tm()
        if tm.enabled:
            tm.counter("wire.giveup")
            tm.event(WIRE_EVENT, kind="giveup", w=self.client_id,
                     op=str(header.get("op")),
                     err=repr(last_err)[:200])
        raise WireGiveUp(
            f"center {self.addr[0]}:{self.addr[1]} unreachable: gave up "
            f"on op {header.get('op')!r} after {attempts} attempts / "
            f"{self.clock.now() - t_start:.1f}s "
            f"(deadline {self.deadline_s:.0f}s)"
            f" — last error: {last_err!r}")

    def close(self) -> None:
        with self._lock:
            self._drop()
