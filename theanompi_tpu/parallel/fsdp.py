"""FSDP / ZeRO-3: parameters themselves sharded over the data-parallel axis.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 — kept a
full parameter replica per GPU; its memory ceiling per worker was the whole
model).  ZeRO stage 3 / PyTorch-FSDP semantics: each worker PERSISTS only
``1/N`` of the flattened parameters (plus the optimizer state for that same
chunk — ZeRO-1 is subsumed), and the full parameters exist only transiently
inside the compiled step:

    full   = all_gather(my_chunk)              # one ICI allgather
    loss   = model.loss(unflatten(full), ...)  # fwd+bwd on the full tree
    g_chunk= AD transpose                      # psum_scatter — automatic!
    chunk' = opt.update(g_chunk/N, my_state, my_chunk)

The gradient reduce-scatter is NOT written anywhere: differentiating through
``lax.all_gather`` transposes to ``lax.psum_scatter``, so each worker's
gradient chunk arrives already summed across workers — the BSP mean is one
multiply away.  This is the idiomatic JAX formulation (manual-collective
``shard_map`` flavor) of the scaling-book's FSDP recipe: persistent state
sharded, XLA inserts the gather/scatter pair per step, both ride ICI.

Memory per chip: persistent params+optimizer+EMA all ÷N (pad ≤ N−1
elements); the transient peak still holds one full gathered parameter set
during fwd/bwd (whole-model gather — per-layer regather would need the
layer stack's cooperation and is out of scope; with ``n_subb`` microbatches
the gather re-runs per microbatch inside the scan, trading one allgather
per microbatch for activation memory).

Composition: BSP grads mode with the exact ``allreduce`` strategy only (the
reduction IS the AD transpose, so wire-compressed strategies have no hook
here); composes with EMA (the shadow tracks the chunk), ``n_subb``,
``steps_per_call``, ``grad_clip`` (global norm via one extra psum), and the
checkpoint machinery (chunks are per-worker state, saved boxed).  Pure
data-parallel layouts only (``param_specs() is None``); tensor/pipeline
models already shard their params over the model axes.

Config: ``fsdp=true`` on any BSP session.  Pinned in ``tests/test_fsdp.py``
(trajectory equality with plain BSP, EMA/ckpt/clip composition, the ÷N
layout fact).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils import helper_funcs
from .mesh import WORKER_AXIS


class FsdpLayout:
    """Flat-chunk layout facts for a parameter tree: chunk size, padding,
    and a shape-only template for unflattening (values never captured —
    closing the real host params into a traced function would constant-fold
    them into the executable)."""

    def __init__(self, params, n_workers: int):
        self.n_workers = int(n_workers)
        self.n_total = helper_funcs.tree_size(params)
        self.chunk = -(-self.n_total // self.n_workers)          # ceil
        self.padded = self.chunk * self.n_workers
        self.template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), params)

    # -- host side ----------------------------------------------------------

    def chunk_host(self, params) -> np.ndarray:
        """``[n_workers, chunk]`` float32 chunks of the flattened params —
        the boxed step-state layout (each worker's row IS its shard)."""
        leaves = jax.tree.leaves(jax.device_get(params))
        flat = np.concatenate([np.asarray(l, np.float32).reshape(-1)
                               for l in leaves])
        return self.rechunk(flat)          # trim is a no-op: len == n_total

    def host_params_from_chunks(self, boxed_chunks) -> object:
        """Inverse of :meth:`chunk_host`: host full tree from the boxed
        ``[n_workers, chunk]`` array (checkpoint .npy snapshots)."""
        return helper_funcs.unflatten_like(
            self.template, np.asarray(boxed_chunks, np.float32).reshape(-1))

    def rechunk(self, boxed_saved) -> np.ndarray:
        """Re-partition a ``[n_saved, chunk_saved]`` boxed chunk array onto
        THIS layout's ``[n_workers, chunk]`` (worker-count-portable resume:
        chunking is a pure partition of the same padded flat vector, so a
        different worker count just re-slices it)."""
        flat = np.asarray(boxed_saved, np.float32).reshape(-1)[:self.n_total]
        flat = np.pad(flat, (0, self.padded - flat.shape[0]))
        return flat.reshape(self.n_workers, self.chunk)

    # -- traced (inside shard_map) -------------------------------------------

    def gather_params(self, chunk, axis: str = WORKER_AXIS):
        """Full parameter tree from this worker's ``[chunk]`` shard.  The
        AD transpose of the ``all_gather`` is ``psum_scatter``: the caller's
        gradient w.r.t. ``chunk`` arrives summed over workers."""
        full = lax.all_gather(chunk, axis, tiled=True)           # [padded]
        return helper_funcs.unflatten_like(self.template, full)

    def clip_chunk(self, g_chunk, clip: float, axis: str = WORKER_AXIS):
        """Global-L2-norm clipping on the chunked gradient: chunks partition
        the padded flat vector (pad entries carry zero gradient), so the
        true global norm is one scalar psum away; every worker then scales
        by the same factor, preserving the partition semantics."""
        if clip <= 0.0:
            return g_chunk
        sq = lax.psum(jnp.sum(jnp.square(g_chunk)), axis)
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
        return g_chunk * scale
