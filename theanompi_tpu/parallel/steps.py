"""Compiled SPMD train/val step assembly.

This is where Theano-MPI's ``model.compile_iter_fns()`` → ``theano.function``
train/val functions (SURVEY.md §2.5, §3.4) become ``jax.jit``-compiled SPMD
programs.  The reference compiled one opaque native function per sub-batch
(cuDNN fwd → loss → bwd → in-place momentum update); here the WHOLE hot
iteration — microbatch ``lax.scan``, backward pass, cross-worker exchange,
optimizer update — is one XLA program per step, so the collective fuses with
compute and rides ICI with no host round-trip.

State layout (uniform across all four rules — see SURVEY.md §2.2): every
state leaf carries a leading ``[n_workers]`` axis sharded over the
``'workers'`` mesh axis, so each chip holds exactly one replica.  For BSP the
replicas stay bit-identical (the exchanger reduces gradients); for
EASGD/ASGD/GoSGD they diverge between exchanges, which is the whole point of
those rules.  A uniform "boxed" layout means one code path, no replication
bookkeeping, and zero memory overhead versus replicated params.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..jax_compat import shard_map
from ..utils import numerics
from .mesh import WORKER_AXIS, batch_sharding, worker_local_sharding


# ---------------------------------------------------------------------------
# boxing helpers: [*shape] per-worker view <-> [n_workers, *shape] global
# ---------------------------------------------------------------------------

def box(tree):
    """Add the local leading axis (inside shard_map: local shard is [1,...])."""
    return jax.tree.map(lambda x: x[None], tree)


def unbox(tree):
    return jax.tree.map(lambda x: x[0], tree)


def place_boxed(tree, mesh: Mesh, specs=None):
    """Place an already-boxed ``[n_workers, ...]`` host pytree onto the mesh
    (checkpoint restore: per-worker replicas round-trip without collapsing).
    ``specs``: optional same-structure pytree of BOXED PartitionSpecs (tensor
    -parallel models shard some leaves over ``'model'`` too)."""
    if specs is None:
        sh = worker_local_sharding(mesh)
        return jax.tree.map(lambda x: jax.device_put(np.asarray(x), sh), tree)
    return jax.tree.map(
        lambda x, s: jax.device_put(np.asarray(x), NamedSharding(mesh, s)),
        tree, specs)


def tree_to_host(tree):
    """Materialize a (possibly multi-host-sharded) pytree as host numpy with
    GLOBAL shapes — rank 0 can then save it, as the reference's rank-0 save."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(tree, tiled=True)
    return jax.device_get(tree)


def replicate_tree(tree, n: int, mesh: Mesh, specs=None):
    """Broadcast an unboxed pytree to the boxed [n_workers, ...] layout and
    place it sharded over the workers axis (one replica per chip — or per
    tp GROUP of chips when ``specs`` shard leaves over ``'model'`` too)."""
    def put(x, sh):
        x = np.asarray(x)
        return jax.device_put(np.broadcast_to(x[None], (n,) + x.shape), sh)

    if specs is None:
        sh = worker_local_sharding(mesh)
        return jax.tree.map(lambda x: put(x, sh), tree)
    return jax.tree.map(lambda x, s: put(x, NamedSharding(mesh, s)),
                        tree, specs)


def boxed_specs(tree, axis: str = WORKER_AXIS):
    """Prefix every leaf PartitionSpec in ``tree`` with the worker axis
    (``None`` leaves mean replicated)."""
    return jax.tree.map(lambda s: P(axis, *(s or ())), tree, is_leaf=_is_spec)


def state_partition_specs(model, exchanger, axis: str = WORKER_AXIS):
    """Boxed PartitionSpecs for the four step-state parts.

    Data-parallel-only models (``param_specs() is None``, the whole CNN zoo):
    the uniform prefix ``P(axis)`` — every leaf is a per-worker replica.

    Tensor-parallel models declare per-leaf specs over the ``'model'`` axis
    (``parallel/tp.py``); here they are prefixed with the worker axis and
    propagated structurally to the optimizer state (same per-leaf layout as
    the params they belong to — ``utils/opt.py``) and the exchanger's extra
    state (``Exchanger.extra_specs``).
    """
    pspecs = model.param_specs()
    if pspecs is None:
        return {k: P(axis)
                for k in ("params", "opt_state", "bn_state", "extra")}

    from ..utils.opt import opt_state_specs
    if not model.config.get("zero_opt", False):
        ospecs = opt_state_specs(model.optimizer, pspecs)
        if model.config.get("ema_decay"):
            # ema_wrap nests the base layout and adds a param-shaped shadow
            ospecs = {"inner": ospecs, "ema": pspecs, "t": P()}
    else:
        # zero1 replaces the layout with flat chunk vectors: every rank-1
        # leaf is [model_shards·chunk], one chunk per model-group rank —
        # sharded over ALL non-worker mesh axes so each device unboxes its
        # own [chunk]; scalars (adam/ema step counts) stay replicated.
        # eval_shape on the wrapped init derives the exact layout for any
        # inner optimizer/wrapper combination without running it.
        maxes = tuple(a for a in model.mesh.axis_names if a != axis)
        shapes = jax.eval_shape(model.opt.init, model.params)
        ospecs = jax.tree.map(
            lambda l: P(maxes) if l.ndim else P(), shapes)
    bn = jax.tree.map(lambda x: P(), model.bn_state)
    return {"params": boxed_specs(pspecs, axis),
            "opt_state": boxed_specs(ospecs, axis),
            "bn_state": boxed_specs(bn, axis),
            "extra": boxed_specs(exchanger.extra_specs(pspecs), axis)}


def _is_spec(x) -> bool:
    return x is None or isinstance(x, P)


def spec_mentions(s, axes) -> bool:
    """True when PartitionSpec ``s`` shards over any of ``axes`` (entries
    may be axis names or tuples of axis names)."""
    for e in (s or ()):
        for a in (e if isinstance(e, (tuple, list)) else (e,)):
            if a in axes:
                return True
    return False


def anchor_invariant(value, axes):
    """Re-establish the statically-known invariance of ``value`` over mesh
    ``axes`` when it is SEMANTICALLY replicated there but the vma tracking
    lost the proof (e.g. after a flatten that joined sharded and replicated
    leaves).  ``psum(where(rank==0, v, 0))`` is bit-exact for any axis size
    (v + zeros) and marks the output invariant; all_gather+[0] does not."""
    if not axes:
        return value
    from jax import lax
    r0 = sum(lax.axis_index(a) for a in axes) == 0
    return lax.psum(jnp.where(r0, value, jnp.zeros_like(value)), axes)


def local_param_template(params, pspecs, mesh: Mesh):
    """Zeros shaped like each leaf's LOCAL shard under ``pspecs`` — what a
    device actually sees inside shard_map.  Sizes the error-feedback state
    of compressed strategies under tensor parallelism."""
    def shrink(x, s):
        shape = list(np.shape(x))
        for i, ax in enumerate(s or ()):
            for a in (ax if isinstance(ax, (tuple, list)) else (ax,)):
                if a is not None:
                    assert shape[i] % mesh.shape[a] == 0, \
                        f"dim {i} of {tuple(np.shape(x))} not divisible " \
                        f"by mesh axis {a!r}={mesh.shape[a]}"
                    shape[i] //= mesh.shape[a]
        dtype = getattr(x, "dtype", None) or np.asarray(x).dtype
        return jnp.zeros(shape, dtype)

    return jax.tree.map(shrink, params, pspecs)


# ---------------------------------------------------------------------------
# microbatch gradient accumulation (reference: n_subb sub-batches, §3.4)
# ---------------------------------------------------------------------------

def _vary(x, axis: str):
    """Mark a replicated value as device-varying for shard_map's vma type
    system (scan carries that accumulate per-worker values need this).
    Idempotent: an already-varying value passes through — pcast raises on
    varying→varying, and callers like _revary_bn see either (the async
    rules' sync_bn is the identity, so their BN stats arrive varying;
    BSP's pmean'd stats arrive invariant).

    Version-robust across the jax API churn around the vma system
    (round-5 ADVICE): ``jax.typeof`` may be absent while ``lax.pcast``
    exists — the varying→varying pcast then fails with whatever error
    that version raises, so the failure is caught BROADLY and falls back
    to ``lax.pvary``.  A failure is masked only when the value cannot be
    proven non-varying (no typeof to consult): when typeof CAN prove the
    value was not already varying, the error is genuine misuse (wrong
    axis name, outside shard_map) and re-raises at the call site.  On
    versions predating the vma system entirely (no pcast, no pvary —
    e.g. 0.4.x, where shard_map tracks replication via check_rep
    instead) the marker is a no-op by construction."""
    typeof = getattr(jax, "typeof", None)

    def already_varying():
        """True/False when typeof can answer, None when it can't."""
        if typeof is None:
            return None
        try:
            vma = getattr(typeof(x), "vma", None)
            return None if vma is None else (axis in vma)
        except Exception:
            return None

    if already_varying():
        return x
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        try:
            return pcast(x, (axis,), to="varying")
        except Exception:      # varying→varying, or signature drift
            if already_varying() is False:
                raise          # provably NOT varying — genuine misuse
    pvary = getattr(lax, "pvary", None)
    if pvary is not None:
        try:
            return pvary(x, (axis,))
        except Exception:      # already varying on a pvary that checks
            if already_varying() is False:
                raise
            return x
    return x


def _revary_bn(bn_state, axis: str):
    """Re-mark synced BN stats as worker-varying.  ``sync_bn``'s pmean
    returns worker-INVARIANT values (the whole point — replicas stay in
    lockstep), but the boxed state carry is worker-varying by type: under
    ``steps_per_call > 1`` the ``lax.scan`` carry would mismatch
    (``float32[...]{V:workers}`` in, plain ``float32[...]`` out) and
    refuse to trace — found pre-hardware by the round-5 AOT compile of
    the staged ``resnet50-*-spc8`` rows (BN models never met spc>1
    anywhere else: AlexNet/GoogLeNet/VGG use LRN).  The cast is
    type-level only; values are identical on every worker."""
    return jax.tree.map(lambda x: _vary(x, axis), bn_state)


def _accumulate_grads(loss_and_metrics: Callable, params, bn_state, batch,
                      rng, n_subb: int, axis: str = WORKER_AXIS):
    """Grad accumulation over ``n_subb`` microbatches as a ``lax.scan``.

    ``loss_and_metrics(params, bn_state, batch, rng, train=True)`` must
    return ``(cost, (err, new_bn_state))``.  BN state threads sequentially
    through microbatches (matching the reference's sequential sub-batch
    execution).
    """

    def lf(p, bn, b, r):
        return loss_and_metrics(p, bn, b, r, True)

    if n_subb == 1:
        (cost, (err, new_bn)), grads = jax.value_and_grad(lf, has_aux=True)(
            params, bn_state, batch, rng)
        return cost, err, grads, new_bn

    def reshape(x):
        assert x.shape[0] % n_subb == 0, (
            f"batch dim {x.shape[0]} not divisible by n_subb={n_subb}")
        return x.reshape((n_subb, x.shape[0] // n_subb) + x.shape[1:])

    micro = jax.tree.map(reshape, batch)

    def body(carry, mb):
        acc_g, acc_c, acc_e, bn, key = carry
        key, sub = jax.random.split(key)
        (cost, (err, bn)), grads = jax.value_and_grad(lf, has_aux=True)(
            params, bn, mb, sub)
        acc_g = jax.tree.map(jnp.add, acc_g, grads)
        return (acc_g, acc_c + cost, acc_e + err, bn, key), None

    zero_g = jax.tree.map(jnp.zeros_like, params)
    zero_c = _vary(jnp.zeros(()), axis)
    (acc_g, acc_c, acc_e, new_bn, _), _ = lax.scan(
        body, (zero_g, zero_c, zero_c, bn_state, rng), micro)
    inv = 1.0 / n_subb
    return acc_c * inv, acc_e * inv, jax.tree.map(lambda g: g * inv, acc_g), new_bn


# ---------------------------------------------------------------------------
# step builders
# ---------------------------------------------------------------------------

# fold tag separating the fused-exchange key stream from the step rng's
# dropout stream (which folds (ridx, count) in the other order)
FUSED_EXCHANGE_FOLD = 0x0E5D


def fused_exchange_key(rng):
    """Base key for the in-scan fused exchange cadence (one per multi-step
    dispatch, traced).  The standalone cadence consumes host-split keys
    (``model.next_exchange_key()``); fusing the exchange into the scanned
    step replaces that host draw with a deterministic traced stream:
    rules fold the step count in themselves (``exchange_body``'s
    ``fold_in(key, count)``), so ONE base key per call yields per-step
    draws — the GoSGD RNG contract (docs/design.md §"fused cadence")."""
    return jax.random.fold_in(rng, FUSED_EXCHANGE_FOLD)


def build_train_step(mesh: Mesh, model, exchanger, n_steps: int = 1) -> Callable:
    """Compile the training step.

    Returns ``train_fn(state_dict, batch, lr, rng, count) ->
    (state_dict, cost[n], err[n])`` where ``state_dict`` has boxed leaves and
    is donated (params update in place in HBM, as the reference's in-place
    Theano updates did).

    ``n_steps > 1`` (config ``steps_per_call``): a ``lax.scan`` runs that
    many FULL training steps per dispatch over a stacked ``[k, ...]`` batch —
    the per-call host cost (pytree flatten + hundreds of buffer handles) is
    paid once per k steps instead of per step.  Profiling motivation: on one
    v5e chip the ResNet-50 step showed 13.2 ms device-busy inside a 17.8 ms
    wall step — ~26% host dispatch.  Valid for EVERY rule: exchangers with a
    post-step collective (EASGD/ASGD/GoSGD, BSP params mode) have their
    cadence fused into the scan — each scanned step ends with
    ``lax.cond(count % exchange_freq == 0, exchange_body, identity)`` — so
    one dispatch covers k steps INCLUDING their cadenced exchanges and the
    between-steps Python hook is skipped (``exchanger.fused``); BSP grads
    mode has no post-step hook to begin with.  ``count`` is the index of
    the LAST step in the call.

    Pipelined models compose for free (round 10, ISSUE 16): the model's
    loss calls ``pipeline_apply`` whose whole schedule — fill/drain or
    interleaved virtual stages (``pp_interleave``), ``v·M + pp − 1``
    ticks of chunk compute, per-slot ``ppermute_start/done`` hops,
    inject/collect masks — is ONE inner ``lax.scan`` inside the loss.
    Under ``n_steps > 1`` that scan nests inside this function's step
    scan, so the host still dispatches once per k-step window even with
    pipelining on: a whole pipeline round (forward schedule + its scan
    transpose) per scanned step, zero host round-trips between ticks.
    The schedule table is static (a pure function of ``(pp, v, M)``
    baked at trace time), so fusing changes no cache key beyond the
    ``pp_interleave`` extra ``utils/compile_cache.key_extra`` stamps.
    """
    axis = WORKER_AXIS
    n = mesh.shape[axis]
    n_subb = getattr(model, "n_subb", 1)
    fsdp = getattr(model, "_fsdp", None)       # FsdpLayout when fsdp=true
    fuse_exchange = n_steps > 1 and exchanger.has_exchange()
    exchange_freq = int(getattr(exchanger, "exchange_freq", 1))
    # numerics health plane (utils/numerics, docs/design.md §25): None
    # unless config `numerics` is on — the off path below is byte-identical
    # to a build without the plane (the inertness contract).  When on, the
    # sample is computed under ``lax.cond(c % numerics_every == 0, ...)``
    # (the same invariant-count cadence pattern as the fused exchange),
    # carried as a latest-sample scan carry, and returned as a 4th output
    # with one P(axis) out-spec per key — the boxed [n_workers] layout IS
    # the beacon's cross-rank gather, with zero extra host round-trips.
    nx = numerics.graph_plan(model, exchanger, axis)

    def mark_varying(tree):
        return jax.tree.map(lambda x: _vary(x, axis), tree)

    def gated_sample(prev, ing, c):
        """The cadence-gated sample: compute on ``c % every == 0``, else
        keep the carried latest sample.  Both arms are re-marked worker-
        varying — the compute arm's ``iter`` derives from the invariant
        count while the carry is varying, and cond arms must agree."""
        if nx.every == 1:
            return mark_varying(nx.compute(*ing, c))
        return lax.cond(c % nx.every == 0,
                        lambda _: mark_varying(nx.compute(*ing, c)),
                        lambda _: mark_varying(prev), 0)

    def fsdp_step(state, batch, lr, rng, count):
        # FSDP / ZeRO-3 (parallel/fsdp.py): state["params"] is this
        # worker's [chunk] flat shard.  The loss gathers the full tree
        # per (micro)batch; differentiating w.r.t. the chunk transposes
        # the all_gather into psum_scatter, so grads arrive pre-summed
        # over workers — the whole BSP exchange with no exchanger hook.
        chunk = unbox(state["params"])
        opt_state = unbox(state["opt_state"])
        bn_state = unbox(state["bn_state"])
        ridx = lax.axis_index(axis)
        local_rng = jax.random.fold_in(jax.random.fold_in(rng, ridx), count)

        def loss_fn(ch, bn, b, r, train):
            return model.loss_and_metrics(fsdp.gather_params(ch, axis),
                                          bn, b, r, train)

        cost, err, g_chunk, new_bn = _accumulate_grads(
            loss_fn, chunk, bn_state, batch, local_rng, n_subb)
        g_chunk = g_chunk * (1.0 / n)          # transpose summed; BSP means
        g_chunk = fsdp.clip_chunk(
            g_chunk, float(model.config.get("grad_clip", 0.0) or 0.0), axis)
        new_chunk, new_opt = model.opt.update(g_chunk, opt_state, chunk, lr)
        new_bn = _revary_bn(exchanger.sync_bn(new_bn, axis=axis, size=n),
                            axis)
        new_state = {
            "params": box(new_chunk),
            "opt_state": box(new_opt),
            "bn_state": box(new_bn),
            "extra": state["extra"],
        }
        return new_state, cost, err

    def one_step(state, batch, lr, rng, count):
        if fsdp is not None:
            return fsdp_step(state, batch, lr, rng, count)
        params = unbox(state["params"])
        opt_state = unbox(state["opt_state"])
        bn_state = unbox(state["bn_state"])
        extra = unbox(state["extra"])
        ridx = lax.axis_index(axis)
        local_rng = jax.random.fold_in(jax.random.fold_in(rng, ridx), count)

        cost, err, grads, new_bn = _accumulate_grads(
            model.loss_and_metrics, params, bn_state, batch, local_rng, n_subb)

        # Model hooks (traced, optional — models outside ModelBase need not
        # define them): grad transform before the exchange, update gating /
        # param projection after it (GAN n_critic cadence, WGAN clipping).
        pg = getattr(model, "postprocess_grads", None)
        if pg is not None:
            grads = pg(grads, count)
        new_params, new_opt, extra = exchanger.step_update(
            params, opt_state, grads, extra, lr, axis=axis, size=n, count=count)
        pu = getattr(model, "postprocess_update", None)
        if pu is not None:
            new_params, new_opt = pu(params, opt_state, new_params, new_opt,
                                     count)
        # numerics ingredients (§25): the already-live old/new params,
        # grads and extra — handed back for the cadence-gated sample at
        # the per_worker level.  Pure reads; None keeps this path inert.
        ing = None if nx is None else (params, new_params, grads, extra)
        params, opt_state = new_params, new_opt
        new_bn = _revary_bn(exchanger.sync_bn(new_bn, axis=axis, size=n),
                            axis)

        new_state = {
            "params": box(params),
            "opt_state": box(opt_state),
            "bn_state": box(new_bn),
            "extra": box(extra),
        }
        if nx is None:
            return new_state, cost, err
        return new_state, cost, err, ing

    if n_steps == 1:
        if nx is None:
            def per_worker(state, batch, lr, rng, count):
                new_state, cost, err = one_step(state, batch, lr, rng, count)
                return new_state, cost[None], err[None]
        else:
            def per_worker(state, batch, lr, rng, count):
                new_state, cost, err, ing = one_step(state, batch, lr, rng,
                                                     count)
                # no scan to carry a latest sample through: off-cadence
                # dispatches return the template (iter=-1, host skips it)
                smp = gated_sample(nx.template(), ing, count)
                return (new_state, cost[None], err[None],
                        jax.tree.map(lambda x: x[None], smp))
    elif not fuse_exchange:
        if nx is None:
            def per_worker(state, batches, lr, rng, count):
                # batches leaves: [k, local_rows, ...]; count names the
                # LAST step
                count0 = count - (n_steps - 1)

                def body(carry, xs):
                    batch, j = xs
                    new_state, cost, err = one_step(carry, batch, lr, rng,
                                                    count0 + j)
                    return new_state, (cost, err)

                js = _vary(jnp.arange(n_steps), axis)
                state, (costs, errs) = lax.scan(body, state, (batches, js))
                return state, jnp.mean(costs)[None], jnp.mean(errs)[None]
        else:
            def per_worker(state, batches, lr, rng, count):
                # numerics needs an INVARIANT step counter for its cond
                # predicate (js is worker-varying — a varying predicate
                # would poison the collectives inside the sample), so the
                # scan grows the same (c, latest-sample) carry the fused
                # variant below already uses
                count0 = count - (n_steps - 1)

                def body(carry, xs):
                    s, c, smp = carry
                    batch, j = xs
                    s, cost, err, ing = one_step(s, batch, lr, rng,
                                                 count0 + j)
                    smp = gated_sample(smp, ing, c)
                    return (s, c + 1, smp), (cost, err)

                js = _vary(jnp.arange(n_steps), axis)
                smp0 = mark_varying(nx.template())
                (state, _, smp), (costs, errs) = lax.scan(
                    body, (state, count0, smp0), (batches, js))
                return (state, jnp.mean(costs)[None], jnp.mean(errs)[None],
                        jax.tree.map(lambda x: x[None], smp))
    else:
        def per_worker(state, batches, lr, rng, count):
            # fused cadence: the scan carries an INVARIANT step counter c
            # alongside the state — the cond predicate (and the collectives
            # inside the taken branch) must be provably uniform across
            # workers; the varying js stream still feeds one_step's
            # per-step rng fold exactly as in the unfused trace
            count0 = count - (n_steps - 1)
            exch_key = fused_exchange_key(rng)

            def do_exchange(s, c):
                s = exchanger.exchange_body(s, exch_key, c)
                # exchange collectives (pmean/psum-averaged params) come
                # back worker-INVARIANT by type; the scan carry is varying
                # — re-mark, values untouched (same move as _revary_bn)
                return jax.tree.map(lambda x: _vary(x, axis), s)

            if nx is None:
                def body(carry, xs):
                    s, c = carry
                    batch, j = xs
                    s, cost, err = one_step(s, batch, lr, rng, count0 + j)
                    if exchange_freq == 1:
                        s = do_exchange(s, c)
                    else:
                        s = lax.cond(c % exchange_freq == 0,
                                     lambda s: do_exchange(s, c),
                                     lambda s: s, s)
                    return (s, c + 1), (cost, err)

                js = _vary(jnp.arange(n_steps), axis)
                (state, _), (costs, errs) = lax.scan(
                    body, (state, count0), (batches, js))
                return state, jnp.mean(costs)[None], jnp.mean(errs)[None]
            else:
                def body(carry, xs):
                    s, c, smp = carry
                    batch, j = xs
                    s, cost, err, ing = one_step(s, batch, lr, rng,
                                                 count0 + j)
                    if exchange_freq == 1:
                        s = do_exchange(s, c)
                    else:
                        s = lax.cond(c % exchange_freq == 0,
                                     lambda s: do_exchange(s, c),
                                     lambda s: s, s)
                    # sampled from the PRE-exchange ingredients: the stats
                    # describe the step's own update; the beacon trees
                    # (BSP params / the center copy) persist across the
                    # exchange, so desync detection is unaffected
                    smp = gated_sample(smp, ing, c)
                    return (s, c + 1, smp), (cost, err)

                js = _vary(jnp.arange(n_steps), axis)
                smp0 = mark_varying(nx.template())
                (state, _, smp), (costs, errs) = lax.scan(
                    body, (state, count0, smp0), (batches, js))
                return (state, jnp.mean(costs)[None], jnp.mean(errs)[None],
                        jax.tree.map(lambda x: x[None], smp))

    state_spec = state_partition_specs(model, exchanger, axis)
    bs = model.batch_spec()
    base = tuple(bs) if bs is not None else (axis,)
    # n_steps > 1 prefixes the scan dim (round-4: composes with custom
    # batch specs — a sequence-parallel stack is P(None, workers, seq))
    batch_spec = P(*base) if n_steps == 1 else P(None, *base)
    out_specs = (state_spec, P(axis), P(axis))
    if nx is not None:
        out_specs = out_specs + (
            {k: P(axis) for k in numerics.SAMPLE_KEYS},)
    sm = shard_map(
        per_worker, mesh=mesh,
        in_specs=(state_spec, batch_spec, P(), P(), P()),
        out_specs=out_specs,
    )
    return jax.jit(sm, donate_argnums=(0,))


def build_val_step(mesh: Mesh, model) -> Callable:
    """Compile the validation step: each worker evaluates its shard of the
    val batch with its own replica (the reference's per-rank validation).

    Returns ``val_fn(params_boxed, bn_boxed, batch) ->
    (cost[n], err[n], err_top5[n])``.
    """
    axis = WORKER_AXIS

    def per_worker(params, bn_state, batch):
        params = unbox(params)
        bn_state = unbox(bn_state)
        cost, (err, err5) = model.val_metrics(params, bn_state, batch)
        return cost[None], err[None], err5[None]

    pspecs = model.param_specs()
    if pspecs is None:
        p_spec = bn_spec = P(axis)
    else:
        p_spec = boxed_specs(pspecs, axis)
        bn_spec = jax.tree.map(lambda x: P(axis), model.bn_state)
    vb_spec = model.batch_spec()
    if vb_spec is None:
        vb_spec = P(axis)
    sm = shard_map(
        per_worker, mesh=mesh,
        in_specs=(p_spec, bn_spec, vb_spec),
        out_specs=(P(axis), P(axis), P(axis)),
    )
    return jax.jit(sm)


def is_device_batch(batch) -> bool:
    """True if the batch is already mesh-resident (staged by the parallel
    loader's producer thread) — ``train_iter`` then skips ``put_batch``."""
    leaves = jax.tree_util.tree_leaves(batch)
    return bool(leaves) and isinstance(leaves[0], jax.Array)


def is_device_window(window) -> bool:
    """True if ``window`` is an already-staged ``[k, ...]`` stack: leaves
    are mesh-resident ``jax.Array``s whose sharding leads with the
    replicated scan axis (the ``P(None, *base)`` layout ``stage_window``
    produces).  ``train_iter`` / ``put_batch_stack`` then dispatch without
    touching the host — the parallel loader's window producer staged it."""
    leaves = jax.tree_util.tree_leaves(window)
    if not leaves or not isinstance(leaves[0], jax.Array):
        return False
    spec = getattr(leaves[0].sharding, "spec", None)
    return spec is not None and len(spec) > 0 and spec[0] is None


def stack_host(batches):
    """Host-side ``[k, ...]`` stack of k per-step batches — THE window
    layout ``stage_window`` ships to the mesh.  One definition, shared by
    the consumer path (``put_batch_stack``) and the PrefetchLoader window
    producer, so a layout tweak can't silently fork the two streams."""
    return jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *batches)


def stage_window(mesh: Mesh, window, spec=None):
    """Place a ``[k, ...]``-leaved window pytree onto the mesh, sharded
    ``P(None, *base)`` — the scan dim replicated, each step's slice split
    per ``spec`` (default ``P(workers)`` row split).  THE staging
    primitive for multi-step dispatch inputs: the PrefetchLoader's window
    producer calls it off the hot path (the queue then holds
    device-resident windows), and ``put_batch_stack`` routes its
    consumer-thread stacking through it, so the sharding algebra lives in
    exactly one place.

    Multi-host: ``window`` is this host's LOCAL ``[k, local_rows, ...]``
    stack; the global array is stitched from per-process shards without
    cross-host copies (same contract as ``put_batch``)."""
    base = tuple(spec) if spec is not None else (WORKER_AXIS,)
    sh = NamedSharding(mesh, P(None, *base))
    if jax.process_count() > 1:
        from .mesh import make_per_host_array
        return make_per_host_array(mesh, jax.tree.map(np.asarray, window),
                                   sharding=sh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), window)


def put_batch_stack(mesh: Mesh, batches, spec=None):
    """Stack k per-step batches into ``[k, ...]`` leaves for a
    ``steps_per_call`` multi-step dispatch, sharded ``P(None, *base)``
    (scan slices the leading axis; each slice splits per ``spec`` —
    default ``P(workers)`` row split, sequence-parallel models also cut
    the time dim).

    Fast path: a single pre-staged window (the para_load window
    producer's output, ``is_device_window``) passes straight through —
    zero consumer-thread work.  Otherwise the stack routes through
    ``stage_window``; per-step batches already staged on device
    (para_load at spc=1 granularity) stack with ``jnp.stack`` so the
    reshard stays a device-side copy."""
    if not isinstance(batches, (list, tuple)):
        # one whole [k, ...] window, not a list of per-step batches: a
        # pre-staged device window passes straight through; a host window
        # (set_window with stage_fn=None) stages here
        return batches if is_device_window(batches) \
            else stage_window(mesh, batches, spec)
    if jax.process_count() == 1 and all(is_device_batch(b) for b in batches):
        window = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
    else:
        window = stack_host(batches)
    return stage_window(mesh, window, spec)


def put_batch(mesh: Mesh, batch, spec=None):
    """Place a host batch onto the mesh, split across workers.

    Single-process: ``batch`` is the global batch, device_put shards it.
    Multi-host: ``batch`` is this host's LOCAL shard; the global array is
    stitched from per-process data without cross-host copies.

    ``spec``: optional PartitionSpec for every batch leaf beyond the default
    ``P(workers)`` row split (sequence-parallel models also shard the time
    dim, ``model.batch_spec()``).
    """
    if jax.process_count() > 1:
        from .mesh import make_per_host_array
        sharding = None if spec is None else NamedSharding(mesh, spec)
        # custom specs (sequence parallelism) stitch fine as long as each
        # host's devices cover COMPLETE trailing-axis groups (dp across
        # hosts, sp within a host — the natural pod layout); per-host local
        # data is then this host's worker rows × the full extra dims, which
        # make_array_from_process_local_data validates
        return make_per_host_array(mesh, batch, sharding=sharding)
    sh = NamedSharding(mesh, spec) if spec is not None else \
        batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)
