"""Mixture-of-Experts with expert parallelism over the ``'model'`` axis.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 — is pure
data parallelism): a Switch-Transformer-style top-1 MoE FFN whose experts are
SHARDED over the ``'model'`` mesh axis — each chip in a tensor-parallel group
hosts ``E/ep`` complete experts, so the FFN parameter count scales with the
mesh while per-chip compute stays flat.

TPU-first mapping (the Mesh-TensorFlow / Switch einsum formulation):

* routing, capacity masking, and the dispatch one-hot ``[N, E, C]`` are
  computed from REPLICATED activations (identical on every chip of the tp
  group) — no all-to-all is needed: each chip slices ITS experts' columns of
  the dispatch tensor, gathers its tokens with one einsum (an MXU matmul, no
  ragged scatter), runs its experts batched, and one ``psum`` over
  ``'model'`` assembles the combined output.  Static shapes throughout —
  over-capacity tokens are dropped (they ride the residual connection), the
  standard Switch behavior.
* the load-balance auxiliary loss is the Switch one: ``E · Σ_e f_e · P_e``
  (``f_e`` = fraction of tokens routed to expert e, ``P_e`` = mean router
  probability), 1.0 at perfectly uniform routing.

``ep == 1`` (no ``'model'`` axis) runs the identical math without the slice
and psum — pinned equal to a dense MLP when all experts share weights
(``tests/test_moe.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models import layers as L
from .mesh import MODEL_AXIS


class MoE(L.Layer):
    """Top-1 (Switch) mixture of 2-layer MLP experts, optionally expert
    -parallel over ``'model'``.

    ``apply`` returns ``(y, aux)`` — the combined output and the scalar load
    -balance loss — so callers must unpack (the transformer block does).
    """

    has_state = False

    def __init__(self, dim, n_experts, mlp_ratio=4, ep: int = 1,
                 capacity_factor: float = 1.25, w_init=("normal", 0.02),
                 compute_dtype=jnp.bfloat16, axis: str = MODEL_AXIS,
                 name: str = "moe"):
        assert n_experts % ep == 0, \
            f"n_experts={n_experts} not divisible by ep={ep}"
        self.dim, self.n_experts, self.hidden = dim, n_experts, mlp_ratio * dim
        self.ep = ep
        self.capacity_factor = float(capacity_factor)
        self.w_init = w_init
        self.compute_dtype = compute_dtype
        self.axis = axis
        self.name = name

    def init(self, key):
        kg, k1, k2 = jax.random.split(key, 3)
        E, d, f = self.n_experts, self.dim, self.hidden
        return {
            "wg": L.init_weight(kg, (d, E), self.w_init),
            "w1": L.init_weight(k1, (E, d, f), self.w_init),
            "b1": jnp.zeros((E, f)),
            "w2": L.init_weight(k2, (E, f, d), self.w_init),
            "b2": jnp.zeros((E, d)),
        }

    def specs(self):
        """Per-leaf PartitionSpecs: router replicated, experts sharded on
        their leading (expert) dim.  None when ep == 1."""
        if self.ep == 1:
            return None
        from jax.sharding import PartitionSpec as P
        M = self.axis
        return {"wg": P(), "w1": P(M, None, None), "b1": P(M, None),
                "w2": P(M, None, None), "b2": P(M, None)}

    def capacity(self, n_tokens: int, train: bool = True) -> int:
        """Per-expert token slots.  Training uses the Switch capacity bound
        (over-capacity tokens drop to the residual — the load-balance
        pressure); inference is DROP-FREE (capacity = n): dropping at eval
        only hurts, and it keeps the KV-decode sampler (which routes one
        step's tokens at a time) exactly consistent with the full-forward
        one (which routes the whole buffer)."""
        if not train:
            return max(1, n_tokens)
        return max(1, int(np.ceil(
            n_tokens / self.n_experts * self.capacity_factor)))

    def apply(self, params, x, *, train=False, rng=None, state=None):
        cd = self.compute_dtype
        shape = x.shape
        d, E = self.dim, self.n_experts
        xf = x.reshape(-1, d)
        n = xf.shape[0]
        C = self.capacity(n, train)

        # -- routing (fp32, replicated over the model axis) ---------------
        logits = jnp.dot(xf.astype(jnp.float32),
                         params["wg"].astype(jnp.float32))       # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        eidx = jnp.argmax(probs, axis=-1)                        # [N]
        gate = jnp.max(probs, axis=-1)                           # [N]
        assign = jax.nn.one_hot(eidx, E, dtype=jnp.float32)      # [N, E]

        # Switch aux loss: E · Σ_e f_e · P_e  (1.0 at uniform routing)
        f_e = jnp.mean(assign, axis=0)
        p_e = jnp.mean(probs, axis=0)
        aux = E * jnp.sum(f_e * p_e)

        # -- capacity + dispatch one-hot [N, E, C] -------------------------
        pos = jnp.cumsum(assign, axis=0) - 1.0                   # [N, E]
        keep = (pos < C).astype(jnp.float32) * assign
        disp = keep[:, :, None] * jax.nn.one_hot(
            pos.astype(jnp.int32), C, dtype=jnp.float32)

        # -- expert-parallel slice: my E/ep experts ------------------------
        e_loc = E // self.ep
        if self.ep > 1:
            rank = lax.axis_index(self.axis)
            disp = lax.dynamic_slice_in_dim(disp, rank * e_loc, e_loc, axis=1)
            comb_gate = lax.dynamic_slice_in_dim(
                keep * gate[:, None], rank * e_loc, e_loc, axis=1)
            w1, b1 = params["w1"], params["b1"]    # local [E/ep, ...] shards
            w2, b2 = params["w2"], params["b2"]
        else:
            comb_gate = keep * gate[:, None]
            w1, b1, w2, b2 = (params["w1"], params["b1"],
                              params["w2"], params["b2"])

        # -- gather → batched expert MLP → combine (all MXU einsums) -------
        xe = jnp.einsum("nec,nd->ecd", disp.astype(cd), xf.astype(cd))
        h = jax.nn.relu(
            jnp.einsum("ecd,edf->ecf", xe, w1.astype(cd))
            + b1[:, None, :].astype(cd))
        ye = jnp.einsum("ecf,efd->ecd", h, w2.astype(cd)) \
            + b2[:, None, :].astype(cd)
        comb = (disp * comb_gate[:, :, None]).astype(cd)
        y = jnp.einsum("ecd,nec->nd", ye, comb)
        if self.ep > 1:
            y = lax.psum(y, self.axis)
            aux = lax.pmean(aux, self.axis)   # equal values; mark invariant
        return y.reshape(shape).astype(x.dtype), aux
