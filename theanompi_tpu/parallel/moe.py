"""Mixture-of-Experts with expert parallelism over the ``'model'`` axis.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 — is pure
data parallelism): a Switch-Transformer-style top-1 MoE FFN whose experts are
SHARDED over the ``'model'`` mesh axis — each chip in a tensor-parallel group
hosts ``E/ep`` complete experts, so the FFN parameter count scales with the
mesh while per-chip compute stays flat.

TPU-first mapping (the Mesh-TensorFlow / Switch einsum formulation):

* routing, capacity masking, and the dispatch one-hot ``[N, E, C]`` are
  computed from REPLICATED activations (identical on every chip of the tp
  group) — no all-to-all is needed: each chip slices ITS experts' columns of
  the dispatch tensor, gathers its tokens with one einsum (an MXU matmul, no
  ragged scatter), runs its experts batched, and one ``psum`` over
  ``'model'`` assembles the combined output.  Static shapes throughout —
  over-capacity tokens are dropped (they ride the residual connection), the
  standard Switch behavior.
* the load-balance auxiliary loss is the Switch one: ``E · Σ_e f_e · P_e``
  (``f_e`` = fraction of tokens routed to expert e, ``P_e`` = mean router
  probability), 1.0 at perfectly uniform routing.

``ep == 1`` (no ``'model'`` axis) runs the identical math without the slice
and psum — pinned equal to a dense MLP when all experts share weights
(``tests/test_moe.py``).

Round-4, sequence-sharded tokens (``seq_shards > 1``):

* with ``ep == 1`` the experts shard over the **'seq'** axis instead and
  tokens travel by ALL-TO-ALL: each shard routes its local block, gathers
  per-expert slots ``[E, C, d]``, one ``lax.all_to_all`` ships each expert
  group to its owner (which batches S sources' slots through its experts),
  and a second all-to-all returns them for the local combine — the classic
  distributed-Switch dispatch, static shapes throughout.  Capacity is per
  SOURCE shard (S·C total per expert); drop-free capacities reproduce the
  dense math exactly (layer-pinned).
* with ``ep > 1`` (sp×tp) the experts stay on 'model' — activations are
  replicated over that axis, so the existing slice+psum path runs on the
  local token block unchanged.
* the load-balance statistic averages the per-shard token means BEFORE the
  ``Σ f_e·P_e`` product (``pmean`` over 'seq') — the EXACT global aux, not
  the noisier mean-of-products.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models import layers as L
from .mesh import MODEL_AXIS


class MoE(L.Layer):
    """Top-k mixture of 2-layer MLP experts, optionally expert-parallel
    over ``'model'``.  ``top_k=1`` (default) is the Switch formulation;
    ``top_k=2`` the GShard one — the k selected gates renormalize to sum
    1, and choice ranks claim capacity slots in priority order (every
    token's primary route before any secondary).

    ``apply`` returns ``(y, aux)`` — the combined output and the scalar load
    -balance loss — so callers must unpack (the transformer block does).
    """

    has_state = False

    def __init__(self, dim, n_experts, mlp_ratio=4, ep: int = 1,
                 capacity_factor: float = 1.25, w_init=("normal", 0.02),
                 compute_dtype=jnp.bfloat16, axis: str = MODEL_AXIS,
                 seq_shards: int = 1, seq_axis: str = None,
                 top_k: int = 1, name: str = "moe"):
        assert n_experts % ep == 0, \
            f"n_experts={n_experts} not divisible by ep={ep}"
        assert 1 <= int(top_k) <= n_experts, (top_k, n_experts)
        self.top_k = int(top_k)
        if seq_shards > 1 and ep == 1:
            # experts shard over the SEQUENCE axis: the all-to-all dispatch
            assert n_experts % seq_shards == 0, (
                f"n_experts={n_experts} not divisible by sp={seq_shards}")
        self.dim, self.n_experts, self.hidden = dim, n_experts, mlp_ratio * dim
        self.ep = ep
        self.seq_shards = int(seq_shards)
        if seq_axis is None:
            from .mesh import SEQ_AXIS
            seq_axis = SEQ_AXIS
        self.seq_axis = seq_axis
        self.capacity_factor = float(capacity_factor)
        self.w_init = w_init
        self.compute_dtype = compute_dtype
        self.axis = axis
        self.name = name

    def init(self, key):
        kg, k1, k2 = jax.random.split(key, 3)
        E, d, f = self.n_experts, self.dim, self.hidden
        return {
            "wg": L.init_weight(kg, (d, E), self.w_init),
            "w1": L.init_weight(k1, (E, d, f), self.w_init),
            "b1": jnp.zeros((E, f)),
            "w2": L.init_weight(k2, (E, f, d), self.w_init),
            "b2": jnp.zeros((E, d)),
        }

    def specs(self):
        """Per-leaf PartitionSpecs: router replicated, experts sharded on
        their leading (expert) dim — over ``'model'`` (ep) or over
        ``'seq'`` (the sp all-to-all mode).  None when unsharded."""
        from jax.sharding import PartitionSpec as P
        if self.ep > 1:
            M = self.axis
        elif self.seq_shards > 1:
            M = self.seq_axis
        else:
            return None
        return {"wg": P(), "w1": P(M, None, None), "b1": P(M, None),
                "w2": P(M, None, None), "b2": P(M, None)}

    def capacity(self, n_tokens: int, train: bool = True) -> int:
        """Per-expert token slots.  Training uses the Switch capacity bound
        (over-capacity tokens drop to the residual — the load-balance
        pressure); inference is DROP-FREE (capacity = n): dropping at eval
        only hurts, and it keeps the KV-decode sampler (which routes one
        step's tokens at a time) exactly consistent with the full-forward
        one (which routes the whole buffer)."""
        if not train:
            return max(1, n_tokens)
        # top_k routes k·n assignments over E experts — capacity scales
        # with k (GShard), else secondaries would drop even at perfect
        # balance
        return max(1, int(np.ceil(
            n_tokens * self.top_k / self.n_experts * self.capacity_factor)))

    def apply(self, params, x, *, train=False, rng=None, state=None):
        cd = self.compute_dtype
        shape = x.shape
        d, E = self.dim, self.n_experts
        xf = x.reshape(-1, d)
        n = xf.shape[0]
        C = self.capacity(n, train)

        # -- routing (fp32, replicated over the model axis) ---------------
        logits = jnp.dot(xf.astype(jnp.float32),
                         params["wg"].astype(jnp.float32))       # [N, E]
        probs = jax.nn.softmax(logits, axis=-1)
        K = self.top_k
        topv, topi = lax.top_k(probs, K)                         # [N, K]
        if K > 1:
            # GShard-style: the k selected gates renormalize to sum 1
            topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
        assigns = [jax.nn.one_hot(topi[:, j], E, dtype=jnp.float32)
                   for j in range(K)]                            # k × [N, E]

        # Switch aux loss on the PRIMARY assignment: E · Σ_e f_e · P_e
        # (1.0 at uniform routing)
        f_e = jnp.mean(assigns[0], axis=0)
        p_e = jnp.mean(probs, axis=0)
        if self.seq_shards > 1:
            # EXACT global routing fractions: average the per-shard token
            # means BEFORE the product (mean-of-products would be a noisier
            # estimator and deviate from the dense objective)
            f_e = lax.pmean(f_e, self.seq_axis)
            p_e = lax.pmean(p_e, self.seq_axis)
        aux = E * jnp.sum(f_e * p_e)

        # -- capacity + dispatch one-hot [N, E, C] -------------------------
        # choice ranks claim slots in PRIORITY order (every token's primary
        # route before any secondary — GShard's ordering): rank j's
        # positions continue from the slots ranks < j actually kept
        disp = jnp.zeros((n, E, C), jnp.float32)
        comb_gate = jnp.zeros((n, E), jnp.float32)
        base = jnp.zeros((E,), jnp.float32)
        for j in range(K):
            a = assigns[j]
            pos = jnp.cumsum(a, axis=0) - 1.0 + base[None, :]    # [N, E]
            kept = (pos < C).astype(jnp.float32) * a
            disp = disp + kept[:, :, None] * jax.nn.one_hot(
                pos.astype(jnp.int32), C, dtype=jnp.float32)
            comb_gate = comb_gate + kept * topv[:, j:j + 1]
            base = base + jnp.sum(kept, axis=0)

        if self.ep == 1 and self.seq_shards > 1:
            y, aux = self._apply_seq_a2a(params, xf, disp, comb_gate, aux,
                                         C, cd)
            return y.reshape(shape).astype(x.dtype), aux

        # -- expert-parallel slice: my E/ep experts ------------------------
        e_loc = E // self.ep
        if self.ep > 1:
            rank = lax.axis_index(self.axis)
            disp = lax.dynamic_slice_in_dim(disp, rank * e_loc, e_loc, axis=1)
            comb_gate = lax.dynamic_slice_in_dim(
                comb_gate, rank * e_loc, e_loc, axis=1)
        w1, b1 = params["w1"], params["b1"]    # local [E/ep, ...] shards
        w2, b2 = params["w2"], params["b2"]

        # -- gather → batched expert MLP → combine (all MXU einsums) -------
        xe = jnp.einsum("nec,nd->ecd", disp.astype(cd), xf.astype(cd))
        h = jax.nn.relu(
            jnp.einsum("ecd,edf->ecf", xe, w1.astype(cd))
            + b1[:, None, :].astype(cd))
        ye = jnp.einsum("ecf,efd->ecd", h, w2.astype(cd)) \
            + b2[:, None, :].astype(cd)
        comb = (disp * comb_gate[:, :, None]).astype(cd)
        y = jnp.einsum("ecd,nec->nd", ye, comb)
        if self.ep > 1:
            y = lax.psum(y, self.axis)
            aux = lax.pmean(aux, self.axis)   # equal values; mark invariant
        return y.reshape(shape).astype(x.dtype), aux

    def _apply_seq_a2a(self, params, xf, disp, comb_gate, aux, C, cd):
        """Sequence-sharded expert parallelism: experts live on the 'seq'
        shards, so each chip's locally-routed tokens travel to their
        expert's chip with ONE ``lax.all_to_all`` (and return with one) —
        the classic distributed-Switch dispatch, static shapes throughout.

        Capacity accounting is per SOURCE shard (each of the S shards
        reserves C slots per expert from its own token block), so an expert
        processes up to S·C slots — the same total budget as the replicated
        path, with drops distributed per shard.  Drop-free capacities are
        exactly the dense math (tested).
        """
        S, E = self.seq_shards, self.n_experts
        e_loc = E // S
        d = self.dim
        # my tokens, gathered into per-expert slots: [E, C, d] → grouped by
        # owner shard [S, e_loc, C, d]; the a2a ships group s to shard s and
        # returns every shard's slots for MY experts (dim 0 = source shard)
        xe = jnp.einsum("nec,nd->ecd", disp.astype(cd), xf.astype(cd))
        xe = xe.reshape(S, e_loc, C, d)
        xe = lax.all_to_all(xe, self.seq_axis, split_axis=0, concat_axis=0)
        # batched local-expert MLP over all sources' slots
        w1, b1 = params["w1"], params["b1"]        # local [e_loc, ...]
        w2, b2 = params["w2"], params["b2"]
        h = jax.nn.relu(
            jnp.einsum("secd,edf->secf", xe, w1.astype(cd))
            + b1[None, :, None, :].astype(cd))
        ye = jnp.einsum("secf,efd->secd", h, w2.astype(cd)) \
            + b2[None, :, None, :].astype(cd)
        # return every source's slots, re-assemble my [E, C, d], combine
        ye = lax.all_to_all(ye, self.seq_axis, split_axis=0, concat_axis=0)
        ye = ye.reshape(E, C, d)
        comb = (disp * comb_gate[:, :, None]).astype(cd)
        y = jnp.einsum("ecd,nec->nd", ye, comb)
        return y, aux       # aux already global+invariant (pmean'd f/P)
