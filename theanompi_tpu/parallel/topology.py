"""Peer-routing table algebra — the jax-free half of the GoSGD/mesh
topology story.

The GoSGD exchanger's routing tables (derangements, iid assignment maps,
collision-round decomposition) and the elastic active-set embedding are
pure seeded numpy: nothing about them needs a device, a mesh, or jax.
Round 17 moves them here so two consumers share ONE implementation:

* :class:`~theanompi_tpu.parallel.exchanger.GOSGD_Exchanger` builds its
  ``lax.switch``/``lax.ppermute`` branches from these tables (the traced
  half stays in exchanger.py);
* ``theanompi_tpu.simfleet`` regenerates the SAME tables under
  membership churn (the real :class:`~.membership.MeshReactor` driving a
  simulated exchanger), so gossip-mixing and Σα-conservation claims at
  1,000-worker width are made about the production routing algebra, not
  a reimplementation.

Seeds are call-site-owned (exchanger keeps its historical ``0x605`` /
``0x1d1`` family seeds) and the generator is the frozen-legacy
``np.random.RandomState``, so tables are reproducible across runs and
releases — the property both the AOT cache keys and the simfleet
byte-identical event log rely on.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def derangements(n: int, k: int, seed: int = 0x605) -> np.ndarray:
    """k distinct random derangements of range(n) (static, seeded).

    Draw-identical to the historical exchanger implementation (same
    RandomState stream, same rejection rule) — only the bookkeeping is
    vectorized, because simfleet regenerates these tables on every
    membership transition of a 1,000-rank mesh."""
    rng = np.random.RandomState(seed)
    idx = np.arange(n)
    out, seen = [], set()
    guard = 0
    while len(out) < k and guard < 10000:
        guard += 1
        p = rng.permutation(n)
        if n > 1 and (p == idx).any():
            continue
        key = p.tobytes()
        if key in seen:
            continue
        seen.add(key)
        out.append(p)
    return np.asarray(out)


def iid_maps(n: int, k: int, seed: int = 0x1d1) -> np.ndarray:
    """k static assignment maps with the reference's iid peer draws:
    ``maps[k][i]`` is sender i's destination, uniform over the other
    workers — NOT a bijection, so collisions (in-degree > 1) occur with
    the same probability as in the reference's independent draws."""
    if n == 1:
        return np.zeros((k, 1), dtype=np.int64)   # self is the only peer
    rng = np.random.RandomState(seed)
    maps = np.empty((k, n), dtype=np.int64)
    for m in range(k):
        draw = rng.randint(0, n - 1, size=n)
        # uniform over [n]\{i}: shift draws >= i up by one
        maps[m] = draw + (draw >= np.arange(n))
    return maps


def collision_rounds(dest: np.ndarray) -> List[List[Tuple[int, int]]]:
    """Decompose an arbitrary assignment map into in-degree-rank rounds:
    round r holds the pairs (sender, dest) where sender is destination's
    r-th inbound.  Each round has unique sources AND unique destinations
    — a partial permutation one ``lax.ppermute`` can route — and every
    sender appears in exactly one round."""
    rounds: list = []
    seen: dict = {}
    for i, d in enumerate(dest):
        r = seen.get(int(d), 0)
        seen[int(d)] = r + 1
        while len(rounds) <= r:
            rounds.append([])
        rounds[r].append((i, int(d)))
    return rounds


def embed_active(sub_tables: np.ndarray, active: Sequence[int],
                 n: int) -> np.ndarray:
    """Lift routing tables over the ACTIVE sub-fleet into full-width
    tables: every inactive rank is a fixed point (``table[r][d] == d`` —
    its α and replica are untouched until readmission), and the active
    ranks route among themselves exactly as ``sub_tables`` prescribes
    over ``range(len(active))``.  This is the elastic-membership
    embedding the reaction matrix (docs/design.md §14) promises: demote
    = drop out of the sub-fleet, readmit = regenerate with the rank back
    in."""
    act = np.asarray(list(active), dtype=np.int64)
    tables = np.tile(np.arange(n), (len(sub_tables), 1))
    if len(sub_tables) and len(act):
        tables[:, act] = act[np.asarray(sub_tables, dtype=np.int64)]
    return tables
