"""Device-mesh runtime core.

TPU-native replacement for Theano-MPI's process/runtime core
(reference: ``theanompi/lib/base.py`` — ``MPI_GPU_Process``: ``MPI.COMM_WORLD``
rank/size discovery plus per-rank GPU binding via ``THEANO_FLAGS=device=cudaN``;
see SURVEY.md §2.1).

On TPU the topology model is inverted: there is ONE Python process per host
driving all local chips, and the "communicator" is a named-axis
:class:`jax.sharding.Mesh`.  What the reference calls a *rank* is a position
along the ``'workers'`` mesh axis; what it does with ``mpirun -np N`` we do
with a mesh of N devices (single host) or ``jax.distributed.initialize`` plus
a global mesh (multi-host, DCN control plane / ICI data plane).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"
MODEL_AXIS = "model"      # tensor/expert-parallel axis (parallel/tp.py)
PIPE_AXIS = "pipe"        # pipeline-stage axis (parallel/pipeline.py)
SEQ_AXIS = "seq"          # sequence-parallel axis (parallel/sp.py)


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the multi-host control plane (replaces ``mpirun`` + MPI_Init).

    Reference equivalent: OpenMPI's job bring-up performed by the ``mpirun``
    command composed in ``theanompi/launcher.py`` (SURVEY.md §2.6).  On TPU
    pods, `jax.distributed.initialize` discovers peers over DCN; collectives
    inside compiled programs then ride ICI.

    No-op when running single-process (the common single-host case) — mirrors
    the reference's ability to run ``-np 1``.
    """
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif coordinator_address is not None:
        # TPU pod slice: remaining args are auto-detected from the environment.
        jax.distributed.initialize(coordinator_address=coordinator_address)


def worker_mesh(
    n_workers: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = WORKER_AXIS,
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
) -> Mesh:
    """Build the data-parallel mesh — the TPU-native "communicator".

    Reference equivalent: the set of MPI ranks created by
    ``mpirun -np N python -m theanompi.worker`` with one rank per GPU
    (SURVEY.md §2.1, §2.6).  Theano-MPI's parallelism surface is pure data
    parallelism in four flavors, so the canonical mesh is 1-D over
    ``'workers'``.

    ``tp > 1`` adds a second ``'model'`` axis (``n_workers × tp`` devices):
    each data-parallel "worker" becomes a GROUP of ``tp`` chips sharing one
    tensor-parallel model replica (``parallel/tp.py``).  The inner (fastest
    -varying) axis is ``'model'`` so a TP group sits on adjacent chips —
    per-layer psums ride the shortest ICI hops, the dp collective the longer
    ones, matching their per-step frequencies.

    ``pp > 1`` adds a ``'pipe'`` axis (pipeline stages,
    ``parallel/pipeline.py``); ``tp`` and ``pp`` COMPOSE on a 3-D
    ``(workers, pipe, model)`` mesh — 'pipe' outer (one activation shift per
    stage per microbatch), 'model' inner (per-layer psums, the most frequent
    collective, ride adjacent chips).  Interleaved virtual stages
    (``pp_interleave``, round 10) are a SCHEDULE property, not a mesh
    one: each of the ``pp`` devices on 'pipe' holds ``v`` non-contiguous
    layer chunks and walks the interleaved schedule table, so the mesh
    stays exactly this shape for every ``v`` — only the hop pattern
    changes (full ring instead of the fill/drain partial shift).  ``sp > 1`` adds a ``'seq'`` axis
    (sequence blocks, ``parallel/sp.py``); EVERY tp/pp/sp combination
    composes (round-4), up to the full ``(workers, pipe, model, seq)``
    stack — 'seq' innermost so ring-attention ppermutes (once per block
    per ring tick, the hottest shifts) ride adjacent chips.
    """
    if devices is None:
        devices = jax.devices()
    tp, pp, sp = int(tp), int(pp), int(sp)
    group = tp * pp * sp
    axes, shape = [axis_name], [0]
    for g, a in ((pp, PIPE_AXIS), (tp, MODEL_AXIS), (sp, SEQ_AXIS)):
        if g > 1:
            axes.append(a)
            shape.append(g)
    if n_workers is None:
        n_workers = len(devices) // group
        if n_workers == 0:
            raise ValueError(
                f"group size {group} needs at least that many devices but "
                f"only {len(devices)} are visible")
        rem = len(devices) - n_workers * group
        if rem:
            # flooring silently idles chips (8 devices, tp=3 → 6 used) and
            # quietly skews per-chip throughput numbers — make it visible
            import warnings
            warnings.warn(
                f"worker_mesh: {len(devices)} devices don't divide by "
                f"group tp*pp*sp={group}; {rem} chip(s) left idle — pass "
                f"n_workers explicitly to silence", stacklevel=2)
    need = n_workers * group
    if need > len(devices):
        raise ValueError(
            f"requested {n_workers} workers × group {group} "
            f"(tp={tp}, pp={pp}, sp={sp}) = {need} devices but only "
            f"{len(devices)} are visible ({[str(d) for d in devices]})"
        )
    shape[0] = n_workers
    dev = np.asarray(devices[:need]).reshape(shape)
    return Mesh(dev, tuple(axes))


def mesh_size(mesh: Mesh, axis_name: str = WORKER_AXIS) -> int:
    return mesh.shape[axis_name]


def batch_sharding(mesh: Mesh, axis_name: str = WORKER_AXIS) -> NamedSharding:
    """Sharding for a global batch: leading dim split across workers.

    Reference equivalent: each MPI rank loading its own shard of the
    ``.hkl`` filename list (SURVEY.md §2.8) — here the split is expressed as
    a sharding constraint and XLA moves nothing if each host fed its own
    shard via ``make_per_host_array``.
    """
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for replicated state (BSP params, center params)."""
    return NamedSharding(mesh, P())


def worker_local_sharding(mesh: Mesh, axis_name: str = WORKER_AXIS) -> NamedSharding:
    """Sharding for per-worker-divergent state (EASGD/ASGD/GoSGD params).

    The async rules let each worker's parameters drift between syncs
    (SURVEY.md §2.2).  On an SPMD mesh "per-worker state" is a pytree whose
    leaves carry a leading ``[n_workers]`` axis sharded over ``'workers'`` —
    each chip holds exactly its own replica, no replication cost.
    """
    return NamedSharding(mesh, P(axis_name))


def shard_batch(mesh: Mesh, batch, axis_name: str = WORKER_AXIS):
    """Place a host batch onto the mesh, split across workers."""
    sh = batch_sharding(mesh, axis_name)
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)


def make_per_host_array(mesh: Mesh, local_batch, axis_name: str = WORKER_AXIS,
                        sharding: NamedSharding = None):
    """Assemble a global array from per-host local shards (multi-host path).

    Reference equivalent: there is none needed — each MPI rank simply owned
    its slice.  Under single-controller JAX the per-host loader output is
    stitched into one global ``jax.Array`` without copying across hosts.
    ``sharding`` overrides the default worker row split (``put_batch_stack``
    stitches ``[k, global_rows, ...]`` stacks with a leading scan dim).
    """
    sh = sharding if sharding is not None else batch_sharding(mesh, axis_name)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sh, np.asarray(x)), local_batch
    )
