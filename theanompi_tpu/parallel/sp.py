"""Sequence parallelism as a first-class model mode.

Long-context training (the brief's first-class requirement; the reference —
Theano-MPI, SURVEY.md §1 — is CNN-only) shards the SEQUENCE dimension over a
``'seq'`` mesh axis: activations hold ``T/sp`` tokens per chip, so the
context length scales with the mesh.  Everything per-token (embeddings,
LayerNorm, MLP, LM head, per-token loss) runs unchanged on the local token
block; only attention needs cross-chip communication, and that is the ring
algorithm in ``ops/ring_attention.py`` — K/V blocks rotate via
``lax.ppermute``, online-softmax accumulation, exact math (oracle-pinned).

:class:`RingMultiHeadAttention` is the drop-in attention for a
sequence-sharded ``TransformerLM`` (``sp=k`` config): same init/params as
the dense layer, Q/K/V projections local, one ring pass per block.  Params
stay replicated over ``'seq'`` (specs all ``P()``), so gradient reduction
over the axis falls out of shard_map's varying-axes typing exactly as in
``parallel/tp.py``; the per-token loss just averages with ``pmean`` over the
axis.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..models import layers as L
from .mesh import SEQ_AXIS
from .tp import TPMultiHeadAttention


class RingMultiHeadAttention(L.MultiHeadAttention):
    """Causal MHA over a sequence-SHARDED activation block.

    ``x`` is ``[B, T/sp, D]`` (this chip's token block); projections are
    per-token (local), the attention itself is the exact blockwise ring over
    ``axis`` with causal masking in GLOBAL positions.
    """

    def __init__(self, dim, n_head, causal: bool = True,
                 axis: str = SEQ_AXIS, **kwargs):
        super().__init__(dim, n_head, causal=causal, **kwargs)
        self.axis = axis

    def apply(self, params, x, *, train=False, rng=None, state=None):
        from ..ops.ring_attention import ring_attention
        cd = self.compute_dtype
        b, t_loc, d = x.shape
        h, hd = self.n_head, self.dim // self.n_head
        xc = x.astype(cd)

        def proj(w):
            y = jnp.dot(xc, w.astype(cd))
            return y.reshape(b, t_loc, h, hd).transpose(0, 2, 1, 3)

        q, k, v = proj(params["wq"]), proj(params["wk"]), proj(params["wv"])
        o = ring_attention(q, k, v, axis=self.axis, causal=self.causal)
        o = o.transpose(0, 2, 1, 3).reshape(b, t_loc, d)
        return jnp.dot(o.astype(cd), params["wo"].astype(cd))


def sp_mean(x, axis: str = SEQ_AXIS):
    """Average a per-local-token-block scalar over the sequence axis (equal
    token counts per shard, so the plain mean of means is the global mean);
    marks the result invariant for the step's out-spec typing."""
    return lax.pmean(x, axis)


class TPRingMultiHeadAttention(TPMultiHeadAttention):
    """Head-sharded AND sequence-sharded attention (round-4: 3-D
    data×seq×model composition).

    ``x`` is ``[B, T/sp, D]`` (this chip's token block) and the weight
    shards hold ``n_head/tp`` complete heads (``parallel/tp.py`` layout):
    Q/K/V projections are local in BOTH senses (own tokens, own heads) —
    the whole TP apply body is inherited — and only the attention itself
    differs: the exact causal ring over the ``'seq'`` axis on the local
    heads (the two shardings are orthogonal).  Same init and math as the
    dense layer.
    """

    def __init__(self, dim, n_head, tp: int, causal: bool = True,
                 seq_axis: str = SEQ_AXIS, **kwargs):
        super().__init__(dim, n_head, tp, causal=causal, **kwargs)
        self.seq_axis = seq_axis

    def _attend(self, q, k, v):
        from ..ops.ring_attention import ring_attention
        return ring_attention(q, k, v, axis=self.seq_axis,
                              causal=self.causal)
