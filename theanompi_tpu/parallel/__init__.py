"""Parallelism: mesh runtime, exchangers (BSP/EASGD/ASGD/GoSGD), collective
strategies, SPMD step assembly."""

from .mesh import WORKER_AXIS, worker_mesh
from .exchanger import (ASGD_Exchanger, BSP_Exchanger, EASGD_Exchanger,
                        GOSGD_Exchanger, get_exchanger)
from .strategies import get_strategy
