"""Bucketed, overlap-scheduled collectives — the exchange wire in slices.

ROADMAP item 1: every exchange rule used to issue its payload as ONE
monolithic collective (leaf-wise ``lax.psum`` sites in ``exchanger.py``,
whole-vector gathers in ``strategies.py``) that serializes against
compute.  The CUDA-aware-MPI characterization paper (PAPERS.md,
1810.11112) shows *overlap of reduction with backprop* — not raw
bandwidth — governs scaling; the standard mechanism (NCCL/DDP buckets,
the pjit/TPUv4 LM stack) is to split the payload into size-targeted
buckets and let the scheduler start bucket k's reduction while bucket
k+1's producers (the tail of backprop) are still running.

This module is the ONE bucket planner and pack/collect/unpack engine all
wires share:

* :func:`plan_buckets` — a PURE function of the payload's tree-def +
  leaf shapes/dtypes (never of values): flatten the leaves in tree order
  and greedily close a bucket when it reaches ``bucket_bytes``
  (default :data:`DEFAULT_BUCKET_BYTES` ≈ 4 MiB).  Buckets are
  dtype-homogeneous (a dtype change closes the current bucket — packing
  must never cast, or bucketed ≢ monolithic), and a leaf larger than a
  bucket becomes its own single-leaf bucket, never split across buckets
  mid-leaf and never merged with neighbors.  Purity makes the plan
  stable across compiles, independent of membership masks (masks scale
  VALUES, not shapes), and hashable into the AOT cache key extras
  (:func:`plan_signature`; ``compile_cache.key_extra`` folds the
  ``bucket_bytes`` knob into the rule signature).

* :func:`pack` / :func:`unpack` — leaves ↔ one contiguous 1-D vector
  per bucket.  Reshape+concatenate+slice only: bit-exact round-trip by
  construction.

* :func:`bucketed_psum` (and the generic :func:`bucketed_collect`) —
  issue EVERY bucket's collective start before the first done is
  awaited, through the ``jax_compat`` async start/done shim.  On a
  jaxlib exposing a real async-collective surface the in-flight window
  is explicit; on this one the shim's sync fallback still leaves XLA's
  latency-hiding scheduler N independent collectives to pipeline into
  the backward pass inside the fused scan (``steps.build_train_step``)
  instead of one serializing monolith.  tpulint's collective-discipline
  checker enforces the start/done pairing (every start's ticket must
  reach a done in the same scope — the bucket-balance probe).

Correctness contract (pinned per rule in ``tests/test_buckets.py``):
at fixed membership, bucketed ≡ monolithic BIT-FOR-BIT.  ``psum`` /
``all_gather`` / ``ppermute`` are element-wise in the payload, so
slicing the payload differently cannot change any element's reduction
order across workers — only the schedule.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..jax_compat import psum_done, psum_start

DEFAULT_BUCKET_BYTES = 4 << 20          # ~4 MiB, the DDP/NCCL sweet spot


class Bucket(NamedTuple):
    """One wire slice: which flat leaf segments ride together."""

    dtype: str                 # numpy dtype name — buckets never mix dtypes
    leaf_ids: Tuple[int, ...]  # indices into the flattened leaf list
    sizes: Tuple[int, ...]     # element count per member leaf (same order)

    @property
    def size(self) -> int:
        return sum(self.sizes)

    def nbytes(self) -> int:
        return self.size * np.dtype(self.dtype).itemsize


class BucketPlan(NamedTuple):
    """The full schedule: every non-empty leaf appears in exactly one
    bucket, in tree order; empty leaves are carried through untouched
    (nothing to reduce, nothing on the wire)."""

    bucket_bytes: int
    buckets: Tuple[Bucket, ...]
    n_leaves: int              # total leaves of the planned tree
    empty_leaf_ids: Tuple[int, ...]

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)


def plan_buckets(tree, bucket_bytes: int = DEFAULT_BUCKET_BYTES
                 ) -> BucketPlan:
    """Deterministic bucket plan for ``tree`` — a pure function of its
    tree-def and leaf shapes/dtypes (traced values are fine: only
    ``.shape``/``.dtype`` are read).  ``bucket_bytes <= 0`` degenerates
    to one bucket per dtype run (still covered by the same pack/collect
    machinery, useful for tests)."""
    bucket_bytes = int(bucket_bytes)
    leaves = jax.tree.leaves(tree)
    buckets: List[Bucket] = []
    empty: List[int] = []
    cur_ids: List[int] = []
    cur_sizes: List[int] = []
    cur_dtype = None
    cur_bytes = 0

    def close():
        nonlocal cur_ids, cur_sizes, cur_dtype, cur_bytes
        if cur_ids:
            buckets.append(Bucket(cur_dtype, tuple(cur_ids),
                                  tuple(cur_sizes)))
        cur_ids, cur_sizes, cur_dtype, cur_bytes = [], [], None, 0

    for i, leaf in enumerate(leaves):
        size = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
        if size == 0:
            empty.append(i)
            continue
        dt = np.dtype(getattr(leaf, "dtype", None)
                      or np.asarray(leaf).dtype)
        nbytes = size * dt.itemsize
        if cur_dtype is not None and dt.name != cur_dtype:
            close()                       # dtype-homogeneous buckets only
        if bucket_bytes > 0 and nbytes >= bucket_bytes:
            close()                       # oversized leaf: its own bucket,
            buckets.append(Bucket(dt.name, (i,), (size,)))  # never split
            continue
        cur_ids.append(i)
        cur_sizes.append(size)
        cur_dtype = dt.name
        cur_bytes += nbytes
        if bucket_bytes > 0 and cur_bytes >= bucket_bytes:
            close()
    close()
    return BucketPlan(bucket_bytes, tuple(buckets), len(leaves),
                      tuple(empty))


def plan_signature(plan: BucketPlan) -> str:
    """Compact stable identity of one plan (AOT key extras, bench rows):
    ``<bucket_bytes>:<n_buckets>b/<n_leaves>l``."""
    return f"{plan.bucket_bytes}:{plan.n_buckets}b/{plan.n_leaves}l"


def count_buckets(tree, bucket_bytes: int) -> int:
    """Collectives one bucketed exchange of ``tree`` issues (bench's
    ``n_buckets`` row column)."""
    return plan_buckets(tree, bucket_bytes).n_buckets


# ---------------------------------------------------------------------------
# pack / unpack
# ---------------------------------------------------------------------------

def pack(tree, plan: BucketPlan) -> List[jnp.ndarray]:
    """Leaves → one contiguous 1-D vector per bucket (dtype preserved —
    packing must never cast, or bucketed ≢ monolithic)."""
    leaves = jax.tree.leaves(tree)
    assert len(leaves) == plan.n_leaves, (
        f"plan built for {plan.n_leaves} leaves, tree has {len(leaves)} — "
        "plan and payload tree drifted")
    out = []
    for b in plan.buckets:
        segs = [leaves[i].reshape(-1) for i in b.leaf_ids]
        out.append(segs[0] if len(segs) == 1 else jnp.concatenate(segs))
    return out


def unpack(vectors: Sequence[jnp.ndarray], tree, plan: BucketPlan):
    """Inverse of :func:`pack`, shaped/structured like ``tree`` (whose
    leaves supply shape+dtype; empty leaves pass through verbatim)."""
    leaves, treedef = jax.tree.flatten(tree)
    out: List[Any] = list(leaves)         # empty leaves keep their slot
    assert len(vectors) == plan.n_buckets
    for b, vec in zip(plan.buckets, vectors):
        ofs = 0
        for i, size in zip(b.leaf_ids, b.sizes):
            # static slice bounds — the plan is Python-level, so XLA sees
            # plain slices it can fuse with the consumer
            out[i] = vec[ofs:ofs + size].reshape(np.shape(leaves[i]))
            ofs += size
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# bucketed collectives
# ---------------------------------------------------------------------------

def bucketed_collect(tree, plan: BucketPlan,
                     start_fn: Callable[[jnp.ndarray], Any],
                     done_fn: Callable[[Any], jnp.ndarray]):
    """The overlap schedule every bucketed wire shares: pack, issue EVERY
    bucket's ``start_fn`` before awaiting the first ``done_fn`` (so a
    real async surface has all buckets in flight at once and the sync
    fallback still hands XLA independent collectives to pipeline), then
    unpack.  ``start_fn``/``done_fn`` wrap one ``jax_compat`` async pair
    — tpulint's collective-discipline bucket-balance probe checks every
    ticket list produced here is drained."""
    tickets = [start_fn(vec) for vec in pack(tree, plan)]
    reduced = [done_fn(t) for t in tickets]
    return unpack(reduced, tree, plan)


def bucketed_psum(tree, axis: str, bucket_bytes: int,
                  plan: BucketPlan = None):
    """Per-bucket ``psum`` of ``tree`` over mesh axis ``axis`` —
    bit-identical to the leaf-wise monolithic ``lax.psum`` (the reduction
    is element-wise; bucketing changes the schedule, not any element's
    cross-worker sum).  ``bucket_bytes <= 0`` falls back to the
    leaf-wise monolithic path so one call site serves both modes."""
    if plan is None:
        if int(bucket_bytes) <= 0:
            return jax.tree.map(lambda x: jax.lax.psum(x, axis), tree)
        plan = plan_buckets(tree, bucket_bytes)
    return bucketed_collect(
        tree, plan,
        lambda vec: psum_start(vec, axis),
        lambda t: psum_done(t))
