"""Collective exchange strategies — the communication backend zoo.

TPU-native rebuild of Theano-MPI's ``theanompi/lib/exchanger_strategy.py``
(SURVEY.md §2.3), the reference's richest ``lib/`` file.  There, a BSP
exchange could run over host-staged MPI (``Exch_allreduce``), CUDA-aware MPI
(``Exch_ar``), a hand-written alltoall-sum-allgather ring with inline PyCUDA
fp16 pack/unpack kernels (``Exch_asa32/asa16``, ``Exch_copper(16)``), or NCCL
(``Exch_nccl32/16``).  On TPU all of these are expressible as XLA collectives
over the ICI mesh, but the *capability* — a selectable wire
format/algorithm — is preserved:

==================  =====================================================
reference name(s)   TPU-native strategy
==================  =====================================================
``allreduce``,      :class:`AllReduce` — ``lax.psum`` (XLA picks the ICI
``ar``, ``nccl32``  algorithm; this is the fast default, ≙ NCCL's role)
``nccl16``          :class:`AllReduce` with bfloat16 wire (cast → psum →
                    cast, fp32 master copy untouched)
``asa32``,          :class:`Ring` — explicit reduce-scatter + allgather
``copper``          over ``lax.ppermute`` hops, the same algorithm the
                    reference hand-wrote over MPI point-to-point
``asa16``,          :class:`Ring` with bfloat16 wire per hop (the
``copper16``        reference's inline fp32↔fp16 PyCUDA kernels, N1/N2 in
                    SURVEY.md §2.9, become dtype casts that XLA fuses)
``onebit``,         :class:`OneBit` / :class:`TopK` — error-feedback
``topk``,           compressed exchange (BASELINE.json config #5); sign
``compressed``      bits are bit-packed 8-per-byte before the collective
                    (``theanompi_tpu.ops.compress``)
==================  =====================================================

Every strategy is a pure function traced INSIDE the compiled step (within a
``shard_map`` over the ``'workers'`` mesh axis), so comm fuses with compute
and rides ICI — there is no host staging to come back to.

Semantics: every strategy returns the **mean** of the input pytree across
workers (the reference divided by size with a fused PyCUDA kernel).
Stateful strategies (error feedback) carry per-worker state.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..jax_compat import (all_gather_done, all_gather_start, psum_done,
                          psum_start)
from ..utils import helper_funcs
from ..ops import compress as compress_ops
from . import buckets

#: Deliberate non-bit-exact rounding sites, audited by tpulint's
#: dtype-flow checker (docs/design.md §26) — every direct
#: ``.astype(a).astype(b)`` round-trip must carry an entry here.
NONBITEXACT = {
    "Ring.__call__": "owned chunk is rounded to the wire dtype before "
                     "the allgather so every rank (owner included) "
                     "holds the identical bit pattern",
}


class Strategy:
    """Base: callable ``(tree, state, axis, size) -> (mean_tree, new_state)``
    traced inside the compiled SPMD step."""

    name = "base"
    stateful = False
    # True when the strategy operates on one FLATTENED vector rather than
    # leaf-wise: under tensor parallelism the flatten mixes sharded and
    # replicated-leaf segments, so the exchanger re-imposes replication on
    # the replicated leaves afterwards (a pmean over 'model')
    flattens = False
    # bucketed overlap-scheduled wire (parallel/buckets.py, ROADMAP item
    # 1): > 0 splits this strategy's collectives into ~bucket_bytes
    # slices issued as async start/done pairs; 0 keeps the monolithic
    # wire.  Set by BSP_Exchanger from config['bucket_bytes'] — a
    # SCHEDULE knob only: bucketed ≡ monolithic bit-for-bit, pinned per
    # strategy in tests/test_buckets.py.
    bucket_bytes = 0

    def init_state(self, params) -> Any:
        """Per-worker persistent state (unsharded template; the exchanger adds
        the leading ``[n_workers]`` axis)."""
        return ()

    def n_buckets(self, params, bucket_bytes: int) -> Optional[int]:
        """Wire slices one exchange of a ``params``-shaped payload ships
        at ``bucket_bytes`` (bench's ``n_buckets`` row column).  The
        default models the fp32 leaf payload (allreduce-family);
        compressed strategies override with their packed layouts; None =
        this strategy's wire does not bucket (ring's hand-rolled chunk
        pipeline, the no-comm probe)."""
        return buckets.count_buckets(params, bucket_bytes)

    def __call__(self, tree, state, *, axis: str, size: int):
        raise NotImplementedError


class NoComm(Strategy):
    """Local-only pseudo-strategy: the per-worker mean WITHOUT the collective.

    Exists for comm-time measurement (``measure_comm``): the fused BSP step
    hides t_comm inside one XLA program, so the reference's headline
    t_train/t_comm decomposition (SURVEY.md §6) is recovered by differencing
    step time under the selected strategy vs under ``none``.  Training with
    it breaks the BSP invariant — replicas diverge.
    """

    name = "none"

    def n_buckets(self, params, bucket_bytes: int):
        return None                       # no collective, nothing to slice

    def __call__(self, tree, state, *, axis: str, size: int):
        inv = 1.0 / size
        return jax.tree.map(lambda g: g * inv, tree), state


class AllReduce(Strategy):
    """``lax.psum``-based mean — XLA emits the tuned ICI allreduce.

    Covers the reference's ``Exch_allreduce`` / ``Exch_ar`` / ``Exch_nccl32``
    (and ``nccl16`` with ``wire_dtype=bfloat16``): on TPU there is no
    host-staged vs device-aware distinction to preserve, the compiled
    collective IS the device-aware path.
    """

    def __init__(self, wire_dtype=None):
        self.wire_dtype = wire_dtype
        self.name = "allreduce" if wire_dtype is None else "allreduce16"

    def __call__(self, tree, state, *, axis: str, size: int):
        inv = 1.0 / size
        wd = self.wire_dtype
        if self.bucket_bytes > 0:
            # per-bucket async psum pairs: all starts issued before the
            # first done so the latency-hiding scheduler can overlap the
            # buckets with the backprop tail.  The wire cast (if any)
            # happens per bucket — same elementwise cast→psum→cast chain
            # as the monolithic leaf, so bit-identity holds either way.
            plan = buckets.plan_buckets(tree, self.bucket_bytes)
            vecs = buckets.pack(tree, plan)
            tickets = [psum_start(v if wd is None else v.astype(wd), axis)
                       for v in vecs]
            summed = [psum_done(t) for t in tickets]
            reduced = [(s if wd is None else s.astype(v.dtype)) * inv
                       for s, v in zip(summed, vecs)]
            return buckets.unpack(reduced, tree, plan), state
        if wd is None:
            out = jax.tree.map(lambda g: lax.psum(g, axis) * inv, tree)
        else:
            out = jax.tree.map(
                lambda g: lax.psum(g.astype(wd), axis).astype(g.dtype) * inv, tree
            )
        return out, state


class Ring(Strategy):
    """Explicit chunked ring: reduce-scatter then allgather over
    ``lax.ppermute``.

    Algorithmic parity with the reference's ``Exch_asa32/asa16`` ("alltoall
    sum allgather" over CUDA-aware MPI p2p) and ``Exch_copper(16)``: the
    parameter pytree is flattened to one contiguous fp32 vector (the
    reference walked a concatenated GPUArray buffer), split into ``size``
    chunks, and each of the ``2(size-1)`` hops moves one chunk to the right
    neighbor.  ``wire_dtype=bfloat16`` casts each hop's payload — the role of
    the reference's runtime-compiled fp32↔fp16 PyCUDA kernels — while the
    accumulator stays fp32.
    """

    def __init__(self, wire_dtype=None):
        self.wire_dtype = wire_dtype
        self.name = "ring" if wire_dtype is None else "ring16"
        self.flattens = True

    def n_buckets(self, params, bucket_bytes: int):
        # the ring IS a chunk pipeline already (2(size-1) ppermute hops
        # over size-th slices) — the bucket planner does not re-slice it
        return None

    def __call__(self, tree, state, *, axis: str, size: int):
        if size == 1:
            return tree, state
        flat = helper_funcs.flatten_tree(tree, pad_to_multiple_of=size)
        chunk = flat.shape[0] // size
        buf = flat.reshape(size, chunk)
        rank = lax.axis_index(axis)
        perm = [(i, (i + 1) % size) for i in range(size)]
        wd = self.wire_dtype

        def send(x):
            return lax.ppermute(x if wd is None else x.astype(wd), axis, perm)

        def recv_cast(x):
            return x if wd is None else x.astype(jnp.float32)

        # Reduce-scatter: after step s, the partial sum for chunk
        # (rank - s - 1) has accumulated s+2 contributions.
        def rs_body(s, carry):
            acc, cur = carry  # cur: the partial chunk we just received/own
            nxt = recv_cast(send(cur))
            idx = (rank - s - 1) % size
            mine = lax.dynamic_index_in_dim(acc, idx, 0, keepdims=False)
            summed = mine + nxt
            acc = lax.dynamic_update_index_in_dim(acc, summed, idx, 0)
            return acc, summed

        own_first = lax.dynamic_index_in_dim(buf, rank % size, 0, keepdims=False)
        acc, _ = lax.fori_loop(0, size - 1, rs_body, (buf, own_first))
        my_idx = (rank + 1) % size
        my_chunk = lax.dynamic_index_in_dim(acc, my_idx, 0, keepdims=False) / size
        if wd is not None:
            # Round the owned chunk to the wire dtype BEFORE the allgather so
            # every rank (owner included) holds the identical bit pattern —
            # replica divergence here would silently break BSP's invariant.
            my_chunk = my_chunk.astype(wd).astype(jnp.float32)

        # Allgather: at step s each rank forwards the chunk it received last.
        out = jnp.zeros_like(buf)
        out = lax.dynamic_update_index_in_dim(out, my_chunk, my_idx, 0)

        def ag_body(s, carry):
            out, cur = carry
            got = recv_cast(send(cur))
            idx = (rank - s) % size
            out = lax.dynamic_update_index_in_dim(out, got, idx, 0)
            return out, got

        out, _ = lax.fori_loop(0, size - 1, ag_body, (out, my_chunk))
        return helper_funcs.unflatten_like(tree, out.reshape(-1)), state


class OneBit(Strategy):
    """1-bit sign compression with error feedback (BASELINE.json config #5).

    Each worker quantizes its (gradient + carried error) vector to
    ``scale * sign``, keeps the quantization residual as next step's error
    feedback, and only sign *bits* plus one scalar scale cross the wire:
    signs are bit-packed 8-per-byte (Pallas kernel on TPU, jnp fallback
    elsewhere — ``ops/compress.py``), all-gathered, then decoded and averaged
    locally.  Wire cost per worker ≈ P/8 bytes vs 4P for fp32 — a 32×
    compression, the modern version of the reference's fp16 wire trick.
    """

    name = "onebit"
    stateful = True
    flattens = True

    def init_state(self, params):
        n = helper_funcs.tree_size(params)
        padded = n + (-n) % compress_ops.PACK_ALIGN
        return jnp.zeros((padded,), jnp.float32)

    def _segment_elems(self, bucket_bytes: int) -> int:
        """fp32 elements per wire bucket, rounded DOWN to the pack-kernel
        grid (PACK_ALIGN) so every bucket's packed buffer is whole tiles
        — the pack/decode pair is blockwise, which is exactly why
        bucketed ≡ monolithic bit-for-bit."""
        return max(compress_ops.PACK_ALIGN,
                   (int(bucket_bytes) // 4 // compress_ops.PACK_ALIGN)
                   * compress_ops.PACK_ALIGN)

    def n_buckets(self, params, bucket_bytes: int):
        n = helper_funcs.tree_size(params)
        n += (-n) % compress_ops.PACK_ALIGN
        seg = self._segment_elems(bucket_bytes)
        return max(1, -(-n // seg))

    def __call__(self, tree, state, *, axis: str, size: int):
        flat = helper_funcs.flatten_tree(
            tree, pad_to_multiple_of=compress_ops.PACK_ALIGN)
        n_true = helper_funcs.tree_size(tree)
        # fused encode: c = flat + state is formed in VMEM and emits the
        # packed sign tiles AND |c| in one pass — c itself never lands in
        # HBM (ops/compress.py pack_signs_encode; jnp oracle elsewhere)
        packed, absc = compress_ops.pack_signs_encode(flat, state)
        # scale over the TRUE length only: the PACK_ALIGN zero pad would
        # deflate mean(|c|) by up to pad/n
        scale = jnp.mean(absc[:n_true]) + 1e-12
        # new error state from |c| + sign bits + scale, bit-exact vs the
        # unfused c − scale·sign(c)
        new_state = compress_ops.signed_residual(absc, packed, scale)
        all_scales = lax.all_gather(scale, axis)       # [size] — one scalar
        if self.bucket_bytes > 0:
            # per-bucket wire: the vector is packed ONCE and each bucket
            # all-gathers its PACK_ALIGN-aligned slice of PACKED rows as
            # its own async pair (all starts before the first done),
            # decoding per bucket with the GLOBAL scale — the pack/decode
            # pair is blockwise, so bucketed ≡ monolithic bit-for-bit
            seg = self._segment_elems(self.bucket_bytes)
            rows_per = seg // (32 * compress_ops.LANES)  # packed rows/bucket
            p_rows = packed.shape[0]
            bounds = [(a, min(a + rows_per, p_rows))
                      for a in range(0, p_rows, rows_per)]
            tickets = [all_gather_start(packed[a:b], axis)
                       for a, b in bounds]
            segs = [compress_ops.unpack_signs_weighted_mean(
                all_gather_done(t), all_scales, size) for t in tickets]
            mean = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
        else:
            all_packed = lax.all_gather(packed, axis)  # P/8 bytes/worker
            mean = compress_ops.unpack_signs_weighted_mean(
                all_packed, all_scales, size)
        return helper_funcs.unflatten_like(tree, mean), new_state


class TopK(Strategy):
    """Chunk-local top-k sparsification with error feedback and a packed
    wire format (BASELINE.json config #5 alongside :class:`OneBit`).

    The gradient+error vector is viewed as ``[C, chunk_size]`` chunks and
    the ``k_c = ratio·chunk_size`` largest-magnitude entries of EACH chunk
    are selected — a vectorized row-wise ``lax.top_k`` instead of a global
    top-k sort of the whole 138M-element VGG-16 vector (the round-1 version,
    which both sorted the full vector and shipped fp32 values + int32
    global indices).  Chunk-local selection is the standard large-model
    variant (error feedback absorbs the difference from exact global top-k)
    and makes the wire format packable:

    * values cross as **bfloat16** (master accumulation stays fp32),
    * indices cross as **int16** chunk-local offsets (signed int16, so
      chunk_size ≤ 32768 — enforced in ``__init__``; the chunk id is
      implicit in position), global index = c·chunk + off.

    Wire bytes per worker ≈ 4·k total (vs 8·k before; vs P/8 for onebit —
    at the 1% default ratio that is 0.04·P vs 0.125·P, ~3× less than
    onebit and 100× less than fp32 allreduce).
    """

    name = "topk"
    stateful = True
    flattens = True

    CHUNK = 8192          # ≤ 2^16 for int16 offsets; multiple of the lane dim

    def __init__(self, ratio: float = 0.01, k: Optional[int] = None,
                 chunk: Optional[int] = None):
        self.ratio = ratio
        self.k = k                    # per-chunk override (mostly for tests)
        self.chunk = int(chunk or self.CHUNK)
        # signed int16 offsets: anything past 2^15−1 would wrap negative on
        # the wire and silently corrupt the scatter indices
        assert self.chunk <= 1 << 15, "int16 offsets need chunk ≤ 32768"

    def init_state(self, params):
        n = helper_funcs.tree_size(params)
        padded = n + (-n) % self.chunk
        return jnp.zeros((padded,), jnp.float32)

    def _k_c(self) -> int:
        """Selected entries per chunk row — ONE derivation for the
        exchange itself and the n_buckets bench column."""
        return self.k or max(1, int(round(self.chunk * self.ratio)))

    def _rows_per_bucket(self, k_c: int, bucket_bytes: int) -> int:
        """Chunk rows per wire bucket: a row ships ``k_c`` bf16 values +
        ``k_c`` int16 offsets = 4·k_c bytes.  Shared by the bucketed
        exchange and n_buckets so the bench column can't drift from the
        collectives actually issued."""
        return max(1, int(bucket_bytes) // (4 * k_c))

    def __call__(self, tree, state, *, axis: str, size: int):
        flat = helper_funcs.flatten_tree(tree, pad_to_multiple_of=self.chunk)
        c = flat + state
        n = c.shape[0]
        n_chunks = n // self.chunk
        k_c = self._k_c()
        c2 = c.reshape(n_chunks, self.chunk)

        # fused encode: top-k select, bf16 value cast, int16 offset emit
        # and the in-place bf16 rounding residual, one chunk-row pass
        # (ops/compress.py topk kernels; jnp oracle elsewhere).  The bf16
        # quantization residual of each shipped value feeds back into the
        # error buffer alongside the unselected mass, so the fp32 master
        # stream loses nothing to the wire rounding either.
        wire_vals, wire_idx, new_c2 = compress_ops.topk_encode(c2, k_c)
        new_state = new_c2.reshape(-1)
        if self.bucket_bytes > 0:
            # per-bucket wire: the (vals, idx) pairs of ~bucket_bytes
            # worth of CHUNK ROWS ride as their own async all-gather
            # pairs; each bucket decodes into its own disjoint dense
            # segment (chunk c only ever lands in [c·chunk, (c+1)·chunk)),
            # so the per-bucket decodes reproduce the monolithic decode
            # bit-for-bit
            rows_per = self._rows_per_bucket(k_c, self.bucket_bytes)
            bounds = [(a, min(a + rows_per, n_chunks))
                      for a in range(0, n_chunks, rows_per)]
            tickets = [(all_gather_start(wire_vals[a:b], axis),
                        all_gather_start(wire_idx[a:b], axis))
                       for a, b in bounds]
            segs = [compress_ops.topk_decode(all_gather_done(tv),
                                             all_gather_done(ti),
                                             self.chunk, size)
                    for tv, ti in tickets]
            mean = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
        else:
            all_vals = lax.all_gather(wire_vals, axis)  # [size, C, k_c]
            all_idx = lax.all_gather(wire_idx, axis)
            mean = compress_ops.topk_decode(all_vals, all_idx,
                                            self.chunk, size)
        return helper_funcs.unflatten_like(tree, mean), new_state

    def n_buckets(self, params, bucket_bytes: int):
        n = helper_funcs.tree_size(params)
        n += (-n) % self.chunk
        n_chunks = n // self.chunk
        rows_per = self._rows_per_bucket(self._k_c(), bucket_bytes)
        return max(1, -(-n_chunks // rows_per))


class PowerSGD(Strategy):
    """Rank-r low-rank gradient compression with error feedback (PowerSGD,
    Vogels et al. 2019, arXiv:1905.13727) — the modern production
    compressor alongside :class:`OneBit` / :class:`TopK`, and the one that
    maps best to the TPU: the encode/decode are small MATMULS (MXU work,
    not elementwise bit-twiddling) and the wire shrinks from rows·cols to
    r·(rows+cols) per matrix.

    Per matrix-shaped leaf M (conv kernels reshape to [k·k·ci, co]), with
    per-worker error feedback e and a warm-started shared Q:

        M' = M + e                       # local, fp32 master stream
        P  = mean_w(M' Q)      (psum)    # [rows, r] on the wire
        P̂  = qr(P).Q                     # orthonormal basis, same everywhere
        Q' = mean_w(M'ᵀ P̂)     (psum)    # [cols, r] on the wire
        M̂  = P̂ Q'ᵀ                       # decoded rank-r mean
        e' = M' − M̂                      # local residual feeds back

    Every worker decodes the SAME M̂ (both collectives precede the decode),
    so BSP replicas stay bit-identical; error feedback keeps the lost mass
    in the fp32 master stream.  When r ≥ rank(mean(M')), P̂ spans its
    column space and the decode is EXACT — pinned against the psum oracle
    in ``tests/test_powersgd.py``.  Vectors/norm scales and matrices too
    small to win (min dim ≤ 4r) reduce exactly — their wire share is
    negligible.

    State is PER LEAF ([Q, e] list aligned with the gradient leaves), not
    a flat vector.  Under model parallelism (tp/pp) each model/pipe rank
    compresses ITS local grad shard independently — the same shard-wise
    composition the flat strategies use — with the per-leaf state carried
    in a leading ``[prod(group)]`` axis sharded over the group axes
    (``BSP_Exchanger.extra_state_template`` builds it from the LOCAL
    shard template and ``extra_specs`` declares ``P(group)``; the
    exchanger unwraps the leading axis around the call).  Select via
    ``exch_strategy='powersgd'`` (rank 2) or ``'powersgd<r>'``.
    """

    stateful = True
    flattens = False
    leafwise_state = True      # extra_state_template gates model-parallel

    def __init__(self, rank: int = 2):
        self.rank = int(rank)
        assert self.rank >= 1
        self.name = f"powersgd{self.rank}"

    def _compressible(self, shape) -> bool:
        if len(shape) < 2:
            return False
        rows = int(np.prod(shape[:-1]))
        return min(rows, int(shape[-1])) > 4 * self.rank

    def init_state(self, params):
        state = []
        for i, l in enumerate(jax.tree.leaves(params)):
            shape = np.shape(l)
            if self._compressible(shape):
                rows, cols = int(np.prod(shape[:-1])), int(shape[-1])
                # deterministic per-leaf init — identical on every worker,
                # so the shared-Q invariant holds from step one
                q = jax.random.normal(jax.random.key(1905 + i),
                                      (cols, self.rank), jnp.float32)
                state.append({"q": q,
                              "e": jnp.zeros((rows, cols), jnp.float32)})
            else:
                state.append({"q": jnp.zeros((0, self.rank), jnp.float32),
                              "e": jnp.zeros((0, 0), jnp.float32)})
        return state

    def n_buckets(self, params, bucket_bytes: int):
        # the compressible leaves' P/Q factor psums are per-leaf small
        # collectives already (their own pipeline); the planner buckets
        # the DENSE remainder (vectors, norms, tiny matrices)
        dense = [l for l in jax.tree.leaves(params)
                 if not self._compressible(np.shape(l))]
        return buckets.count_buckets(dense, bucket_bytes) if dense else 0

    def __call__(self, tree, state, *, axis: str, size: int):
        from ..ops import factor_pack
        from .steps import _vary
        inv = 1.0 / size
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        assert len(leaves) == len(state), (len(leaves), len(state))
        out = [None] * len(leaves)
        new_state = list(state)
        comp = [i for i, g in enumerate(leaves)
                if self._compressible(np.shape(g))]

        # -- compressible leaves: stacked low-rank factor exchange --------
        # Every factor matmul lands directly in its zero-padded slice of
        # ONE staging buffer (ops/factor_pack.matmul_pack fuses the matmul
        # with the staging pack), so all P factors ride a single psum —
        # and likewise all Q factors — instead of one collective per leaf.
        # Zero pad rows psum to zero, so each slice equals the per-leaf
        # psum it replaces bit-for-bit.
        Mps = {i: leaves[i].reshape(-1, leaves[i].shape[-1])
               .astype(jnp.float32) + state[i]["e"] for i in comp}

        def _stacked_psum(tiles):
            buf = tiles[0] if len(tiles) == 1 else jnp.concatenate(tiles, 0)
            return lax.psum(buf, axis) * inv

        if comp:
            p_tiles = [factor_pack.matmul_pack(Mps[i], state[i]["q"])
                       for i in comp]
            P_all = _stacked_psum(p_tiles)
            Phs, off = {}, 0
            for i, t in zip(comp, p_tiles):
                rows = Mps[i].shape[0]
                Phs[i], _ = jnp.linalg.qr(P_all[off:off + rows])
                off += t.shape[0]
            q_tiles = [factor_pack.matmul_pack(Mps[i].T, Phs[i])
                       for i in comp]
            Q_all = _stacked_psum(q_tiles)
            off = 0
            for i, t in zip(comp, q_tiles):
                g = leaves[i]
                cols = Mps[i].shape[1]
                Qn = Q_all[off:off + cols]
                off += t.shape[0]
                Mhat = Phs[i] @ Qn.T
                out[i] = Mhat.reshape(g.shape).astype(g.dtype)
                # Qn is a psum result (worker-INVARIANT in the vma type
                # system), but it persists in the boxed per-worker state
                # whose scan carry under steps_per_call is worker-varying —
                # re-mark it (values are identical everywhere; a type cast)
                new_state[i] = {"q": _vary(Qn, axis), "e": Mps[i] - Mhat}

        # -- dense remainder ----------------------------------------------
        dense_ids = [i for i in range(len(leaves)) if out[i] is None]
        if self.bucket_bytes > 0 and dense_ids:
            # the dense remainder rides the bucket planner: one async
            # psum pair per ~bucket_bytes of incompressible leaves
            # (element-wise sum — bit-identical to the leaf-wise psums)
            summed = buckets.bucketed_psum([leaves[i] for i in dense_ids],
                                           axis, self.bucket_bytes)
            for i, s in zip(dense_ids, summed):
                out[i] = s * inv
        else:
            for i in dense_ids:
                out[i] = lax.psum(leaves[i], axis) * inv
        return jax.tree_util.tree_unflatten(treedef, out), new_state


def get_strategy(name: str, **kwargs) -> Strategy:
    """Resolve a strategy by its reference-compatible config string."""
    name = name.lower()
    table = {
        "none": lambda: NoComm(),
        "nocomm": lambda: NoComm(),
        "allreduce": lambda: AllReduce(),
        "ar": lambda: AllReduce(),
        "nccl32": lambda: AllReduce(),
        "nccl16": lambda: AllReduce(wire_dtype=jnp.bfloat16),
        "asa32": lambda: Ring(),
        "ring": lambda: Ring(),
        "copper": lambda: Ring(),
        "asa16": lambda: Ring(wire_dtype=jnp.bfloat16),
        "ring16": lambda: Ring(wire_dtype=jnp.bfloat16),
        "copper16": lambda: Ring(wire_dtype=jnp.bfloat16),
        "bf16": lambda: AllReduce(wire_dtype=jnp.bfloat16),
        "onebit": lambda: OneBit(),
        "compressed": lambda: OneBit(),
        "topk": lambda: TopK(**kwargs),
        "powersgd": lambda: PowerSGD(**kwargs),
    }
    if name.startswith("powersgd") and name[8:].isdigit():
        # 'powersgd4' etc.; an explicit rank kwarg must not silently lose
        assert "rank" not in kwargs or int(kwargs["rank"]) == int(name[8:]), \
            f"strategy name {name!r} conflicts with rank={kwargs['rank']}"
        return PowerSGD(rank=int(name[8:]))
    try:
        return table[name]()
    except KeyError:
        raise ValueError(f"unknown exchange strategy {name!r}; "
                         f"have {sorted(table)}")
