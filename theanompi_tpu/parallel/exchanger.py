"""Parameter/gradient exchangers — the four parallelism rules.

TPU-native rebuild of Theano-MPI's ``theanompi/lib/exchanger.py``
(SURVEY.md §2.2): the reference implements pure data parallelism in four
flavors that differ in *when* and *with whom* parameters are mixed —

* **BSP**: every iteration, all workers average gradients/parameters
  (allreduce, barrier semantics) → here ``lax.psum``-family strategies fused
  into the compiled step, or post-step parameter averaging.
* **EASGD**: a center parameter store; every ``sync_freq`` iterations each
  worker does an elastic pairwise update with it (Zhang et al. 2015).
* **ASGD**: downpour-style push of accumulated deltas / pull of fresh params.
* **GoSGD**: decentralized gossip — with probability ``p`` send
  ``(params, α/2)`` to a random peer and merge by weighted averaging
  (Blot et al. 2016).

**Asynchrony on SPMD hardware (the semantic delta, SURVEY.md §7):** TPU chips
in one program execute in lockstep, so "server serves one worker at a time"
and "message arrives whenever" have no direct analogue.  Each async rule maps
to its *synchronous-cadence* variant with the update algebra kept exact:

* EASGD → the synchronous elastic averaging step from the EASGD paper's own
  momentum variant: all workers exchange with the (replicated) center every
  ``sync_freq`` steps.  A real parameter-server process becomes a replicated
  center pytree — no server rank burns a chip.
* ASGD → workers train locally ``sync_freq`` steps, then the center absorbs
  the *sum* of worker deltas (downpour applies every worker's contribution)
  and workers restart from the new center.
* GoSGD → per-step Bernoulli send gating is kept per-worker; the random peer
  choice becomes a shared random ring-shift (every sender shifts by the same
  random ``s`` that step, delivered via ``lax.ppermute``), preserving the
  weighted-average merge and the Σα invariant exactly.

Exchange cost rides ICI inside compiled programs in all cases.

**Fused cadence (round 6):** each rule's exchange algebra is one pure
per-worker ``exchange_body(state, key, count)`` backing two dispatch
shapes — the standalone jitted collective the worker loop calls between
dispatches (``steps_per_call=1``), and, for ``steps_per_call > 1``, an
in-scan ``lax.cond(count % exchange_freq == 0, exchange_body, identity)``
inside the multi-step train dispatch (``steps.build_train_step``), so one
XLA dispatch covers k full steps INCLUDING their cadenced exchanges.
See docs/design.md §8 for the GoSGD traced-RNG contract.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import buckets, steps, topology, update_sharding
from ..jax_compat import shard_map
from ..utils import devprof, telemetry, tracing
from .mesh import WORKER_AXIS
from .strategies import Strategy, get_strategy


def _spec_axes(s):
    """Mesh axes named anywhere in one PartitionSpec (tuple entries too)."""
    out = set()
    for e in (s or ()):
        if isinstance(e, (tuple, list)):
            out.update(e)
        elif e is not None:
            out.add(e)
    return out


class Exchanger:
    """Base exchanger.

    Lifecycle (mirrors the reference: ``Exchanger(config, model)`` then
    ``.prepare(...)`` then per-iteration ``.exchange(recorder)``):

    * :meth:`prepare` — given the mesh and model, build state templates and
      jit the exchange collective.
    * :meth:`step_update` — traced INSIDE the per-worker train step: apply
      grads locally, optionally reducing them first (BSP fused mode).
    * :meth:`exchange_body` — the rule's exchange algebra as a PURE traced
      per-worker function, reused by both the standalone collective and the
      in-scan fused cadence (``steps_per_call > 1``).
    * :meth:`exchange` — Python-level cadence hook called by the worker loop
      after each ``train_iter``; runs the rule's collective when due.  A
      no-op when the cadence is fused into the multi-step dispatch
      (``self.fused``, set by ``model_base.compile_iter_fns``).
    """

    name = "exchanger"

    def identical_parts(self):
        """State parts bit-identical across workers (checkpoint dedup is
        PER PART — e.g. ZeRO-1 shards only the optimizer state, so params
        still dedup to one replica on disk; FSDP chunks neither): BSP grads
        mode with a stateless strategy; never async rules or per-worker EF
        state."""
        return ()

    def _group_axes(self):
        """Non-worker mesh axes (model/pipe) — under model parallelism each
        device along these axes holds a DIFFERENT local shard."""
        return tuple(a for a in self.mesh.axis_names if a != WORKER_AXIS)

    def __init__(self, config: Optional[dict] = None):
        self.config = dict(config or {})
        self.exchange_freq = 1
        self.mesh: Optional[Mesh] = None
        self.model = None
        self._exchange_fn = None
        # leaf-wise update-plane sharding (parallel/update_sharding.py,
        # config update_sharding=true): the active plan over this rule's
        # shardable extra keys, built in prepare() once model+mesh exist
        self._ushard_plan = None
        self._ushard_keys: tuple = ()
        # bucketed overlap-scheduled wire (parallel/buckets.py): split the
        # exchange payload into ~bucket_bytes collectives issued as async
        # start/done pairs so XLA's latency-hiding scheduler can overlap
        # them with the backprop tail.  0 (default) = the monolithic wire
        # — bucketed ≡ monolithic bit-for-bit at fixed membership
        # (tests/test_buckets.py), so this is purely a schedule knob.
        self.bucket_bytes = int(self.config.get("bucket_bytes", 0) or 0)
        # True when compile_iter_fns fused this rule's cadence into the
        # scanned multi-step train dispatch (steps_per_call > 1): the
        # Python exchange() hook then must not run the collective again.
        self.fused = False
        # elastic membership (parallel/membership.py): None = every rank
        # participates; a tuple of rank ids = the ACTIVE set after a
        # straggler demotion / host loss — demoted ranks train locally,
        # issue the same collectives (SPMD lockstep demands it) but
        # contribute nothing and keep their replica bit-unchanged.
        self._active_ranks: Optional[tuple] = None

    # -- wiring ------------------------------------------------------------

    def prepare(self, mesh: Mesh, model) -> None:
        self.mesh = mesh
        self.model = model
        self.size = mesh.shape[WORKER_AXIS]
        self._build_update_plan()

    def has_exchange(self) -> bool:
        """True when the rule runs a post-step exchange collective (the
        async rules always; BSP only in params mode).  False means the
        whole rule already lives inside the train step (BSP grads mode)
        and there is no cadence to fuse or hook."""
        return False

    # -- elastic membership (parallel/membership.py) ------------------------

    def supports_elastic(self) -> bool:
        """True when this rule's exchange algebra tolerates membership
        change (the async rules: per-worker push-pull/gossip).  False
        (BSP) means the reaction to a lost/straggling worker is a
        supervised world restart at the committed window cursor — there is
        no barrier-free way to shrink an allreduce's contract."""
        return False

    def set_active_ranks(self, active) -> None:
        """Shrink/re-grow the participating worker set WITHOUT stopping
        the run: regenerate the rule's peer topology (GoSGD routing
        tables, EASGD/ASGD collective masks) over ``active`` and rebuild
        the standalone collective.  ``active=None`` (or the full range)
        restores full membership.  Demoted ranks keep training locally
        with their replicas bit-unchanged by exchanges, so a readmitted
        worker re-enters the mixing with whatever it has — the elastic
        algebra pulls it back to consensus.  When the cadence is fused
        into the multi-step dispatch the caller must also recompile the
        model (``MeshReactor`` does)."""
        if not self.supports_elastic():
            raise NotImplementedError(
                f"{type(self).__name__} ({self.name}) cannot shrink its "
                f"membership — BSP-family rules react to host loss via "
                f"`launcher --supervise` world restart (docs/design.md "
                f"§14 reaction matrix)")
        assert self.mesh is not None, \
            "set_active_ranks before prepare()"
        n = self.mesh.shape[WORKER_AXIS]
        if active is None:
            self._active_ranks = None
        else:
            act = tuple(sorted({int(a) for a in active}))
            assert act and all(0 <= a < n for a in act), (
                f"active ranks {act} outside the {n}-worker mesh (or "
                f"empty) — at least one worker must remain active")
            self._active_ranks = None if len(act) == n else act
        # regenerated prepare(): routing tables / masks / the jitted
        # standalone collective are all rebuilt for the new active set
        self.prepare(self.mesh, self.model)

    def active_mask(self) -> np.ndarray:
        """``[size]`` float32 participation mask (1 = active)."""
        mask = np.ones(self.size, np.float32)
        if self._active_ranks is not None:
            mask[:] = 0.0
            mask[list(self._active_ranks)] = 1.0
        return mask

    # -- bucketed wire (parallel/buckets.py) --------------------------------

    def _psum_tree(self, tree, axis):
        """The rule's cross-worker sum of a params-shaped payload: the
        leaf-wise monolithic ``lax.psum`` at ``bucket_bytes=0``, else
        per-bucket async start/done pairs.  Membership masking composes
        per bucket for free — masks scale VALUES upstream of the pack,
        and the plan is a pure function of shapes, so a demoted rank's
        zeroed contribution rides the identical bucket schedule."""
        return buckets.bucketed_psum(tree, axis, self.bucket_bytes)

    def n_buckets(self) -> Optional[int]:
        """Collectives one exchange issues under the current
        ``bucket_bytes`` (bench's ``n_buckets`` row column; None when the
        wire is monolithic or the rule has no exchange payload).  The
        default models the params-shaped payload the psum/gossip rules
        ship; compressed strategies override via their packed layouts."""
        if self.bucket_bytes <= 0 or self.model is None:
            return None
        return buckets.count_buckets(self.model.params, self.bucket_bytes)

    def exchange_body(self, state, key, count):
        """The rule's exchange algebra as a PURE per-worker function:
        ``(boxed state dict, key, count) -> boxed state dict``, traced
        inside ``shard_map`` over the worker axis (state leaves are the
        local ``[1, ...]`` shards).  ONE definition serves both dispatch
        shapes: the standalone jitted ``_exchange_fn`` (steps_per_call=1
        and the session API) and the in-scan fused cadence that
        ``steps.build_train_step`` wraps in ``lax.cond`` for
        ``steps_per_call > 1``."""
        raise NotImplementedError(
            f"{type(self).__name__}.has_exchange() is True but no "
            "exchange_body is defined")

    def _build_exchange_fn(self) -> None:
        """Jit :meth:`exchange_body` as the standalone whole-state
        collective (kept even when the cadence is fused — checkpoint
        tooling and the session API still call it for spc=1 runs)."""
        if not self.has_exchange():
            return
        state_spec = steps.state_partition_specs(self.model, self,
                                                 WORKER_AXIS)
        sm = shard_map(self.exchange_body, mesh=self.mesh,
                       in_specs=(state_spec, P(), P()),
                       out_specs=state_spec)
        self._exchange_fn = jax.jit(sm, donate_argnums=(0,))

    # -- leaf-wise update-plane sharding (docs/design.md §23) ---------------

    def shardable_extra(self) -> tuple:
        """Extra-state keys whose leaves are bit-identical replicas across
        workers — the only state update-plane sharding may chunk (EASGD/
        ASGD center copies).  Per-worker DIVERGENT state must stay off this
        list: error-feedback buffers and gossip α differ per worker by
        construction — each chip already holds only its own copy, so there
        is no redundancy to shard away (the schema still classifies them:
        their plan entry is 'local', i.e. absent)."""
        return ()

    def _build_update_plan(self) -> None:
        """Stamp the leaf-wise plan over the shardable extra keys (config
        ``update_sharding=true``).  Inactive (plan None) when the rule has
        nothing shardable, the mesh has one worker, or no leaf clears the
        ``ushard_min_bytes`` threshold — active sharding under model
        parallelism is not supported and fails loudly."""
        self._ushard_plan, self._ushard_keys = None, ()
        if not self.config.get("update_sharding", False):
            return
        keys = tuple(sorted(self.shardable_extra()))
        if not keys or self.size <= 1:
            return
        assert self.model.param_specs() is None and all(
            self.mesh.shape[a] == 1 for a in self.mesh.axis_names
            if a != WORKER_AXIS), (
            "update_sharding currently supports pure data-parallel "
            "layouts (param_specs() is None, no model/pipe/seq mesh axes)")
        full = self._extra_full_template()
        keys = tuple(k for k in keys if k in full)
        if not keys:
            return
        plan = update_sharding.plan_tree(
            {k: full[k] for k in keys}, self.size,
            min_bytes=int(self.config.get(
                "ushard_min_bytes", update_sharding.DEFAULT_MIN_BYTES)))
        if plan.any_sharded:
            self._ushard_plan, self._ushard_keys = plan, keys

    def update_plan(self):
        """The active :class:`update_sharding.UpdatePlan` over this rule's
        shardable extra keys, or None when sharding is off/inactive."""
        return self._ushard_plan

    def unshard_extra(self, extra, axis: str = WORKER_AXIS):
        """Traced rebuild of the plan-sharded extra keys' FULL values from
        the local chunks (one fused allgather); identity when sharding is
        off.  Exchange bodies call this, do their unchanged full-tensor
        algebra, then :meth:`reshard_extra` the results — so the math (and
        its psum reduction order) is bit-identical to the replicated
        path."""
        plan = self.update_plan()
        if plan is None:
            return extra
        full = update_sharding.unshard_tree(
            {k: extra[k] for k in self._ushard_keys}, plan, axis)
        return dict(extra, **full)

    def reshard_extra(self, full_sub, axis: str = WORKER_AXIS):
        """Slice this worker's chunks back out of updated full values —
        the store-side half of the :meth:`unshard_extra` round trip;
        identity when sharding is off."""
        plan = self.update_plan()
        if plan is None:
            return full_sub
        rank = lax.axis_index(axis)
        return update_sharding.shard_tree(
            {k: full_sub[k] for k in self._ushard_keys}, plan, rank)

    def extra_host_boxed(self, n: int):
        """Boxed ``[n, ...]`` host INIT VALUES for the extra part while the
        plan is active (``model_base`` places them via
        ``steps.place_boxed``): plan keys are genuinely PARTITIONED rows —
        each worker's chunk differs, which ``steps.replicate_tree``'s
        one-template broadcast cannot express — and the rest replicate."""
        plan = self.update_plan()
        assert plan is not None, "extra_host_boxed needs an active plan"
        full = self._extra_full_template()
        out = update_sharding.shard_host_boxed(
            {k: full[k] for k in self._ushard_keys}, plan)
        for k, v in full.items():
            if k not in self._ushard_keys:
                out[k] = jax.tree.map(
                    lambda x: np.broadcast_to(
                        np.asarray(x)[None], (n,) + np.shape(x)).copy(), v)
        return out

    def _extra_full_template(self) -> Dict[str, Any]:
        """Unboxed per-worker persistent state (error feedback, center,
        α...), FULL shapes — rules override THIS, not
        :meth:`extra_state_template`."""
        return {}

    def extra_state_template(self) -> Dict[str, Any]:
        """The extra-state shapes the step machinery carries: the full
        template, with the plan-sharded keys' leaves chunked to the
        per-worker ``[chunk]`` windows when update-plane sharding is
        active — every venue (live compile, ``_state_avals`` prewarm)
        derives byte-identical programs from the same shapes."""
        full = self._extra_full_template()
        plan = self.update_plan()
        if plan is None:
            return full
        sub = update_sharding.chunk_template(
            {k: full[k] for k in self._ushard_keys}, plan)
        return dict(full, **sub)

    def extra_specs(self, param_specs):
        """Per-leaf PartitionSpecs for :meth:`extra_state_template` when the
        model is tensor-parallel (``model.param_specs() is not None``).  Must
        mirror the template's structure.  Rules whose extra state is a copy
        of the params (EASGD/ASGD centers) return ``param_specs`` shapes."""
        if self.extra_state_template():
            raise NotImplementedError(
                f"{type(self).__name__} does not declare tensor-parallel "
                "specs for its extra state")
        return {}

    # -- in-step (traced) --------------------------------------------------

    def _clip_grads(self, grads):
        """Global-L2-norm gradient clipping (config ``grad_clip``, off by
        default — the reference predates it; modern LM training expects it).
        Applied to the gradients the optimizer actually consumes: the
        REDUCED gradient under BSP, the local gradient under async rules.

        Under model parallelism the TRUE global norm needs each sharded
        leaf's squared sum ``psum``'d over the axes it is sharded on (and
        replicated leaves counted once) — every rank then clips by the same
        scale, keeping cross-rank replication intact."""
        clip = float(self.config.get("grad_clip", 0.0) or 0.0)
        if clip <= 0.0:
            return grads
        pspecs = self.model.param_specs()
        group = self._group_axes()

        def leaf_sq(g, spec=None):
            v = jnp.sum(jnp.square(g.astype(jnp.float32)))
            if spec is not None:
                axes = tuple(a for a in _spec_axes(spec) if a in group)
                if axes:
                    v = lax.psum(v, axes)
            return v

        if pspecs is None or not group:
            sq = sum(leaf_sq(g) for g in jax.tree.leaves(grads))
        else:
            sq = sum(jax.tree.leaves(
                jax.tree.map(leaf_sq, grads, pspecs)))
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
        return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)

    def step_update(self, params, opt_state, grads, extra, lr, *, axis, size,
                    count):
        """Default: purely local optimizer step (async rules train locally
        between exchanges)."""
        opt = self.model.opt
        params, opt_state = opt.update(self._clip_grads(grads), opt_state,
                                       params, lr)
        return params, opt_state, extra

    def sync_bn(self, bn_state, *, axis, size):
        """How BatchNorm running stats relate across workers.  Async rules
        keep them local (they are part of the divergent replica); BSP
        averages them so replicas stay bit-identical."""
        return bn_state

    def numerics_extra(self, params, extra, axis):
        """Rule-specific inputs for the numerics health plane
        (utils/numerics, docs/design.md §25) — traced inside the step,
        pure reads.  Keys, all optional:

        * ``beacon_tree`` — a tree this rule keeps BIT-IDENTICAL across
          workers (BSP grads-mode params, the EASGD/ASGD center copy):
          the consistency beacon digests it, and any cross-rank digest
          mismatch means replica desync.  Absent when replicas genuinely
          diverge (gossip, local training) — healthy divergence must not
          masquerade as corruption.
        * ``center`` — the center-parameter tree, for the exact
          ``‖w_i − c‖`` distance of the source paper.
        * ``ef_state`` — the strategy's error-feedback/residual state,
          for the EF-saturation norm.

        The base rule trains locally between exchanges: nothing is
        replicated, nothing is a center — no fields."""
        return {}

    # -- exchange collective (Python cadence + jitted body) ----------------

    def due(self, count: int) -> bool:
        return self._exchange_fn is not None and count % self.exchange_freq == 0

    def exchange(self, recorder=None, count: int = 0) -> None:
        if self.fused or not self.due(count):
            # fused: the cadence already ran inside the multi-step dispatch
            return
        tm = telemetry.active()
        # causal tracing (§17): the sync rules' exchange is in-mesh (no
        # wire), so its round span has no server join — but it lands in
        # the same per-rank span stream, so the critical-path table can
        # name 'compute vs exchange dispatch' for SPMD runs too
        tr = tracing.active()
        sp = tr.begin("exchange", count=count,
                      rule=self.name) if tr.enabled else None
        if recorder:
            recorder.start()
        t0 = time.time() if tm.enabled else 0.0
        # devprof dispatch anchor: a profiler capture sees one named span
        # per standalone exchange dispatch, so trace attribution can count
        # exchanges without guessing from collective-op repetitions (a
        # TraceMe no-op while no capture is active)
        with jax.profiler.TraceAnnotation(devprof.EXCHANGE_SPAN):
            self.model.step_state = self._exchange_fn(
                self.model.step_state, self.model.next_exchange_key(), count)
        if tm.enabled:
            # PER-EXCHANGE histograms, not bare sums: host dispatch cost
            # here; the device-side comm time lands via recorder.end('comm')
            # → phase.comm below (full distribution, p95/p99 in the report)
            tm.observe("exchange.dispatch_secs", time.time() - t0)
            tm.counter("exchange.count")
            tm.counter(f"exchange.count.{self.name}")
        if recorder:
            # blocking only when a recorder asks for honest comm buckets —
            # bench's recorder-less loop stays fully asynchronous
            jax.block_until_ready(self.model.step_state["params"])
            recorder.end("comm")
        if sp is not None:
            sp.end()


class BSP_Exchanger(Exchanger):
    """Bulk-synchronous exchange (reference: ``BSP_Exchanger``).

    ``mode='grads'`` (default): the selected strategy reduces gradients
    inside the compiled step — comm fuses with compute, and N-worker training
    is bit-equivalent to 1-worker training on the concatenated batch (the
    defining BSP invariant, tested in ``tests/test_bsp_equivalence.py``).

    ``mode='params'``: reference-exact cadence — local update then post-step
    parameter averaging as a separate compiled collective, timed into the
    recorder's ``t_comm`` bucket like the reference's exchange.
    """

    name = "bsp"

    def __init__(self, config: Optional[dict] = None):
        super().__init__(config)
        self.mode = self.config.get("exch_mode", "grads")
        self.strategy: Strategy = get_strategy(
            self.config.get("exch_strategy", "allreduce"))
        # bucketed wire: the strategy owns BSP's collectives (in-step
        # grads mode and the params-mode exchange_body alike), so the
        # knob is forwarded there — each strategy buckets its OWN wire
        # format (fp32 leaves, packed signs, topk rows...)
        self.strategy.bucket_bytes = self.bucket_bytes

    def n_buckets(self):
        if self.bucket_bytes <= 0 or self.model is None:
            return None
        return self.strategy.n_buckets(self.model.params, self.bucket_bytes)

    def identical_parts(self):
        # grads mode: every worker applies the same reduced gradient; params
        # mode keeps per-worker momentum; stateful strategies carry
        # per-worker error feedback; the measurement-only 'none' strategy
        # skips the collective entirely; ZeRO-1/FSDP deliberately shard
        # their parts per worker — all of those break replica identity
        # (for checkpoint dedup purposes).
        if not (self.mode == "grads" and not self.strategy.stateful
                and self.strategy.name != "none"):
            return ()
        parts = {"params", "opt_state", "bn_state", "extra"}
        if self.config.get("zero_opt", False) or \
                self.config.get("update_sharding", False):
            parts.discard("opt_state")    # the chunk partition differs/worker
        if self.config.get("fsdp", False):
            parts.discard("params")       # FSDP chunks are the partition:
            parts.discard("opt_state")    # genuinely per-worker state
        return tuple(sorted(parts))

    def extra_specs(self, param_specs):
        if self.strategy.stateful:
            # the error-feedback state is per-device within a worker
            # group: each model/pipe rank compresses ITS local grad shard
            # independently.  Flat strategies: one [prod(group)·local_flat]
            # vector sharded over the group axes.  Leaf-wise strategies
            # (powersgd): every per-leaf array carries a leading
            # [prod(group)] axis, sharded the same way — structure must
            # mirror extra_state_template, derived WITHOUT materializing
            # the (param-sized) EF buffers via eval_shape.
            group = self._group_axes()
            if getattr(self.strategy, "leafwise_state", False) and group:
                st_shapes = jax.eval_shape(
                    lambda p: self.strategy.init_state(
                        steps.local_param_template(p, param_specs,
                                                   self.mesh)),
                    self.model.params)
                return {"strat": jax.tree.map(lambda _: P(group),
                                              st_shapes)}
            return {"strat": P(group) if group else P()}
        return {}

    def has_exchange(self) -> bool:
        return self.mode == "params"

    def exchange_body(self, state, key, count):
        # reference-exact cadence: local update happened in step_update;
        # here the strategy averages the PARAMETERS across workers
        params = steps.unbox(state["params"])
        extra = steps.unbox(state["extra"])
        strat_state = extra.get("strat", ())
        params, strat_state = self._strat_call(
            params, strat_state, axis=WORKER_AXIS, size=self.size)
        if "strat" in extra:
            extra = dict(extra, strat=strat_state)
        return dict(state, params=steps.box(params),
                    extra=steps.box(extra))

    def prepare(self, mesh: Mesh, model) -> None:
        super().prepare(mesh, model)
        self._build_exchange_fn()

    def _extra_full_template(self) -> Dict[str, Any]:
        # error-feedback state is per-worker DIVERGENT (each worker
        # compresses its own residual), so none of it is shardable_extra —
        # under update_sharding only the optimizer moments chunk (the
        # model wraps its opt; see model_base.__init__)
        if self.strategy.stateful:
            pspecs = self.model.param_specs()
            group = self._group_axes()
            if pspecs is None or not group:
                return {"strat": self.strategy.init_state(self.model.params)}
            # model-parallel layout: EF state sized from the LOCAL shard a
            # device sees inside shard_map, tiled to a global layout that
            # extra_specs shards back over the group axes
            local = steps.local_param_template(self.model.params, pspecs,
                                               self.mesh)
            st = self.strategy.init_state(local)
            n = int(np.prod([self.mesh.shape[a] for a in group]))
            if getattr(self.strategy, "leafwise_state", False):
                # per-leaf state (powersgd Q/e): every array gets a leading
                # [prod(group)] axis — rank i's block is its own local
                # state (init identical on every rank; step_update unwraps
                # the leading axis around the strategy call).  The flat
                # strategies instead concatenate on the flat axis below.
                return {"strat": jax.tree.map(
                    lambda x: jnp.tile(x[None], (n,) + (1,) * x.ndim), st)}
            return {"strat": jnp.tile(st, n)}
        return {}

    def _strat_call(self, tree, strat_state, *, axis, size):
        """Invoke the exchange strategy, normalizing the model-parallel
        leaf-wise state layout: under tp/pp a leaf-wise strategy's arrays
        carry a leading ``[prod(group)]`` axis (see extra_state_template)
        whose local shard_map view is ``[1, ...]`` — strip it for the
        strategy, restore it for the boxed carry.  Flat strategies and
        pure data-parallel layouts pass through untouched."""
        lw = (getattr(self.strategy, "leafwise_state", False)
              and self._group_axes() and strat_state != ()
              # the leading axis exists only for the sharded-param layout
              # (extra_state_template's pspecs branch) — sequence-parallel
              # models with replicated params keep the plain per-leaf
              # state (grads are seq-psum'd identical across seq ranks)
              and self.model.param_specs() is not None)
        if lw:
            strat_state = jax.tree.map(lambda x: x[0], strat_state)
        tree, strat_state = self.strategy(tree, strat_state,
                                          axis=axis, size=size)
        if lw:
            strat_state = jax.tree.map(lambda x: x[None], strat_state)
        return tree, strat_state

    def step_update(self, params, opt_state, grads, extra, lr, *, axis, size,
                    count):
        if self.mode == "grads":
            strat_state = extra.get("strat", ())
            grads, strat_state = self._strat_call(grads, strat_state,
                                                  axis=axis, size=size)
            if "strat" in extra:
                extra = dict(extra, strat=strat_state)
            grads = self._restore_replication(grads)
        opt = self.model.opt
        params, opt_state = opt.update(self._clip_grads(grads), opt_state,
                                       params, lr)
        return params, opt_state, extra

    def _restore_replication(self, grads):
        """Flattening strategies under model parallelism: chunk-level
        compression (topk) can select DIFFERENT entries of a replicated
        leaf's segment on different model/pipe ranks, and even value
        -identical decodes lose the vma invariance the out-specs need —
        pmean each leaf over the group axes its spec does NOT shard
        (tiny: LayerNorms, biases, stage-replicated embeddings)."""
        pspecs = self.model.param_specs()
        group = self._group_axes()
        per_shard = (self.strategy.flattens
                     or getattr(self.strategy, "leafwise_state", False))
        if pspecs is None or not group or not per_shard:
            return grads

        def fix(g, s):
            missing = tuple(a for a in group if a not in _spec_axes(s))
            return lax.pmean(g, missing) if missing else g

        return jax.tree.map(fix, grads, pspecs)

    def sync_bn(self, bn_state, *, axis, size):
        # Keep BSP replicas bit-identical: running stats are averaged every
        # step (cheap — BN state is tiny next to params).
        return jax.tree.map(lambda x: lax.pmean(x, axis), bn_state)

    def numerics_extra(self, params, extra, axis):
        out = {}
        if self.mode == "grads" and self.strategy.name != "none":
            # every worker applied the same reduced gradient (stateful
            # strategies included — the decoded psum result is uniform
            # even though the EF buffers differ), so post-update params
            # are bit-identical: the beacon digests them.  Params mode
            # samples PRE-exchange (replicas legitimately apart between
            # cadenced averages) and the 'none' strategy never reduces —
            # no beacon there.
            out["beacon_tree"] = params
        if self.strategy.stateful and "strat" in extra:
            out["ef_state"] = extra["strat"]
        return out


def _canonical_center(exch: Exchanger, state):
    """The center-parameter tree out of BOXED state, for both center rules
    and both venues — on-device (``begin_val``) and gathered-host
    (checkpoint save): plain replica read when replicated, the
    pad-trimming concat of the ``[n, chunk]`` rows when plan-sharded
    (``update_sharding.unshard_boxed`` is pure array-method algebra, so it
    runs on numpy and jax arrays alike)."""
    plan = exch.update_plan()
    if plan is None:
        return steps.unbox(state["extra"])["center"]
    return update_sharding.unshard_boxed(
        {"center": state["extra"]["center"]}, plan)["center"]


class EASGD_Exchanger(Exchanger):
    """Elastic averaging (reference: ``EASGD_Exchanger``, server+worker modes;
    SURVEY.md §3.2).

    The reference ran a dedicated server process holding center parameters,
    serving one worker at a time over CUDA-aware MPI Send/Recv.  Here the
    center is a replicated pytree carried in the exchanger state — the
    elastic update every ``sync_freq`` steps is, per the EASGD paper's
    synchronous form:

        worker_i ← worker_i − α (worker_i − center)
        center   ← center  + α · mean_i (worker_i − center)
    """

    name = "easgd"

    def __init__(self, config: Optional[dict] = None):
        super().__init__(config)
        self.alpha = float(self.config.get("alpha", 0.5))
        self.exchange_freq = int(self.config.get("sync_freq", 4))

    def _extra_full_template(self) -> Dict[str, Any]:
        return {"center": jax.tree.map(jnp.asarray, self.model.params)}

    def shardable_extra(self) -> tuple:
        # the center is bit-identical across workers (every worker applies
        # the same psum'd mean delta) — exactly the redundancy the
        # update-plane plan shards away
        return ("center",)

    def extra_specs(self, param_specs):
        # the center is a params-shaped tree: same per-leaf layout
        return {"center": param_specs}

    def has_exchange(self) -> bool:
        return True

    def supports_elastic(self) -> bool:
        return True

    def exchange_body(self, state, key, count):
        axis, alpha = WORKER_AXIS, self.alpha
        params = steps.unbox(state["params"])
        extra = steps.unbox(state["extra"])
        # sharded layout: rebuild the full center from the local chunks
        # (one fused allgather of values that ARE exact center windows —
        # bit-identical input to the unchanged algebra below), and slice
        # the updated center back into chunks at the end.  Identity when
        # sharding is off.
        center = self.unshard_extra(extra, axis)["center"]
        delta = jax.tree.map(lambda p, c: p - c, params, center)
        # elastic membership: demoted ranks contribute zero to the center
        # mean and skip the elastic pull (their replica is bit-unchanged),
        # while still issuing the SAME psum — SPMD lockstep demands every
        # rank run every collective.  Full membership traces the exact
        # pmean algebra (psum / size IS lax.pmean's definition).
        active = self._active_ranks
        ridx = lax.axis_index(axis)      # uniform; hoisted out of the arms
        if active is None:
            contrib, pull, n_act = delta, 1.0, float(self.size)
        else:
            m = jnp.asarray(self.active_mask())[ridx]
            contrib = jax.tree.map(lambda d: d * m, delta)
            pull, n_act = m, float(len(active))
        # the wire: one psum per bucket (bucket_bytes > 0) or the leaf
        # -wise monolith — bit-identical either way, the mask already
        # scaled the values above
        delta_sum = self._psum_tree(contrib, axis)
        mean_delta = jax.tree.map(lambda d: d / n_act, delta_sum)
        new_center = jax.tree.map(lambda c, d: c + alpha * d,
                                  center, mean_delta)
        new_params = jax.tree.map(lambda p, d: p - alpha * pull * d,
                                  params, delta)
        extra = dict(extra, **self.reshard_extra({"center": new_center},
                                                 axis))
        return dict(state, params=steps.box(new_params),
                    extra=steps.box(extra))

    def prepare(self, mesh: Mesh, model) -> None:
        super().prepare(mesh, model)
        self._build_exchange_fn()

    def canonical_params(self, state):
        """Validation/checkpoint read the CENTER (the reference validated
        against the server's center parameters)."""
        return _canonical_center(self, state)

    def numerics_extra(self, params, extra, axis):
        # the center copy is bit-identical across workers (every worker
        # applies the same psum'd mean delta) — the beacon digests it,
        # and ‖w_i − c‖ is the exact elastic distance of the source paper
        center = self.unshard_extra(extra, axis)["center"]
        return {"beacon_tree": center, "center": center}


class ASGD_Exchanger(Exchanger):
    """Downpour-style push-pull (reference: ``ASGD_Exchanger`` — described
    upstream as rudimentary, sharing the EASGD server scaffolding).

    Workers train locally for ``sync_freq`` steps; at exchange the center
    absorbs the SUM of worker deltas (downpour applies every worker's
    accumulated update) and workers restart from the fresh center.
    """

    name = "asgd"

    def __init__(self, config: Optional[dict] = None):
        super().__init__(config)
        self.exchange_freq = int(self.config.get("sync_freq", 1))

    def _extra_full_template(self) -> Dict[str, Any]:
        return {"center": jax.tree.map(jnp.asarray, self.model.params)}

    def shardable_extra(self) -> tuple:
        # identical replicas across workers (same psum'd delta sum applied)
        return ("center",)

    def extra_specs(self, param_specs):
        return {"center": param_specs}

    def has_exchange(self) -> bool:
        return True

    def supports_elastic(self) -> bool:
        return True

    def exchange_body(self, state, key, count):
        axis = WORKER_AXIS
        params = steps.unbox(state["params"])
        extra = steps.unbox(state["extra"])
        # sharded layout: full center from chunks in, chunks of the new
        # center out (see EASGD_Exchanger.exchange_body) — identity when off
        center = self.unshard_extra(extra, axis)["center"]
        # elastic membership: the center absorbs only ACTIVE workers'
        # accumulated deltas, and only active workers reset to the fresh
        # center — a demoted worker keeps its local replica bit-unchanged
        # (one uniform psum either way; SPMD lockstep).
        ridx = lax.axis_index(axis)      # uniform; hoisted out of the arms
        gate = None
        if self._active_ranks is not None:
            gate = jnp.asarray(self.active_mask())[ridx]

        def leaf_delta(p, c):
            d = p - c
            return d * gate if gate is not None else d

        # mask-then-psum, bucketed or monolithic per bucket_bytes — the
        # downpour sum is element-wise, so the schedule can't change it
        delta_sum = self._psum_tree(
            jax.tree.map(leaf_delta, params, center), axis)
        new_center = jax.tree.map(jnp.add, center, delta_sum)
        if gate is None:
            new_params = new_center
        else:
            new_params = jax.tree.map(
                lambda c, p: jnp.where(gate > 0, c, p), new_center, params)
        extra = dict(extra, **self.reshard_extra({"center": new_center},
                                                 axis))
        return dict(state, params=steps.box(new_params),
                    extra=steps.box(extra))

    def prepare(self, mesh: Mesh, model) -> None:
        super().prepare(mesh, model)
        self._build_exchange_fn()

    def canonical_params(self, state):
        return _canonical_center(self, state)

    def numerics_extra(self, params, extra, axis):
        # same contract as EASGD: replicated center = beacon + distance
        center = self.unshard_extra(extra, axis)["center"]
        return {"beacon_tree": center, "center": center}


class GOSGD_Exchanger(Exchanger):
    """Gossip SGD (reference: ``GOSGD_Exchanger``; SURVEY.md §3.3).

    Per exchange, each worker draws Bernoulli(p); senders ship
    ``(α/2 · params, α/2)`` to a peer and halve their α; receivers merge by
    weighted average and absorb the weight.  Σα is conserved exactly
    (tested).  Two peer-assignment modes (``gosgd_peers`` config):

    * ``'perm'`` (default): a random DERANGEMENT drawn per exchange from
      ``gosgd_n_perms`` (default 16) statically compiled candidates — a
      traced replicated index picks one ``lax.switch`` branch, each a single
      full-payload ``lax.ppermute``.  Peer choices decorrelate across
      senders (knowing one sender's peer no longer determines all others,
      the round-1 fidelity gap vs the reference's independent draws) at P
      wire bytes per exchange.  ``scripts/gosgd_mixing.py`` measures the
      mixing rates: statistically equal to ``'shift'`` at the reference's
      p=0.25 — the default is chosen on fidelity and wire cost (P vs
      P·log₂N), not mixing speed.
    * ``'shift'``: the shared random ring-shift ``s ∈ {1..N-1}`` decomposed
      into log₂N conditional power-of-two hops (every sender shifts by the
      same ``s``; P·log₂N wire bytes).
    * ``'iid'``: the reference's EXACT routing distribution — each sender
      draws its peer independently (uniform over the other workers), so two
      senders can hit one receiver.  ``gosgd_n_perms`` static iid
      assignment maps are pre-drawn; each decomposes into in-degree-rank
      ROUNDS (round r ships every destination's r-th inbound sender — a
      partial permutation, so one ``lax.ppermute`` each), and receivers SUM
      the inbound ``(α·params, α)`` payloads across rounds before one
      normalize: the sequential multi-message merge of the reference's
      receive loop (SURVEY.md §3.3), evaluated in closed form.  Wire cost
      P·(max in-degree of the drawn map); a worker with no inbound message
      receives zeros (ppermute semantics) and just keeps ``w_keep``.

    The round-3 verdict's exact-collision gap (#4) is closed by ``'iid'``:
    the merge algebra was always collision-ready, now a routing mode
    exercises it.  ``'perm'`` stays the default — collision-free routing
    mixes marginally faster (no mass concentration) at P wire bytes.
    """

    name = "gosgd"

    def __init__(self, config: Optional[dict] = None):
        super().__init__(config)
        self.p_share = float(self.config.get("exch_prob", 0.25))
        self.peers_mode = str(self.config.get("gosgd_peers", "perm"))
        self.n_perms = int(self.config.get("gosgd_n_perms", 16))
        # family seed offset: the K candidate routings are pre-drawn at a
        # fixed module seed for replayability; a long run that worries
        # about cycling one K=16 family can diversify via gosgd_seed or
        # raise gosgd_n_perms (K-sensitivity measured flat — see
        # scripts/gosgd_mixing.py --k-sweep, round-4 verdict weak #6)
        self.family_seed = int(self.config.get("gosgd_seed", 0))
        self.exchange_freq = 1

    def _extra_full_template(self) -> Dict[str, Any]:
        # α is per-worker divergent (it tracks the gossip mass each replica
        # carries) — never shardable
        return {"alpha": jnp.ones(())}

    def extra_specs(self, param_specs):
        return {"alpha": P()}

    # The routing-table algebra is jax-free seeded numpy, shared with the
    # simfleet width rehearsal — ONE implementation (parallel/topology.py)
    # generates the tables both the traced ppermute branches here and the
    # 1,000-worker virtual fleet route by.  Kept as staticmethods: tests
    # and scripts/gosgd_mixing.py address them through the class.
    _derangements = staticmethod(topology.derangements)
    _iid_maps = staticmethod(topology.iid_maps)
    _collision_rounds = staticmethod(topology.collision_rounds)

    def has_exchange(self) -> bool:
        return True

    def supports_elastic(self) -> bool:
        return True

    def prepare(self, mesh: Mesh, model) -> None:
        super().prepare(mesh, model)
        axis, n = WORKER_AXIS, self.size
        # elastic membership: gossip draws route only among the ACTIVE
        # ranks — a demoted rank is a fixed point of every routing table
        # (its send gate is also forced off in exchange_body), so its α
        # and replica are untouched until readmission regenerates the
        # tables with it back in.  Full membership is the identity
        # embedding: active == range(n).
        active = list(self._active_ranks) if self._active_ranks is not None \
            else list(range(n))
        m = len(active)
        n_bits = max(1, int(np.ceil(np.log2(max(m, 2)))))
        if self.peers_mode == "perm":
            sub_perms = self._derangements(m, self.n_perms,
                                           seed=0x605 + self.family_seed)
            perms = topology.embed_active(sub_perms, active, n)
        elif self.peers_mode == "iid":
            sub_maps = self._iid_maps(m, self.n_perms,
                                      seed=0x1d1 + self.family_seed)
            iid_maps = topology.embed_active(sub_maps, active, n)
        mode = self.peers_mode
        assert mode in ("perm", "shift", "iid"), (
            f"unknown gosgd_peers={mode!r}; have 'perm', 'shift', 'iid'")

        def route_shift(payload, step_key):
            """Shared ring-shift over the ACTIVE sub-ring: log₂M
            conditional power-of-two hops (inactive ranks receive zeros —
            their zero payload contributes nothing either way)."""
            shift = jax.random.randint(step_key, (), 1, m) if m > 1 \
                else jnp.ones((), jnp.int32)

            def hop(payload, k):
                stride = 1 << k
                perm = [(active[j], active[(j + stride) % m])
                        for j in range(m)]
                moved = jax.tree.map(
                    lambda x: lax.ppermute(x, axis, perm), payload)
                take = ((shift >> k) & 1) == 1
                return jax.tree.map(
                    lambda a, b: jnp.where(take, a, b), moved, payload)

            for k in range(n_bits):
                payload = hop(payload, k)
            return payload

        def route_perm(payload, step_key):
            """One of K static derangements (of the active set), picked by
            a replicated index."""
            if n == 1:
                return payload
            kidx = jax.random.randint(step_key, (), 0, len(perms))

            def mk(perm):
                pairs = [(i, int(perm[i])) for i in range(n)]
                return lambda p: jax.tree.map(
                    lambda x: lax.ppermute(x, axis, pairs), p)

            return lax.switch(kidx, [mk(p) for p in perms], payload)

        def route_iid(payload, step_key):
            """One of K static iid maps; collisions routed as summed rounds
            of partial-permutation ppermutes (see class docstring)."""
            if n == 1:
                return payload
            kidx = jax.random.randint(step_key, (), 0, len(iid_maps))

            def mk(dest):
                rounds = self._collision_rounds(dest)

                def f(p):
                    msg, w = p
                    acc_m = jax.tree.map(jnp.zeros_like, msg)
                    acc_w = jnp.zeros_like(w)
                    for pairs in rounds:
                        acc_m = jax.tree.map(
                            lambda a, x: a + lax.ppermute(x, axis, pairs),
                            acc_m, msg)
                        acc_w = acc_w + lax.ppermute(w, axis, pairs)
                    return acc_m, acc_w

                return f

            return lax.switch(kidx, [mk(d) for d in iid_maps], payload)

        # routing tables are static per (mesh size, mode, family seed,
        # active set) — pre-built here so exchange_body stays a pure traced
        # function whichever dispatch shape (standalone / in-scan fused)
        # traces it; set_active_ranks re-runs prepare to regenerate them
        self._route = {"perm": route_perm, "shift": route_shift,
                       "iid": route_iid}[mode]
        self._build_exchange_fn()

    def exchange_body(self, state, key, count):
        """Gossip draw contract: every random choice (Bernoulli send gate,
        routing pick) derives from ``fold_in(key, count)`` — a TRACED
        function of the base key and the step count, so the fused in-scan
        cadence (which passes one base key per k-step dispatch,
        ``steps.fused_exchange_key``) draws exactly like k standalone
        calls handed the same base key."""
        axis = WORKER_AXIS
        params = steps.unbox(state["params"])
        extra = steps.unbox(state["extra"])
        alpha = extra["alpha"]
        ridx = lax.axis_index(axis)
        step_key = jax.random.fold_in(key, count)
        # Per-worker Bernoulli send gate; a demoted rank (elastic
        # membership) never sends — its α mass would otherwise leak to a
        # peer the restricted routing tables no longer deliver to
        send = jax.random.bernoulli(
            jax.random.fold_in(step_key, ridx), self.p_share)
        amask = None if self._active_ranks is None else \
            jnp.asarray(self.active_mask() > 0)[ridx]
        if amask is not None:
            send = jnp.logical_and(send, amask)
        w_send = jnp.where(send, alpha * 0.5, 0.0)
        w_keep = alpha - w_send
        msg = jax.tree.map(lambda p: p * w_send, params)
        # bucketed wire: the routing modes tree.map their ppermutes over
        # whatever payload structure they are handed, so packing the
        # message into ~bucket_bytes vectors turns ONE whole-model
        # permute per hop into n_buckets independent per-bucket permutes
        # the scheduler can pipeline — and the merge below unpacks the
        # bit-identical payload (permutes are element-wise routing)
        plan = buckets.plan_buckets(params, self.bucket_bytes) \
            if self.bucket_bytes > 0 else None
        wire_msg = msg if plan is None else buckets.pack(msg, plan)
        wire_msg, w_recv = self._route((wire_msg, w_send), step_key)
        recv_msg = wire_msg if plan is None else \
            buckets.unpack(wire_msg, msg, plan)

        new_alpha = w_keep + w_recv
        new_params = jax.tree.map(
            lambda p, m: (w_keep * p + m) / new_alpha, params, recv_msg)
        if amask is not None:
            # demoted ranks are bit-frozen (the (α·p)/α round-trip is not
            # exact in floats): keep p and α verbatim off the active set
            new_alpha = jnp.where(amask, new_alpha, alpha)
            new_params = jax.tree.map(
                lambda np_, p_: jnp.where(amask, np_, p_),
                new_params, params)
        extra = dict(extra, alpha=new_alpha)
        return dict(state, params=steps.box(new_params),
                    extra=steps.box(extra))

    def canonical_params(self, state):
        """Consensus estimate: the α-weighted average of worker replicas."""
        params = state["params"]   # boxed [n, ...]
        alpha = state["extra"]["alpha"]  # [n]
        total = jnp.sum(alpha)

        def avg(x):
            w = alpha.reshape((-1,) + (1,) * (x.ndim - 1))
            return jnp.sum(x * w, axis=0) / total

        return jax.tree.map(avg, params)


EXCHANGERS = {
    "bsp": BSP_Exchanger,
    "easgd": EASGD_Exchanger,
    "asgd": ASGD_Exchanger,
    "gosgd": GOSGD_Exchanger,
}


def get_exchanger(name: str, config: Optional[dict] = None) -> Exchanger:
    try:
        return EXCHANGERS[name.lower()](config)
    except KeyError:
        raise ValueError(f"unknown exchanger {name!r}; have {sorted(EXCHANGERS)}")
