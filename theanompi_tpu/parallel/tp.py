"""Tensor (model) parallelism over a second mesh axis.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 — is pure
data parallelism): Megatron-style intra-layer model parallelism for the
transformer family, composed with every data-parallel rule on a 2-D
``('workers', 'model')`` mesh.

Design (the scaling-book recipe, done manually inside ``shard_map``):

* **Column-parallel** linear: weight sharded on the OUTPUT dim
  (``P(None, 'model')``), bias sharded with it.  The local matmul needs no
  communication; activations come out sharded on the feature dim.  A plain
  :class:`..models.layers.FC` applied to the local shard IS the
  column-parallel layer — only the PartitionSpec differs.
* **Row-parallel** linear (:class:`RowFC`): weight sharded on the INPUT dim
  (``P('model', None)``); each shard computes a partial product which is
  ``psum``'d over ``'model'`` BEFORE the (replicated) bias is added.
* **Attention** (:class:`TPMultiHeadAttention`): QKV projections
  column-parallel → each shard owns ``n_head/tp`` complete heads; the output
  projection is row-parallel.  One ``psum`` per attention block.
* **Embedding** (:class:`VocabParallelEmbedding`): vocabulary sharded; out-of
  -shard ids contribute zeros and one ``psum`` assembles the dense vectors.
* **Vocab-parallel loss** (:func:`tp_softmax_cross_entropy`): the LM head is
  column-parallel over the vocab, and cross-entropy works on the SHARDED
  logits — a ``psum`` of shard-local sum-exp and label log-likelihood instead
  of materializing (or gathering) the full ``[B·T, V]`` logits.  At real
  vocab sizes this is the difference between the head being free and the head
  being the memory high-water mark.

Gradient correctness falls out of shard_map's varying-axes type system: the
step state is "boxed" (varying over ``'workers'``), sharded leaves are varying
over ``'model'`` too, and autodiff inserts the transpose-psums for
replicated-over-'model' leaves (LayerNorms, row-parallel biases)
automatically — verified against a dense oracle in ``tests/test_tp.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..models import layers as L

MODEL_AXIS = "model"


@partial(jax.custom_jvp, nondiff_argnums=(1,))
def pmax_sg(x, axis_name):
    """``lax.pmax`` with a zero tangent.

    Used for the max-subtraction in the sharded log-sum-exp, where the true
    gradient contribution cancels exactly (same reason plain logsumexp may
    stop-gradient its max) — and ``pmax`` has no differentiation rule anyway.
    Output is vma-INVARIANT over ``axis_name``, which is what keeps the whole
    loss invariant and the transpose-psums correct.
    """
    return lax.pmax(x, axis_name)


@pmax_sg.defjvp
def _pmax_sg_jvp(axis_name, primals, tangents):
    (x,) = primals
    out = lax.pmax(x, axis_name)
    return out, jnp.zeros_like(out)


# ---------------------------------------------------------------------------
# TP layers (local-shard apply; global-shape init, sharded at placement)
# ---------------------------------------------------------------------------

class RowFC(L.FC):
    """Row-parallel linear: partial products ``psum``'d before the bias.

    ``init`` returns the GLOBAL weight; the per-leaf PartitionSpec
    ``P('model', None)`` (see :func:`fc_row_spec`) makes shard_map hand
    ``apply`` the local ``[n_in/tp, n_out]`` slice.
    """

    def __init__(self, *args, axis: str = MODEL_AXIS, **kwargs):
        super().__init__(*args, **kwargs)
        self.axis = axis

    def apply(self, params, x, *, train=False, rng=None, state=None):
        cd = self.compute_dtype
        y = jnp.dot(x.astype(cd), params["w"].astype(cd))
        y = lax.psum(y, self.axis) + params["b"].astype(cd)
        return L._activate(y, self.activation)


class TPMultiHeadAttention(L.MultiHeadAttention):
    """Head-sharded attention: ``n_head/tp`` complete heads per shard.

    QKV are column-parallel (no comm), the output projection is row-parallel
    (one ``psum``).  Same math and init as the dense layer — pinned equal in
    ``tests/test_tp.py``.
    """

    def __init__(self, dim, n_head, tp: int, causal: bool = True,
                 axis: str = MODEL_AXIS, **kwargs):
        super().__init__(dim, n_head, causal=causal, **kwargs)
        assert n_head % tp == 0, f"n_head={n_head} not divisible by tp={tp}"
        assert dim % tp == 0, f"dim={dim} not divisible by tp={tp}"
        self.tp = tp
        self.axis = axis

    def apply(self, params, x, *, train=False, rng=None, state=None):
        cd = self.compute_dtype
        b, t, d = x.shape
        h_loc = self.n_head // self.tp
        hd = self.dim // self.n_head
        d_loc = h_loc * hd
        xc = x.astype(cd)

        def proj(w):
            # local w slice is [d, d/tp] — a contiguous block of whole heads
            y = jnp.dot(xc, w.astype(cd))
            return y.reshape(b, t, h_loc, hd).transpose(0, 2, 1, 3)

        q, k, v = proj(params["wq"]), proj(params["wk"]), proj(params["wv"])
        o = self._attend(q, k, v)     # local heads, full sequence
        o = o.transpose(0, 2, 1, 3).reshape(b, t, d_loc)
        # output projection: local wo slice is [d/tp, d] (row-parallel)
        return lax.psum(jnp.dot(o.astype(cd), params["wo"].astype(cd)),
                        self.axis)


class VocabParallelEmbedding(L.Embedding):
    """Vocabulary-sharded embedding: out-of-shard ids contribute zeros; one
    ``psum`` assembles the dense vectors (Megatron's input embedding)."""

    def __init__(self, vocab, dim, tp: int, axis: str = MODEL_AXIS, **kwargs):
        super().__init__(vocab, dim, **kwargs)
        assert vocab % tp == 0, f"vocab={vocab} not divisible by tp={tp}"
        self.tp = tp
        self.axis = axis

    def apply(self, params, x, *, train=False, rng=None, state=None):
        w = params["w"]                      # local [vocab/tp, dim]
        v_loc = self.vocab // self.tp
        rank = lax.axis_index(self.axis)
        loc = x - rank * v_loc
        ok = (loc >= 0) & (loc < v_loc)
        rows = w[jnp.clip(loc, 0, v_loc - 1)]
        rows = jnp.where(ok[..., None], rows, 0.0)
        return lax.psum(rows, self.axis).astype(self.compute_dtype)


# ---------------------------------------------------------------------------
# vocab-parallel loss / metric heads (logits sharded [N, V/tp])
# ---------------------------------------------------------------------------

def tp_softmax_cross_entropy(local_logits, labels, axis: str = MODEL_AXIS,
                             label_smoothing: float = 0.0):
    """Mean NLL over VOCAB-SHARDED logits — never materializes ``[N, V]``.

    Shard-local sum-exp and label log-likelihood, one ``psum`` each; the max
    subtraction uses :func:`pmax_sg`.  ``label_smoothing`` mixes in the
    uniform term (its full-vocab logit mean is one more ``psum``).  Output
    is invariant over ``axis``.
    """
    l32 = local_logits.astype(jnp.float32)
    v_loc = l32.shape[-1]
    lmax = pmax_sg(jnp.max(l32, axis=-1), axis)
    z = lax.psum(jnp.sum(jnp.exp(l32 - lmax[:, None]), axis=-1), axis)
    logz = jnp.log(z) + lmax
    rank = lax.axis_index(axis)
    loc = labels - rank * v_loc
    ok = (loc >= 0) & (loc < v_loc)
    ll_loc = jnp.take_along_axis(
        l32, jnp.clip(loc, 0, v_loc - 1)[:, None], axis=-1)[:, 0]
    ll = lax.psum(jnp.where(ok, ll_loc, 0.0), axis)
    nll = jnp.mean(logz - ll)
    if label_smoothing:
        eps = float(label_smoothing)
        v_tot = v_loc * lax.psum(1, axis)
        mean_logit = lax.psum(jnp.sum(l32, axis=-1), axis) / v_tot
        return (1.0 - eps) * nll + eps * jnp.mean(logz - mean_logit)
    return nll


def tp_errors(local_logits, labels, axis: str = MODEL_AXIS):
    """Top-1 error over vocab-sharded logits: gather one (max, argmax) PAIR
    per shard (``[tp, N]``, not the logits) and pick the global winner."""
    v_loc = local_logits.shape[-1]
    rank = lax.axis_index(axis)
    l32 = local_logits.astype(jnp.float32)
    vals = lax.all_gather(jnp.max(l32, axis=-1), axis)            # [tp, N]
    args = lax.all_gather(jnp.argmax(l32, axis=-1) + rank * v_loc, axis)
    pred = jnp.take_along_axis(args, jnp.argmax(vals, axis=0)[None], 0)[0]
    err = jnp.mean((pred != labels).astype(jnp.float32))
    return lax.pmean(err, axis)       # values equal; pmean marks invariant


def tp_errors_top_x(local_logits, labels, x: int = 5,
                    axis: str = MODEL_AXIS):
    """Top-x error: shard-local top-x (clamped to the shard width), gathered
    ``[tp, N, x]`` and merged — ``tp·x`` candidates always cover the true
    global top-x."""
    v_loc = local_logits.shape[-1]
    x_loc = min(x, v_loc)
    rank = lax.axis_index(axis)
    l32 = local_logits.astype(jnp.float32)
    vals, idx = lax.top_k(l32, x_loc)
    vals = lax.all_gather(vals, axis)                    # [tp, N, x_loc]
    idx = lax.all_gather(idx + rank * v_loc, axis)
    n = l32.shape[0]
    vals = vals.transpose(1, 0, 2).reshape(n, -1)        # [N, tp·x_loc]
    idx = idx.transpose(1, 0, 2).reshape(n, -1)
    x_eff = min(x, vals.shape[-1])
    _, sel = lax.top_k(vals, x_eff)
    top = jnp.take_along_axis(idx, sel, axis=-1)
    hit = jnp.any(top == labels[:, None], axis=-1)
    err = jnp.mean((~hit).astype(jnp.float32))
    return lax.pmean(err, axis)
