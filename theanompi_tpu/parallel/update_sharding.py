"""Leaf-wise update-plane sharding over the data axis (ROADMAP item 2a).

Per "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (PAPERS.md, arXiv:2004.13336): under any rule whose update-plane
state is bit-identical across workers — BSP optimizer moments (every worker
applies the same reduced gradient), EASGD/ASGD center copies — replicating
that state per chip is pure memory waste.  This module is the ONE place the
partitioning lives:

* :func:`plan_tree` stamps a per-leaf schema (:class:`LeafPlan`): every leaf
  above a byte threshold is sharded on the data axis as a padded
  evenly-divisible flat chunk (spec ``P(workers)``); smaller leaves stay
  replicated (``P()``).  Worker-local divergent state (error-feedback
  buffers, gossip α) is never planned — rules declare their shardable keys
  via ``Exchanger.shardable_extra``.
* :func:`shard_tree` / :func:`unshard_tree` are the traced partition /
  rebuild primitives: per-leaf ``dynamic_slice`` down, ONE fused
  ``all_gather`` (per dtype) back up.  Elementwise update math on disjoint
  chunks followed by a value-exact gather is bit-identical to the
  replicated path — no reduction order changes anywhere
  (``tests/test_update_sharding.py`` pins it per rule).
* :func:`shard_opt` wraps any ``utils/opt.py`` ``OptPair`` so its state
  lives on the local chunks (the boxed ``[n_workers, chunk]`` layout IS the
  partition — per-chip update-plane bytes shrink ~N×), with the fused
  allgather rebuilding full params for the forward pass inside the same
  compiled step.
* :func:`flat_shard_opt` is the flat-chunk-everything configuration —
  ZeRO-1 (``parallel/zero.py``) collapses into a thin delegation to it.

tpulint's shard-rebuild-dominance checker
(``analysis/checkers/donation_safety.py``) gates the contract statically:
a chunk produced by :func:`slice_chunk`/:func:`shard_tree` may only escape
a function through its allgather rebuild (or from the schema's own named
producer functions) — a donated full buffer must never be silently
replaced by a local shard.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..utils import helper_funcs
from ..utils.opt import OptPair
from .mesh import WORKER_AXIS

# default byte threshold below which a leaf stays replicated: sharding a
# LayerNorm scale or a per-leaf step counter buys nothing and costs a
# gather lane; 64 KiB ≈ the point where the chunk still amortizes its
# slice/concat bookkeeping (config ``ushard_min_bytes`` overrides)
DEFAULT_MIN_BYTES = 65536


def chunk_size(n_total: int, n_workers: int) -> int:
    """ceil(P/N) — the per-worker chunk length of an N-way flat partition."""
    return -(-n_total // n_workers)


def padded_size(n_total: int, n_workers: int) -> int:
    """``chunk_size·N`` — the evenly-divisible padded flat length.  Callers
    pad to THIS, explicitly, before slicing chunks: a ragged ``n_total``
    (P=10, N=4 → chunk 3, padded 12) must never rely on an implicit
    zero-fill downstream (tests/test_zero.py pins the ragged case)."""
    return chunk_size(n_total, n_workers) * n_workers


class LeafPlan(NamedTuple):
    """The schema entry for ONE update-plane leaf."""
    path: str            # jax key-path string, for reports and errors
    shape: Tuple[int, ...]
    dtype: Any           # numpy dtype
    size: int            # prod(shape)
    sharded: bool        # above threshold → flat-chunked over the data axis
    chunk: int           # per-worker chunk length (== size when not sharded)
    pad: int             # chunk·N − size (0 when not sharded)
    spec: P              # P(workers) when sharded, P() when replicated


class UpdatePlan(NamedTuple):
    """A :class:`LeafPlan` per leaf, in the template's flatten order."""
    leaves: Tuple[LeafPlan, ...]
    n_workers: int
    min_bytes: int

    @property
    def any_sharded(self) -> bool:
        return any(l.sharded for l in self.leaves)

    def specs(self, template):
        """The schema as a template-structured pytree of PartitionSpecs."""
        flat, treedef = jax.tree_util.tree_flatten(template)
        assert len(flat) == len(self.leaves), (
            f"tree has {len(flat)} leaves, plan has {len(self.leaves)}")
        return jax.tree_util.tree_unflatten(
            treedef, [l.spec for l in self.leaves])


def plan_tree(template, n_workers: int, *,
              min_bytes: int = DEFAULT_MIN_BYTES,
              axis: str = WORKER_AXIS) -> UpdatePlan:
    """Stamp the leaf-wise sharding schema for ``template``.

    A leaf is sharded when its byte size reaches ``min_bytes`` AND it has at
    least ``n_workers`` elements (a scalar step counter can't usefully
    chunk).  ``n_workers == 1`` plans everything replicated — there is no
    partition to build."""
    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(template)[0]:
        shape = tuple(np.shape(leaf))
        dtype = np.dtype(getattr(leaf, "dtype", None)
                         or np.asarray(leaf).dtype)
        size = int(np.prod(shape)) if shape else 1
        sharded = (n_workers > 1 and size >= n_workers
                   and size * dtype.itemsize >= min_bytes)
        chunk = chunk_size(size, n_workers) if sharded else size
        leaves.append(LeafPlan(
            path=jax.tree_util.keystr(path), shape=shape, dtype=dtype,
            size=size, sharded=sharded, chunk=chunk,
            pad=(chunk * n_workers - size) if sharded else 0,
            spec=P(axis) if sharded else P()))
    return UpdatePlan(tuple(leaves), int(n_workers), int(min_bytes))


def _zip_leaves(tree, plan: UpdatePlan):
    flat, treedef = jax.tree_util.tree_flatten(tree)
    assert len(flat) == len(plan.leaves), (
        f"tree has {len(flat)} leaves, plan has {len(plan.leaves)} — "
        f"the plan must be built on the same template")
    return flat, treedef


def slice_chunk(flat, rank, chunk: int):
    """This worker's ``[chunk]`` window of an evenly-padded flat vector.
    ``flat`` must already be ``padded_size`` long — the slice is then always
    in bounds (dynamic_slice would silently clamp a ragged layout)."""
    return lax.dynamic_slice(flat, (rank * chunk,), (chunk,))


def all_gather_chunks(chunk_vec, axis: str = WORKER_AXIS):
    """Rebuild the padded flat vector from every worker's chunk — the ONE
    collective of the update-sharding wire (concatenating along the flat
    axis, so worker i's chunk lands at offset i·chunk exactly as
    :func:`slice_chunk` cut it)."""
    return lax.all_gather(chunk_vec, axis, tiled=True)


def shard_tree(tree, plan: UpdatePlan, rank):
    """Traced partition: each sharded leaf → this worker's flat ``[chunk]``
    (zero-padded to the evenly-divisible length first); replicated leaves
    pass through untouched.  Dtypes are preserved — the chunk is a window
    of the leaf's own storage, not an fp32 working copy."""
    flat, treedef = _zip_leaves(tree, plan)
    out = []
    for leaf, lp in zip(flat, plan.leaves):
        if not lp.sharded:
            out.append(leaf)
            continue
        v = jnp.reshape(leaf, (-1,))
        if lp.pad:
            v = jnp.pad(v, (0, lp.pad))
        out.append(slice_chunk(v, rank, lp.chunk))
    return jax.tree_util.tree_unflatten(treedef, out)


def unshard_tree(chunked, plan: UpdatePlan, axis: str = WORKER_AXIS):
    """Traced rebuild: ONE fused allgather per dtype.  All sharded chunks of
    a dtype concatenate into a single ``[C_total]`` vector, one
    ``all_gather(tiled=False)`` lifts it to ``[N, C_total]``, and each leaf
    slices its column block back out — ``[N, chunk] → flat[:size] → shape``.
    Values are exactly the chunks each worker cut, so the round trip is the
    identity bit for bit."""
    flat, treedef = _zip_leaves(chunked, plan)
    order = [i for i, lp in enumerate(plan.leaves) if lp.sharded]
    if not order:
        return chunked
    by_dtype: dict = {}
    for i in order:
        by_dtype.setdefault(plan.leaves[i].dtype, []).append(i)
    out = list(flat)
    for dtype, idxs in by_dtype.items():
        vec = flat[idxs[0]] if len(idxs) == 1 else \
            jnp.concatenate([flat[i] for i in idxs])
        gathered = lax.all_gather(vec, axis, tiled=False)  # [N, C_total]
        off = 0
        for i in idxs:
            lp = plan.leaves[i]
            block = lax.slice_in_dim(gathered, off, off + lp.chunk, axis=1)
            full = jnp.reshape(block, (-1,))[:lp.size]
            out[i] = jnp.reshape(full, lp.shape)
            off += lp.chunk
    return jax.tree_util.tree_unflatten(treedef, out)


def chunk_template(template, plan: UpdatePlan):
    """The per-worker shape template: sharded leaves become ``[chunk]``
    zeros of the leaf dtype (identical on every worker — broadcasting ONE
    template replicates it correctly, since optimizer state initializes to
    zeros); replicated leaves keep their full value."""
    flat, treedef = _zip_leaves(template, plan)
    out = [jnp.zeros((lp.chunk,), lp.dtype) if lp.sharded else leaf
           for leaf, lp in zip(flat, plan.leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_host_boxed(tree, plan: UpdatePlan):
    """Host-side boxed init for state whose VALUES differ per worker chunk
    (the EASGD/ASGD center copies): each sharded leaf partitions into its
    ``[N, chunk]`` rows (row i IS worker i's chunk — ``steps.place_boxed``
    with the uniform ``P(workers)`` spec then hands each chip exactly its
    shard); replicated leaves broadcast to ``[N, ...]`` rows.  The
    broadcast path of ``steps.replicate_tree`` can't do this — it places
    ONE template on every row."""
    n = plan.n_workers
    flat, treedef = _zip_leaves(tree, plan)
    out = []
    for leaf, lp in zip(flat, plan.leaves):
        a = np.asarray(leaf)
        if lp.sharded:
            v = np.pad(a.reshape(-1), (0, lp.pad))
            out.append(v.reshape(n, lp.chunk))
        else:
            out.append(np.broadcast_to(a[None], (n,) + a.shape).copy())
    return jax.tree_util.tree_unflatten(treedef, out)


def unshard_boxed(boxed, plan: UpdatePlan):
    """Host/device inverse of :func:`shard_host_boxed` on BOXED state: a
    sharded leaf's ``[N, chunk]`` rows concatenate back to the full value
    (trimming the pad); a replicated leaf reads row 0.  Pure array-method
    algebra (reshape/slice), so it serves both the gathered-host checkpoint
    path and the on-device ``begin_val`` read."""
    flat, treedef = _zip_leaves(boxed, plan)
    out = []
    for leaf, lp in zip(flat, plan.leaves):
        if lp.sharded:
            out.append(leaf.reshape((-1,))[:lp.size].reshape(lp.shape))
        else:
            out.append(leaf[0])
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_opt(opt: OptPair, plan: UpdatePlan,
              axis: str = WORKER_AXIS) -> OptPair:
    """Wrap ``opt`` so its state lives on the per-leaf local chunks.

    ``init`` builds state for the chunked template (the boxed
    ``[n_workers, chunk]`` layout is the partition); ``update`` slices
    grads/params down to this worker's chunks, runs the inner optimizer's
    elementwise math on them, and rebuilds full params with the fused
    allgather — inside whatever compiled step traces it, so
    ``steps_per_call`` scans and bucketed exchange collectives are
    untouched.  Pad lanes are zeros in params AND grads, and every wrapped
    optimizer's update maps zeros to zeros, so the pad never leaks (and is
    trimmed by the rebuild regardless).  Requires bit-identical grads
    across workers (BSP grads mode) — ``model_base.compile_iter_fns``
    asserts it."""

    def init(params):
        return {"opt": opt.init(chunk_template(params, plan))}

    def update(grads, st, params, lr):
        rank = lax.axis_index(axis)
        my_g = shard_tree(grads, plan, rank)
        my_p = shard_tree(params, plan, rank)
        my_p_new, opt_state = opt.update(my_g, st["opt"], my_p, lr)
        new_params = unshard_tree(my_p_new, plan, axis)
        return new_params, {"opt": opt_state}

    return OptPair(init, update)


def flat_shard_opt(opt: OptPair, n_workers: int, params_template,
                   axis: str = WORKER_AXIS, model_shards: int = 1,
                   pspecs=None, model_axes: tuple = ()) -> OptPair:
    """The flat-chunk-everything configuration — ZeRO-1.  One ceil(P/N)
    chunk of the WHOLE flattened tree per worker instead of per-leaf
    chunks: simpler layout, fp32 working copy, and the model-parallel
    composition (``model_shards``/``pspecs``) the leaf-wise wrapper does
    not carry.  ``parallel/zero.py`` is a thin delegation to this.

    Model parallelism (round-4): under tensor/pipeline param specs the
    per-device params are already the LOCAL shard, so ``params_template``
    must be the local template (``steps.local_param_template``) and
    ``update`` composes unchanged — flatten local, slice my worker chunk,
    all-gather over workers rebuilds the local flat.  Only ``init``
    differs: the HOST state template must be global-shaped,
    ``model_shards`` × the chunk (one chunk per model-group rank), laid
    out so the boxed spec ``P(workers, <model axes>)`` hands each device
    exactly its chunk (``steps.state_partition_specs``)."""
    n_total = helper_funcs.tree_size(params_template)
    chunk = chunk_size(n_total, n_workers)
    padded = padded_size(n_total, n_workers)

    def init(params):
        # per-worker view: state for ONE chunk per model-group rank (boxed
        # to [n_workers, model_shards·chunk] by the step machinery and
        # sharded so each chip holds exactly its [chunk] shard)
        return {"opt": opt.init(
            jnp.zeros((model_shards * chunk,), jnp.float32))}

    def update(grads, st, params, lr):
        flat_g = helper_funcs.flatten_tree(grads, pad_to_multiple_of=padded)
        flat_p = helper_funcs.flatten_tree(params, pad_to_multiple_of=padded)
        rank = lax.axis_index(axis)
        my_g = slice_chunk(flat_g, rank, chunk)
        my_p = slice_chunk(flat_p, rank, chunk)
        my_p_new, opt_state = opt.update(my_g, st["opt"], my_p, lr)
        full = all_gather_chunks(my_p_new, axis)                # [padded]
        new_params = helper_funcs.unflatten_like(params, full)
        if model_axes and pspecs is not None:
            # the flat concat JOINS every leaf's varying-mesh-axes set, so
            # leaves replicated over a model axis (LN scales, biases)
            # come back statically unprovable as invariant even though
            # their values are (grads of replicated leaves are psum'd over
            # model in the tp backward).  Re-anchor each leaf bit-exactly
            # (steps.anchor_invariant) over exactly the model axes its spec
            # does NOT shard — per axis, so a 3-D mesh leaf sharded over
            # 'pipe' but replicated over 'model' anchors on 'model' only.
            from .steps import _is_spec, anchor_invariant, spec_mentions

            def anchor(s, v):
                axes = tuple(a for a in model_axes
                             if not spec_mentions(s, (a,)))
                return anchor_invariant(v, axes)

            new_params = jax.tree.map(anchor, pspecs, new_params,
                                      is_leaf=_is_spec)
        return new_params, {"opt": opt_state}

    return OptPair(init, update)
