"""Pipeline parallelism: GPipe-style fill/drain AND interleaved
virtual-stage microbatch pipelining over a ``'pipe'`` mesh axis.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 — is pure
data parallelism): the transformer's homogeneous block stack is SHARDED over
pipeline stages and microbatches stream through the stages with one
``ppermute`` hop per tick.

TPU-first shape: everything is ONE compiled SPMD program.  A ``lax.scan``
walks a STATICALLY-BUILT per-tick schedule table (:func:`build_schedule` —
a pure function of ``(pp, v, M)``, so program shapes and AOT cache keys
depend only on those ints); each tick every device applies ONE of its local
layer chunks to either the freshly injected microbatch (global stage 0) or
the activation received from its ring predecessor, then shifts its output
one hop down the ring through the async-collective shims
(``jax_compat.ppermute_start``/``ppermute_done`` — per schedule slot, so a
jaxlib with a real async surface can overlap each hop with the next chunk's
compute inside the same fused scan).

**Fill/drain (``interleave=1``, the classic GPipe schedule).**  Each device
holds ``L/pp`` consecutive layers; ``M + pp − 1`` ticks; bubble
``(pp−1)/(M+pp−1)``, amortized by ``M ≫ pp``.

**Interleaved virtual stages (``interleave=v > 1``, per the MPMD
pipeline-parallelism paper — PAPERS.md, 2412.14374 — kept inside one SPMD
program per the pjit/TPUv4 LM paper, 2204.06514).**  Each device holds ``v``
NON-contiguous chunks of ``L/(pp·v)`` layers; chunk ``k`` of device ``r`` is
global stage ``k·pp + r`` (:func:`stage_permutation` maps the stacked layer
layout).  Microbatches stream in groups of ``pp``: group ``g``'s microbatch
``m'`` meets stage ``s = k·pp + r`` exactly at tick
``g·v·pp + k·pp + r + m'`` — consecutive stages are always one ring hop and
one tick apart (the ``pp−1 → 0`` wrap lands exactly where stage ``k·pp``
continues on device 0), so a single activation slot per device suffices, no
buffering.  ``v·M + pp − 1`` ticks of ``1/v``-sized chunks: warm-up shrinks
from ``pp−1`` to ``(pp−1)/v`` full-stage units and the bubble drops to
``(pp−1)/(v·M + pp−1)``.  ``v=1`` degenerates to the fill/drain schedule
EXACTLY (same table values, same partial-shift hop — bit-for-bit outputs,
pinned in ``tests/test_pipeline.py``).

**Bubble gating.**  Warm-up/drain ticks carry no real microbatch; the tick
body branches on the schedule's ``real`` mask with ``lax.cond`` so idle
devices genuinely idle (HLO conditional — the skipped chunk is never
computed) instead of burning the tick on masked garbage.  This is what
makes the schedule's bubble OBSERVABLE: devprof's ``bubble_fraction``
column reads fill/drain gaps straight off the trace, and the interleaved
schedule's smaller bubble is a measured win, not a modeled one
(``scripts/predict_scaling.py`` carries the matching analytic model).

Collected outputs live on the last global stage and are broadcast with a
masked ``psum``.  Gradients need nothing special: autodiff transposes the
scan + ``ppermute`` (reverse hops) + ``cond`` (same mask) + the chunk
``dynamic_slice`` (scatter-add into the stack), and shard_map's
varying-axes typing inserts the transpose-psums for stage-replicated
parameters, exactly as in ``parallel/tp.py`` — pinned against the dense
model in ``tests/test_pipeline.py``.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import jax_compat as jc
from .mesh import PIPE_AXIS
from .steps import _vary as _pvary


class Schedule(NamedTuple):
    """The per-tick schedule table — a pure function of ``(pp, v, m)``
    (:func:`build_schedule`), host-side numpy, scanned as ``xs``.

    Per-tick/per-device columns (``[T, pp]``): ``chunk`` (which local layer
    chunk device ``r`` runs), ``real`` (does it carry a real microbatch),
    ``micro`` (which one — clipped to a valid id on idle ticks).  Per-tick
    columns (``[T]``): ``inject_idx``/``inject`` (global stage 0's
    microbatch feed), ``collect_idx``/``collect`` (the last global stage's
    output slot).  ``perm`` is the static ppermute hop: the partial shift
    for ``v=1`` (today's schedule, bit-for-bit), the full ring for ``v>1``
    (the ``pp−1 → 0`` wrap carries chunk ``k``'s output to chunk ``k+1``)."""

    pp: int
    v: int
    m: int
    ticks: int
    chunk: np.ndarray
    real: np.ndarray
    micro: np.ndarray
    inject_idx: np.ndarray
    inject: np.ndarray
    collect_idx: np.ndarray
    collect: np.ndarray
    perm: Tuple[Tuple[int, int], ...]


def build_schedule(pp: int, v: int, m: int) -> Schedule:
    """Build the schedule table for ``pp`` devices × ``v`` virtual chunks ×
    ``m`` microbatches.  Pure ``(pp, v, m) → numpy`` — no jax, no device
    state — so two calls with equal ints are equal tables and the traced
    program (and its AOT cache key) is shape-stable."""
    pp, v, m = int(pp), int(v), int(m)
    if pp < 1 or v < 1 or m < 1:
        raise ValueError(f"build_schedule: pp={pp}, v={v}, m={m} must all "
                         "be >= 1")
    if v == 1:
        # the classic fill/drain table — EXACTLY today's closed forms
        # (inject always on at rank 0, clipped indices), so v=1 is the
        # current schedule bit-for-bit
        ticks = m + pp - 1
        t = np.arange(ticks)
        u = t[:, None] - np.arange(pp)[None, :]          # microbatch t-rank
        real = (u >= 0) & (u < m)
        chunk = np.zeros((ticks, pp), np.int32)
        micro = np.clip(u, 0, m - 1).astype(np.int32)
        inject_idx = np.clip(t, 0, m - 1).astype(np.int32)
        inject = np.ones(ticks, bool)
        collect_idx = np.clip(t - (pp - 1), 0, m - 1).astype(np.int32)
        collect = t >= pp - 1
        perm = tuple((i, i + 1) for i in range(pp - 1))
    else:
        if m % pp:
            raise ValueError(
                f"build_schedule: interleaved collect needs the microbatch "
                f"count divisible by pp — n_micro={m} % pp={pp} != 0 "
                f"(raise/align the 'pp_microbatches' config knob)")
        groups = m // pp
        span = v * pp                 # ticks one microbatch group occupies
        ticks = groups * span + pp - 1
        u = np.arange(ticks)[:, None] - np.arange(pp)[None, :]
        real = (u >= 0) & (u < groups * span)
        q = np.mod(u, span)
        chunk = np.where(real, q // pp, 0).astype(np.int32)
        micro = np.where(real, (u // span) * pp + np.mod(u, pp), 0)
        micro = np.clip(micro, 0, m - 1).astype(np.int32)
        # global stage 0 = device 0 chunk 0; stage v·pp−1 = last device's
        # last chunk.  The full-ring wrap from the last device re-enters
        # device 0 as its next chunk's input — the inject mask replaces it
        # only on chunk-0 ticks, which is precisely when the wrapped value
        # is a finished (already-collected) output.
        inject = real[:, 0] & (q[:, 0] < pp)
        inject_idx = np.where(inject, micro[:, 0], 0).astype(np.int32)
        collect = real[:, -1] & (q[:, -1] // pp == v - 1)
        collect_idx = np.where(collect, micro[:, -1], 0).astype(np.int32)
        perm = tuple((i, (i + 1) % pp) for i in range(pp))
    return Schedule(pp, v, m, ticks, chunk, real, micro, inject_idx, inject,
                    collect_idx, collect, perm)


def stage_permutation(n_layer: int, pp: int, v: int) -> np.ndarray:
    """Stacked-row → global-layer map for the interleaved layout.

    The stacked ``blocks`` leaves stay ``'pipe'``-sharded on their leading
    layer dim, so device ``r`` owns stacked rows ``[r·L/pp, (r+1)·L/pp)``;
    for those rows to BE its ``v`` virtual chunks (chunk ``k`` = global
    stage ``k·pp + r`` = depth-order layers ``[(k·pp+r)·c, (k·pp+r+1)·c)``,
    ``c = L/(pp·v)``), the stack stores layers in device-major/chunk-minor
    order: ``perm[j]`` is the depth-order layer held at stacked row ``j``.
    Identity when ``v == 1`` — the interleaved layout degenerates to the
    contiguous one."""
    n_layer, pp, v = int(n_layer), int(pp), int(v)
    if n_layer % (pp * v):
        raise ValueError(
            f"stage_permutation: n_layer={n_layer} not divisible by "
            f"pp*v={pp * v} (config knobs 'n_layer', 'pp', 'pp_interleave')")
    c = n_layer // (pp * v)
    return np.asarray([(k * pp + r) * c + i
                       for r in range(pp) for k in range(v)
                       for i in range(c)], dtype=np.int64)


def _validate(pp: int, v: int, m: int, local_layers: int) -> None:
    """Loud trace-time errors for degenerate schedules — each message names
    the config knob that fixes it (a silently-clipped schedule trains on
    garbage masks)."""
    if m < pp:
        raise ValueError(
            f"pipeline_apply: n_micro={m} < pp={pp} — the schedule is all "
            f"warm-up/drain bubble and some stages never see a real "
            f"microbatch; raise the 'pp_microbatches' config knob to at "
            f"least pp (default 2*pp)")
    if v > 1:
        if m % pp:
            raise ValueError(
                f"pipeline_apply: interleaved collect streams microbatches "
                f"in groups of pp — n_micro={m} is not divisible by "
                f"pp={pp}; align the 'pp_microbatches' config knob")
        if local_layers % v:
            raise ValueError(
                f"pipeline_apply: {local_layers} local layers do not split "
                f"into pp_interleave={v} chunks — n_layer must be "
                f"divisible by pp*pp_interleave (config knobs 'n_layer', "
                f"'pp_interleave')")


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis: str = PIPE_AXIS, remat: bool = True,
                   with_aux: bool = False, interleave: int = 1):
    """Stream microbatches through pipeline stages (inside ``shard_map``).

    ``stage_fn(stage_params, x) -> y`` applies a contiguous run of local
    layers to one microbatch (same shape in and out — transformer blocks).
    ``stage_params``: pytree whose leaves carry a leading LOCAL layer dim
    (the ``'pipe'``-sharded slice of the stacked layer stack — for
    ``interleave > 1`` in :func:`stage_permutation` order, so local rows
    ``[k·c, (k+1)·c)`` are virtual chunk ``k``).
    ``x_micro``: ``[M, mb, ...]`` microbatches, replicated over ``axis``.
    Returns ``[M, mb, ...]`` outputs, replicated over ``axis``.

    ``remat``: rematerialize each chunk application on the backward pass —
    the standard GPipe memory trade (activations for the whole scan would
    otherwise be saved per tick).

    ``with_aux``: ``stage_fn`` returns ``(y, aux_scalar)`` (MoE stacks ride
    their load-balance loss through the pipeline); the return becomes
    ``(outputs, aux_total)`` where ``aux_total`` sums every stage's aux
    over the REAL schedule slots only — warm-up/drain bubble ticks are
    cond-gated out entirely — then ``psum``s over the stages.

    ``interleave``: virtual chunks per device (``v``); see the module
    docstring.  ``interleave=1`` is today's fill/drain schedule
    bit-for-bit."""
    pp = lax.psum(1, axis)          # static: psum of a literal = axis size
    rank = lax.axis_index(axis)
    m = x_micro.shape[0]
    v = int(interleave)
    if pp == 1:
        v = 1                       # one device: no ring, no chunks to split
    local_layers = int(jax.tree.leaves(stage_params)[0].shape[0])
    _validate(pp, v, m, local_layers)
    chunk_layers = local_layers // v

    def raw(p, x):
        if with_aux:
            return stage_fn(p, x)
        # zero scalar derived from ONE element of x so BOTH lax.cond
        # branches below return an aux with x's full set of varying mesh
        # axes (a fresh jnp.zeros(()) would be device-invariant and
        # mismatch the skip branch's type)
        return stage_fn(p, x), x.reshape(-1)[0].astype(jnp.float32) * 0

    fn = jax.checkpoint(raw) if remat else raw

    sched = build_schedule(pp, v, m)
    last = pp - 1

    def tick(carry, xs):
        state, outputs, aux_acc = carry
        (inj_idx, inj, chunk_row, real_row, col_idx, col) = xs
        inject = jnp.take(x_micro, inj_idx, axis=0)
        inp = jnp.where((rank == 0) & inj, inject, state)
        if v == 1:
            params_k = stage_params
        else:
            k = jnp.take(chunk_row, rank)
            params_k = jax.tree.map(
                lambda l: lax.dynamic_slice_in_dim(
                    l, k * chunk_layers, chunk_layers, axis=0),
                stage_params)
        real = jnp.take(real_row, rank)
        # bubble gating: idle slots skip the chunk entirely (HLO
        # conditional) — fill/drain gaps are real device idle on the
        # trace, and the ring just carries the slot's input through
        out, aux = lax.cond(
            real,
            lambda px: fn(*px),
            lambda px: (px[1], px[1].reshape(-1)[0].astype(jnp.float32) * 0),
            (params_k, inp))
        aux_acc = aux_acc + jnp.where(real, aux, 0.0)
        # the last device's last chunk is the final global stage
        collect = (rank == last) & col
        cur = jnp.take(outputs, col_idx, axis=0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(collect, out, cur), col_idx, axis=0)
        if sched.perm:
            # one hop per schedule slot through the async shims: a jaxlib
            # with a real async surface overlaps the hop with the next
            # chunk's compute; the sync fallback is today's ppermute
            ticket = jc.ppermute_start(out, axis, list(sched.perm))
            state = jc.ppermute_done(ticket)
        else:
            state = out
        return (state, outputs, aux_acc), None

    state0 = _pvary(jnp.zeros_like(x_micro[0]), axis)
    out0 = _pvary(jnp.zeros_like(x_micro), axis)
    # zero scalar derived from ONE element of the data so it inherits
    # x_micro's full set of varying mesh axes (e.g. 'workers') on top of the
    # pipe axis, without a full-tensor reduce
    aux0 = _pvary(x_micro.reshape(-1)[0].astype(jnp.float32) * 0, axis)
    xs = tuple(_pvary(jnp.asarray(a), axis) for a in
               (sched.inject_idx, sched.inject, sched.chunk, sched.real,
                sched.collect_idx, sched.collect))
    (_, outputs, aux_acc), _ = lax.scan(tick, (state0, out0, aux0), xs)
    # only the last stage wrote non-zero outputs — masked psum broadcasts
    outputs = lax.psum(outputs, axis)
    if with_aux:
        return outputs, lax.psum(aux_acc, axis)
    return outputs


def microbatch(x, n_micro: int):
    """Split the leading batch dim into ``[n_micro, b/n_micro, ...]``."""
    b = x.shape[0]
    assert b % n_micro == 0, \
        f"batch {b} not divisible by pp_microbatches={n_micro}"
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def unmicrobatch(y):
    return y.reshape((-1,) + y.shape[2:])
