"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``'pipe'``
mesh axis.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 — is pure
data parallelism): the transformer's homogeneous block stack is SHARDED over
pipeline stages — each chip holds ``L/pp`` consecutive layers — and
microbatches stream through the stages with one ``ppermute`` hop per tick.

TPU-first shape: everything is ONE compiled SPMD program.  A ``lax.scan``
runs ``M + pp − 1`` ticks (M microbatches, pp stages); each tick every stage
applies its local layers to either the freshly injected microbatch (stage 0)
or the activation received from its predecessor, then shifts its output one
stage down the ring.  The bubble (stages idling for ``pp − 1`` ticks) is the
textbook GPipe cost — amortized by choosing ``M ≫ pp``.  Collected outputs
live on the last stage and are broadcast with a masked ``psum``.  Gradients
need nothing special: autodiff transposes the scan + ``ppermute`` (reverse
hops) and shard_map's varying-axes typing inserts the transpose-psums for
stage-replicated parameters (embeddings/head), exactly as in
``parallel/tp.py`` — pinned against the dense model in
``tests/test_pipeline.py``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import PIPE_AXIS
from .steps import _vary as _pvary


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis: str = PIPE_AXIS, remat: bool = True,
                   with_aux: bool = False):
    """Stream microbatches through pipeline stages (inside ``shard_map``).

    ``stage_fn(stage_params, x) -> y`` applies THIS stage's local layers to
    one microbatch (same shape in and out — transformer blocks).
    ``stage_params``: pytree whose leaves carry a leading LOCAL layer dim
    (the ``'pipe'``-sharded slice of the stacked layer stack).
    ``x_micro``: ``[M, mb, ...]`` microbatches, replicated over ``axis``.
    Returns ``[M, mb, ...]`` outputs, replicated over ``axis``.

    ``remat``: rematerialize each stage application on the backward pass —
    the standard GPipe memory trade (activations for the whole scan would
    otherwise be saved per tick).

    ``with_aux``: ``stage_fn`` returns ``(y, aux_scalar)`` (MoE stacks ride
    their load-balance loss through the pipeline); the return becomes
    ``(outputs, aux_total)`` where ``aux_total`` sums every stage's aux over
    the REAL microbatch ticks only — warm-up/drain bubble ticks process
    zeros/garbage and are masked out — then ``psum``s over the stages.
    """
    pp = lax.psum(1, axis)
    rank = lax.axis_index(axis)
    m = x_micro.shape[0]
    raw = stage_fn if with_aux \
        else (lambda p, x: (stage_fn(p, x), jnp.zeros((), jnp.float32)))
    fn = jax.checkpoint(raw) if remat else raw

    shift = [(i, i + 1) for i in range(pp - 1)] if pp > 1 else []

    def tick(carry, t):
        state, outputs, aux_acc = carry
        inject = jnp.take(x_micro, jnp.clip(t, 0, m - 1), axis=0)
        inp = jnp.where(rank == 0, inject, state)
        out, aux = fn(stage_params, inp)
        # this stage processed microbatch t-rank this tick iff in [0, M)
        real = (t >= rank) & (t - rank < m)
        aux_acc = aux_acc + jnp.where(real, aux, 0.0)
        # the last stage finished microbatch t-(pp-1) this tick
        j = jnp.clip(t - (pp - 1), 0, m - 1)
        collect = (rank == pp - 1) & (t >= pp - 1)
        cur = jnp.take(outputs, j, axis=0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(collect, out, cur), j, axis=0)
        state = lax.ppermute(out, axis, shift) if shift else out
        return (state, outputs, aux_acc), None

    state0 = _pvary(jnp.zeros_like(x_micro[0]), axis)
    out0 = _pvary(jnp.zeros_like(x_micro), axis)
    # zero scalar derived from ONE element of the data so it inherits
    # x_micro's full set of varying mesh axes (e.g. 'workers') on top of the
    # pipe axis, without a full-tensor reduce
    aux0 = _pvary(x_micro.reshape(-1)[0].astype(jnp.float32) * 0, axis)
    ticks = _pvary(jnp.arange(m + pp - 1), axis)
    (_, outputs, aux_acc), _ = lax.scan(tick, (state0, out0, aux0), ticks)
    # only the last stage wrote non-zero outputs — masked psum broadcasts
    outputs = lax.psum(outputs, axis)
    if with_aux:
        return outputs, lax.psum(aux_acc, axis)
    return outputs


def microbatch(x, n_micro: int):
    """Split the leading batch dim into ``[n_micro, b/n_micro, ...]``."""
    b = x.shape[0]
    assert b % n_micro == 0, \
        f"batch {b} not divisible by pp_microbatches={n_micro}"
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def unmicrobatch(y):
    return y.reshape((-1,) + y.shape[2:])
