"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``'pipe'``
mesh axis.

Beyond-parity capability (the reference — Theano-MPI, SURVEY.md §1 — is pure
data parallelism): the transformer's homogeneous block stack is SHARDED over
pipeline stages — each chip holds ``L/pp`` consecutive layers — and
microbatches stream through the stages with one ``ppermute`` hop per tick.

TPU-first shape: everything is ONE compiled SPMD program.  A ``lax.scan``
runs ``M + pp − 1`` ticks (M microbatches, pp stages); each tick every stage
applies its local layers to either the freshly injected microbatch (stage 0)
or the activation received from its predecessor, then shifts its output one
stage down the ring.  The bubble (stages idling for ``pp − 1`` ticks) is the
textbook GPipe cost — amortized by choosing ``M ≫ pp``.  Collected outputs
live on the last stage and are broadcast with a masked ``psum``.  Gradients
need nothing special: autodiff transposes the scan + ``ppermute`` (reverse
hops) and shard_map's varying-axes typing inserts the transpose-psums for
stage-replicated parameters (embeddings/head), exactly as in
``parallel/tp.py`` — pinned against the dense model in
``tests/test_pipeline.py``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import PIPE_AXIS
from .steps import _vary as _pvary


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis: str = PIPE_AXIS, remat: bool = True):
    """Stream microbatches through pipeline stages (inside ``shard_map``).

    ``stage_fn(stage_params, x) -> y`` applies THIS stage's local layers to
    one microbatch (same shape in and out — transformer blocks).
    ``stage_params``: pytree whose leaves carry a leading LOCAL layer dim
    (the ``'pipe'``-sharded slice of the stacked layer stack).
    ``x_micro``: ``[M, mb, ...]`` microbatches, replicated over ``axis``.
    Returns ``[M, mb, ...]`` outputs, replicated over ``axis``.

    ``remat``: rematerialize each stage application on the backward pass —
    the standard GPipe memory trade (activations for the whole scan would
    otherwise be saved per tick).
    """
    pp = lax.psum(1, axis)
    rank = lax.axis_index(axis)
    m = x_micro.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    shift = [(i, i + 1) for i in range(pp - 1)] if pp > 1 else []

    def tick(carry, t):
        state, outputs = carry
        inject = jnp.take(x_micro, jnp.clip(t, 0, m - 1), axis=0)
        inp = jnp.where(rank == 0, inject, state)
        out = fn(stage_params, inp)
        # the last stage finished microbatch t-(pp-1) this tick
        j = jnp.clip(t - (pp - 1), 0, m - 1)
        collect = (rank == pp - 1) & (t >= pp - 1)
        cur = jnp.take(outputs, j, axis=0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(collect, out, cur), j, axis=0)
        state = lax.ppermute(out, axis, shift) if shift else out
        return (state, outputs), None

    state0 = _pvary(jnp.zeros_like(x_micro[0]), axis)
    out0 = _pvary(jnp.zeros_like(x_micro), axis)
    ticks = _pvary(jnp.arange(m + pp - 1), axis)
    (_, outputs), _ = lax.scan(tick, (state0, out0), ticks)
    # only the last stage wrote non-zeros — masked psum broadcasts to all
    return lax.psum(outputs, axis)


def microbatch(x, n_micro: int):
    """Split the leading batch dim into ``[n_micro, b/n_micro, ...]``."""
    b = x.shape[0]
    assert b % n_micro == 0, \
        f"batch {b} not divisible by pp_microbatches={n_micro}"
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def unmicrobatch(y):
    return y.reshape((-1,) + y.shape[2:])
