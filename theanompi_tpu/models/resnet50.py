"""ResNet-50.

Reference: ``theanompi/models/resnet50.py`` / ``lasagne_model_zoo/resnet50.py``
(SURVEY.md §2.7) — the He et al. 2015 bottleneck architecture wrapped in the
Theano-MPI model contract.  BASELINE.json config #4 trains it under the GoSGD
gossip exchanger.

The residual graph is built from a composite :class:`Bottleneck` layer that
threads BatchNorm running statistics through the ``state`` pytree (the
framework's BN-state convention, models/layers.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import layers as L
from .data.imagenet import ImageNet_data
from .model_base import ModelBase


class ConvBN(L.Layer):
    """conv → BN → (relu) — the ResNet primitive."""

    has_state = True

    def __init__(self, in_ch, out_ch, kernel, stride=1, padding="SAME",
                 relu=True, cd=jnp.bfloat16, bn_nd=None, name="convbn"):
        self.name = name
        self.conv = L.Conv(in_ch, out_ch, kernel, stride=stride,
                           padding=padding, w_init="he", activation=None,
                           compute_dtype=cd, name="conv")
        self.bn = L.BatchNorm(out_ch, norm_dtype=bn_nd, name="bn")
        self.relu = relu

    def init(self, key):
        return {"conv": self.conv.init(key), "bn": self.bn.init(key)}

    def init_state(self):
        return {"bn": self.bn.init_state()}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        y = self.conv.apply(params["conv"], x, train=train)
        y, bn_new = self.bn.apply(params["bn"], y, train=train,
                                  state=state["bn"])
        if self.relu:
            y = jax.nn.relu(y)
        return y, ({"bn": bn_new} if bn_new is not None else None)


class Bottleneck(L.Layer):
    """1×1 → 3×3 → 1×1 bottleneck with identity or projection shortcut."""

    has_state = True

    def __init__(self, in_ch, mid_ch, out_ch, stride=1, project=False,
                 cd=jnp.bfloat16, bn_nd=None, name="block"):
        self.name = name
        self.a = ConvBN(in_ch, mid_ch, 1, cd=cd, bn_nd=bn_nd, name="a")
        self.b = ConvBN(mid_ch, mid_ch, 3, stride=stride, cd=cd, bn_nd=bn_nd,
                        name="b")
        self.c = ConvBN(mid_ch, out_ch, 1, relu=False, cd=cd, bn_nd=bn_nd,
                        name="c")
        self.project = project
        if project:
            self.proj = ConvBN(in_ch, out_ch, 1, stride=stride, relu=False,
                               cd=cd, bn_nd=bn_nd, name="proj")

    def _subs(self):
        subs = {"a": self.a, "b": self.b, "c": self.c}
        if self.project:
            subs["proj"] = self.proj
        return subs

    def init(self, key):
        subs = self._subs()
        keys = jax.random.split(key, len(subs))
        return {n: m.init(k) for (n, m), k in zip(subs.items(), keys)}

    def init_state(self):
        return {n: m.init_state() for n, m in self._subs().items()}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        new_state = {}

        def run(name, mod, inp):
            y, st = mod.apply(params[name], inp, train=train,
                              state=state[name])
            if st is not None:
                new_state[name] = st
            return y

        y = run("a", self.a, x)
        y = run("b", self.b, y)
        y = run("c", self.c, y)
        sc = run("proj", self.proj, x) if self.project else x
        out = jax.nn.relu(y + sc)
        return out, (new_state or None)


class ResNet50(ModelBase):
    batch_size = 32
    epochs = 90
    n_subb = 1
    learning_rate = 0.1
    momentum = 0.9
    weight_decay = 0.0001
    lr_adjust_epochs = (30, 60, 80)
    n_class = 1000

    # (mid_ch, out_ch, n_blocks, first_stride) per stage
    stages = ((64, 256, 3, 1), (128, 512, 4, 2),
              (256, 1024, 6, 2), (512, 2048, 3, 2))

    def build_model(self) -> None:
        cd = self.config.get("compute_dtype", jnp.bfloat16)
        # bn_norm_dtype='bfloat16': normalize in bf16 with fp32 stats —
        # perf A/B lever (BASELINE.md round-3 finding 2); default fp32-exact
        bn_nd = self.config.get("bn_norm_dtype")
        if isinstance(bn_nd, str):
            bn_nd = jnp.dtype(bn_nd).type if bn_nd != "none" else None
        nc = self.config.get("n_class", self.n_class)
        layers = [
            ConvBN(3, 64, 7, stride=2, padding=3, cd=cd, bn_nd=bn_nd,
                   name="conv1"),
            L.Pool(3, 2, mode="max", padding="SAME", name="pool1"),
        ]
        in_ch = 64
        for si, (mid, out, reps, stride) in enumerate(self.stages, start=2):
            for bi in range(reps):
                layers.append(Bottleneck(
                    in_ch, mid, out,
                    stride=stride if bi == 0 else 1,
                    project=(bi == 0), cd=cd, bn_nd=bn_nd,
                    name=f"res{si}_{bi + 1}"))
                in_ch = out
        self.trunk = L.Sequential(layers)
        self.fc = L.FC(2048, nc, w_init=("normal", 0.01), activation=None,
                       compute_dtype=cd, name="softmax")
        self.data = ImageNet_data(self.config, self.batch_size, crop=224)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"trunk": self.trunk.init(k1), "fc": self.fc.init(k2)}

    def init_bn_state(self):
        return {"trunk": self.trunk.init_state()}

    def apply_model(self, params, x, *, train, rng, state):
        y, trunk_state = self.trunk.apply(params["trunk"], x, train=train,
                                          rng=rng, state=state["trunk"])
        y = jnp.mean(y, axis=(1, 2))      # global average pool
        logits = self.fc.apply(params["fc"], y, train=train)
        return logits, {"trunk": trunk_state}
