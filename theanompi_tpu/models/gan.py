"""GAN family — WGAN and LSGAN.

Reference: ``theanompi/models/wgan.py`` / ``lsgan.py`` (SURVEY.md §2.7) —
added late upstream, each a two-function (G/D) training loop driven by the
same worker contract as the CNN zoo.

TPU-first re-design: instead of two separately compiled Theano functions
called in alternation from Python, the G and D updates live in ONE compiled
SPMD step over the combined ``{"G": ..., "D": ...}`` parameter pytree, using
``stop_gradient`` to decouple the two objectives:

* the critic loss sees generated images through ``stop_gradient`` (no grads
  into G),
* the generator loss sees the critic through ``stop_gradient``-ed critic
  params (no grads into D),

so one ``value_and_grad`` yields both gradient sets at the current params
(simultaneous-SGD GAN training).  The reference's "train D for ``n_critic``
iterations per G iteration" cadence is preserved by the
:meth:`postprocess_update` hook, which on gated steps keeps G's OLD params
and optimizer state (equivalent to the reference not calling the G update
function at all — merely zeroing G's gradient would still let a stateful
optimizer's momentum/weight-decay move G).  Traced ``jnp.where`` selection,
so the step stays one static XLA program.  WGAN's weight clipping rides the
same hook.

Because the combined params are an ordinary pytree, all four exchange rules
(BSP/EASGD/ASGD/GoSGD) and every wire strategy work on GANs unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import layers as L
from .data.cifar10 import Cifar10_data
from .model_base import ModelBase


def _generator(z_dim: int, base: int, cd) -> L.Sequential:
    """DCGAN-style: z → 4×4×(4·base) → 8×8 → 16×16 → 32×32×3 tanh."""
    return L.Sequential([
        L.FC(z_dim, 4 * 4 * base * 4, w_init=("normal", 0.02),
             activation=None, compute_dtype=cd, name="proj"),
        L.Reshape((4, 4, base * 4), name="reshape"),
        L.BatchNorm(base * 4, name="bn0"),
        L.Activation("relu", name="relu0"),
        L.ConvTranspose(base * 4, base * 2, 5, stride=2, activation=None,
                        compute_dtype=cd, name="up1"),
        L.BatchNorm(base * 2, name="bn1"),
        L.Activation("relu", name="relu1"),
        L.ConvTranspose(base * 2, base, 5, stride=2, activation=None,
                        compute_dtype=cd, name="up2"),
        L.BatchNorm(base, name="bn2"),
        L.Activation("relu", name="relu2"),
        L.ConvTranspose(base, 3, 5, stride=2, activation="tanh",
                        compute_dtype=cd, name="up3"),
    ])


def _critic(base: int, cd) -> L.Sequential:
    """Strided-conv critic, LeakyReLU, no norm layers (weight-clipped WGAN
    critics and plain LSGAN discriminators both work unnormalized here, and
    keeping D stateless means its double application — real then fake —
    threads no BN state)."""
    return L.Sequential([
        L.Conv(3, base, 5, stride=2, padding="SAME", w_init=("normal", 0.02),
               activation="leaky_relu", compute_dtype=cd, name="c1"),
        L.Conv(base, base * 2, 5, stride=2, padding="SAME",
               w_init=("normal", 0.02), activation="leaky_relu",
               compute_dtype=cd, name="c2"),
        L.Conv(base * 2, base * 4, 5, stride=2, padding="SAME",
               w_init=("normal", 0.02), activation="leaky_relu",
               compute_dtype=cd, name="c3"),
        L.Flatten(),
        L.FC(4 * 4 * base * 4, 1, w_init=("normal", 0.02), activation=None,
             compute_dtype=cd, name="score"),
    ])


class GAN_ModelBase(ModelBase):
    """Shared G/D machinery; subclasses define the two losses."""

    batch_size = 64
    epochs = 50
    n_subb = 1
    learning_rate = 5e-5
    weight_decay = 0.0
    optimizer = "rmsprop"
    z_dim = 128
    base_width = 64
    n_critic = 5          # D steps per G step (WGAN paper's cadence)

    def build_model(self) -> None:
        cd = self.config.get("compute_dtype", jnp.bfloat16)
        self.z_dim = int(self.config.get("z_dim", self.z_dim))
        self.n_critic = int(self.config.get("n_critic", self.n_critic))
        # the n_critic gate selects optimizer-state subtrees by their "G"
        # param path — incompatible with layouts that flatten paths away
        # (model_base's zero_opt guard keys off this)
        self.gates_opt_state_by_path = self.n_critic > 1
        base = int(self.config.get("base_width", self.base_width))
        self.G = _generator(self.z_dim, base, cd)
        self.D = _critic(base, cd)
        self.data = Cifar10_data(self.config, self.batch_size)

    # combined pytree: one params/state tree drives the whole step machinery
    def init_params(self, key):
        kg, kd = jax.random.split(key)
        return {"G": self.G.init(kg), "D": self.D.init(kd)}

    def init_bn_state(self):
        return {"G": self.G.init_state()}

    def generate(self, params, z, *, train=False, rng=None, bn_state=None):
        """Sample images from the generator (returns (images, new_G_bn))."""
        g_state = bn_state["G"] if bn_state else self.G.init_state()
        return self.G.apply(params["G"], z, train=train, rng=rng,
                            state=g_state)

    # -- subclass hooks: the two objectives ---------------------------------

    def d_loss(self, score_real, score_fake):
        raise NotImplementedError

    def g_loss(self, score_fake):
        raise NotImplementedError

    # -- the combined objective (see module docstring) ----------------------

    def loss_and_metrics(self, params, bn_state, batch, rng, train):
        rng_z, rng_g, rng_d = jax.random.split(rng, 3)
        x_real = batch["x"]
        n = x_real.shape[0]
        z = jax.random.normal(rng_z, (n, self.z_dim))
        fake, g_bn = self.G.apply(params["G"], z, train=train, rng=rng_g,
                                  state=bn_state["G"])
        fake = fake.astype(jnp.float32)

        # critic objective: no grads into G.  D is stateless (no norm
        # layers), so real and detached-fake share ONE critic pass.
        both = jnp.concatenate([x_real, jax.lax.stop_gradient(fake)], axis=0)
        scores = self.D.apply(params["D"], both, train=train,
                              rng=rng_d)[0].astype(jnp.float32)
        s_real, s_fake_d = scores[:n], scores[n:]
        d_cost = self.d_loss(s_real, s_fake_d)

        # generator objective: through a frozen critic
        d_frozen = jax.lax.stop_gradient(params["D"])
        s_fake_g = self.D.apply(d_frozen, fake, train=train,
                                rng=rng_d)[0].astype(jnp.float32)
        g_cost = self.g_loss(s_fake_g)

        # The differentiated value must be the SUM (each term owns one
        # gradient path).  Reported columns: cost = D+G combined, error =
        # G loss — so the critic loss is (cost − error); the reference's
        # GAN scripts printed both losses separately.
        return d_cost + g_cost, (g_cost, {"G": g_bn})

    def val_metrics(self, params, bn_state, batch):
        rng = jax.random.key(0)
        cost, (g_cost, _) = self.loss_and_metrics(params, bn_state, batch,
                                                  rng, False)
        return cost, (g_cost, g_cost)

    # -- cadence + projection hooks -----------------------------------------

    def postprocess_update(self, old_params, old_opt, new_params, new_opt,
                           count):
        """Off the critic cadence, keep G's old params AND optimizer state —
        as if the G update function was never called (the reference
        alternated two compiled functions).  ``opt_state`` may nest the
        G/D split anywhere (momentum mirrors params; adam wraps it in
        m/v/t), so gating selects any subtree under a ``"G"`` key."""
        if self.n_critic <= 1:
            return new_params, new_opt
        g_on = count % self.n_critic == 0

        def gate(new, old):
            def pick(path, n_leaf, o_leaf):
                in_g = any(getattr(k, "key", None) == "G" for k in path)
                return jnp.where(g_on, n_leaf, o_leaf) if in_g else n_leaf
            return jax.tree_util.tree_map_with_path(pick, new, old)

        return gate(new_params, old_params), gate(new_opt, old_opt)


class WGAN(GAN_ModelBase):
    """Wasserstein GAN with weight clipping (Arjovsky et al. 2017), the
    algorithm of the reference's ``wgan.py``."""

    clip = 0.01

    def build_model(self) -> None:
        # zero_opt flattens the EMA shadow into per-worker chunks nested at
        # opt['opt']['ema']; the clip projection below keys on a top-level
        # 'ema' and would silently skip it — validation would then score an
        # unclipped (Lipschitz-violating) critic shadow.  Config-only check:
        # fail before the expensive network/dataset build.
        assert not (self.config.get("ema_decay")
                    and self.config.get("zero_opt")), (
            "WGAN weight clipping cannot project the EMA shadow once "
            "zero_opt has flattened it into optimizer chunks — drop one of "
            "ema_decay/zero_opt")
        super().build_model()
        self.clip = float(self.config.get("clip", self.clip))

    def d_loss(self, s_real, s_fake):
        # critic maximizes E[s_real] − E[s_fake]
        return jnp.mean(s_fake) - jnp.mean(s_real)

    def g_loss(self, s_fake):
        return -jnp.mean(s_fake)

    def postprocess_update(self, old_params, old_opt, new_params, new_opt,
                           count):
        new_params, new_opt = super().postprocess_update(
            old_params, old_opt, new_params, new_opt, count)
        c = self.clip
        new_params = {"G": new_params["G"],
                      "D": jax.tree.map(lambda p: jnp.clip(p, -c, c),
                                        new_params["D"])}
        if isinstance(new_opt, dict) and "ema" in new_opt:
            # the EMA wrapper blends PRE-clip params into the shadow (it
            # runs before this hook) — project the shadow's critic into the
            # clip box too, or validation/inference would score an
            # infeasible (Lipschitz-violating) critic
            new_opt = dict(new_opt, ema={
                "G": new_opt["ema"]["G"],
                "D": jax.tree.map(lambda p: jnp.clip(p, -c, c),
                                  new_opt["ema"]["D"])})
        return new_params, new_opt


class LSGAN(GAN_ModelBase):
    """Least-squares GAN (Mao et al. 2017), the algorithm of the reference's
    ``lsgan.py`` — a=0, b=1, c=1 coding."""

    learning_rate = 2e-4
    optimizer = "adam"
    n_critic = 1

    def d_loss(self, s_real, s_fake):
        return 0.5 * (jnp.mean((s_real - 1.0) ** 2) + jnp.mean(s_fake ** 2))

    def g_loss(self, s_fake):
        return 0.5 * jnp.mean((s_fake - 1.0) ** 2)
