"""Model registry shared by the bench/sweep harnesses.

One source of truth for the short names used by ``bench.py`` (BENCH_MODEL)
and ``scripts/scaling_sweep.py`` (--model): dotted modelfile, modelclass,
and the synthetic-data config that makes the model runnable with zero data
setup — the same (modelfile, modelclass) import-by-string contract the
reference's launcher used (SURVEY.md §2.1).
"""

MODELS = {
    "alexnet": ("theanompi_tpu.models.alex_net", "AlexNet",
                {"synthetic_batches": 4}),
    "googlenet": ("theanompi_tpu.models.googlenet", "GoogLeNet",
                  {"synthetic_batches": 4}),
    "vgg16": ("theanompi_tpu.models.vggnet_16", "VGGNet_16",
              {"synthetic_batches": 4}),
    "resnet50": ("theanompi_tpu.models.resnet50", "ResNet50",
                 {"synthetic_batches": 4}),
    # sample_kind rides the extra dict: bench.py labels throughput honestly
    # (sequences/sec, no cross-unit vs_baseline) for sequence models
    "transformer_lm": ("theanompi_tpu.models.transformer_lm", "TransformerLM",
                       {"synthetic_train": 2048,
                        "sample_kind": "sequences"}),
    "moe_lm": ("theanompi_tpu.models.transformer_lm", "MoETransformerLM",
               {"synthetic_train": 2048, "sample_kind": "sequences"}),
    # 8192 synthetic samples: enough for a 64-worker × batch-128 global
    # batch in the scaling sweep (the bench's per-chip runs need far less)
    "cifar10": ("theanompi_tpu.models.cifar10", "Cifar10_model",
                {"synthetic_train": 8192}),
}
