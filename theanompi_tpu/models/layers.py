"""Layer library.

TPU-native rebuild of Theano-MPI's ``theanompi/models/layers2.py``
(SURVEY.md §2.7): ``Weight`` (init schemes + ``.npy`` save/load), ``Conv``
(was cuDNN, now ``lax.conv_general_dilated`` lowered onto the MXU), ``Pool``,
``LRN``, ``FC``, ``Dropout`` (train/test switch), ``Softmax``, ``BatchNorm``,
and input mean-subtraction handling.

Design departures from the reference, all deliberate and TPU-first:

* **NHWC layout** (reference was Theano's bc01/NCHW): XLA:TPU's native conv
  layout, keeps the channel dim in the lane dimension of the VPU/MXU tiles.
* **Pure pytrees, no shared variables**: a layer is a small object holding
  static hyperparameters; ``init(key)`` returns its parameter pytree and
  ``apply(params, x, ...)`` is pure, so the whole model jits and shards.
* **Mixed precision hook**: every layer takes ``compute_dtype`` — params stay
  float32, matmul/conv inputs are cast (bfloat16 on TPU) with float32
  accumulation via ``preferred_element_type``.
* **BatchNorm state** (running stats) is threaded as a separate ``state``
  pytree through :class:`Sequential` rather than mutated in place.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Weight: init schemes + save/load  (reference: layers2.Weight)
# ---------------------------------------------------------------------------

def init_weight(key, shape: Sequence[int], scheme: Union[str, Tuple[str, float]],
                dtype=jnp.float32) -> jnp.ndarray:
    """Initialize one weight array.

    Scheme forms (matching the reference's ``Weight`` modes):
      ``('normal', std)``   gaussian, the AlexNet-era default (std 0.01/0.005)
      ``('constant', c)``   constant fill (bias init 0 / 0.1 / 1)
      ``'xavier'``          Glorot uniform
      ``'he'``              He normal (fan-in), for ReLU nets
    """
    if isinstance(scheme, tuple):
        kind, arg = scheme
    else:
        kind, arg = scheme, None
    if kind == "normal":
        std = 0.01 if arg is None else arg
        return std * jax.random.normal(key, shape, dtype)
    if kind == "constant":
        c = 0.0 if arg is None else arg
        return jnp.full(shape, c, dtype)
    fan_in, fan_out = _fans(shape)
    if kind == "xavier":
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -limit, limit)
    if kind == "he":
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    raise ValueError(f"unknown init scheme {kind!r}")


def _fans(shape: Sequence[int]) -> Tuple[int, int]:
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv HWIO: receptive field * in, receptive field * out
    rf = int(np.prod(shape[:-2]))
    return rf * shape[-2], rf * shape[-1]


# ---------------------------------------------------------------------------
# Layer base + Sequential
# ---------------------------------------------------------------------------

class Layer:
    """Base layer: static hyperparams on the object, params/state as pytrees."""

    name: str = "layer"
    has_state: bool = False  # True for BatchNorm (running stats)

    def init(self, key) -> Any:
        return None

    def init_state(self) -> Any:
        return None

    def apply(self, params, x, *, train: bool = False, rng=None, state=None):
        """Returns ``(y, new_state)``; ``new_state`` is None for stateless layers."""
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class Sequential:
    """Composes layers; params/state are dicts keyed by unique layer names.

    Reference equivalent: the explicit layer lists each model file built and
    iterated over (``layers2`` usage in ``alex_net.py`` etc.).
    """

    def __init__(self, layers: List[Layer]):
        self.layers = layers
        seen: Dict[str, int] = {}
        self._keys = []
        for l in layers:
            n = l.name
            if n in seen:
                seen[n] += 1
                n = f"{n}_{seen[l.name]}"
            else:
                seen[n] = 0
            self._keys.append(n)

    def init(self, key) -> Dict[str, Any]:
        params = {}
        for k, layer in zip(self._keys, self.layers):
            key, sub = jax.random.split(key)
            p = layer.init(sub)
            if p is not None:
                params[k] = p
        return params

    def init_state(self) -> Dict[str, Any]:
        state = {}
        for k, layer in zip(self._keys, self.layers):
            s = layer.init_state()
            if s is not None:
                state[k] = s
        return state

    def apply(self, params, x, *, train=False, rng=None, state=None):
        state = state or {}
        new_state = dict(state)
        for k, layer in zip(self._keys, self.layers):
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            y = layer.apply(params.get(k), x, train=train, rng=sub,
                            state=state.get(k))
            if layer.has_state:
                x, st = y
                if st is not None:
                    new_state[k] = st
            else:
                x = y if not isinstance(y, tuple) else y[0]
        return x, new_state


# ---------------------------------------------------------------------------
# Conv  (reference: layers2.Conv on cuDNN; here lax conv on the MXU)
# ---------------------------------------------------------------------------

class Conv(Layer):
    def __init__(self, in_ch: int, out_ch: int, kernel: Union[int, Tuple[int, int]],
                 stride: Union[int, Tuple[int, int]] = 1,
                 padding: Union[str, int] = "SAME",
                 groups: int = 1,
                 w_init=("normal", 0.01), b_init=("constant", 0.0),
                 activation: Optional[str] = "relu",
                 compute_dtype=jnp.bfloat16, name: str = "conv"):
        self.in_ch, self.out_ch = in_ch, out_ch
        self.kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        if isinstance(padding, int):
            self.padding = [(padding, padding), (padding, padding)]
        else:
            self.padding = padding
        self.groups = groups  # AlexNet's historical 2-group convs
        self.w_init, self.b_init = w_init, b_init
        self.activation = activation
        self.compute_dtype = compute_dtype
        self.name = name

    def init(self, key):
        kh, kw = self.kernel
        kw_key, b_key = jax.random.split(key)
        w = init_weight(kw_key, (kh, kw, self.in_ch // self.groups, self.out_ch),
                        self.w_init)
        b = init_weight(b_key, (self.out_ch,), self.b_init)
        return {"w": w, "b": b}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        cd = self.compute_dtype
        # No preferred_element_type here: with bf16 operands the MXU still
        # accumulates in fp32 internally, and requesting an fp32 output breaks
        # the conv transpose (bf16 kernel vs fp32 cotangent) in jax 0.9.
        y = jax.lax.conv_general_dilated(
            x.astype(cd), params["w"].astype(cd),
            window_strides=self.stride, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.groups,
        )
        y = y + params["b"].astype(cd)
        return _activate(y, self.activation)


class ConvTranspose(Layer):
    """Transposed (fractionally-strided) convolution — the DCGAN-style
    generator upsampler used by the reference's GAN models
    (``theanompi/models/wgan.py`` / ``lsgan.py``, SURVEY.md §2.7).  Lowered
    via ``lax.conv_transpose`` onto the MXU."""

    def __init__(self, in_ch: int, out_ch: int, kernel: Union[int, Tuple[int, int]],
                 stride: Union[int, Tuple[int, int]] = 2,
                 padding: str = "SAME",
                 w_init=("normal", 0.02), b_init=("constant", 0.0),
                 activation: Optional[str] = "relu",
                 compute_dtype=jnp.bfloat16, name: str = "deconv"):
        self.in_ch, self.out_ch = in_ch, out_ch
        self.kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        self.padding = padding
        self.w_init, self.b_init = w_init, b_init
        self.activation = activation
        self.compute_dtype = compute_dtype
        self.name = name

    def init(self, key):
        kh, kw = self.kernel
        kw_key, b_key = jax.random.split(key)
        w = init_weight(kw_key, (kh, kw, self.in_ch, self.out_ch), self.w_init)
        b = init_weight(b_key, (self.out_ch,), self.b_init)
        return {"w": w, "b": b}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        cd = self.compute_dtype
        y = jax.lax.conv_transpose(
            x.astype(cd), params["w"].astype(cd),
            strides=self.stride, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = y + params["b"].astype(cd)
        return _activate(y, self.activation)


class FC(Layer):
    """Fully connected layer (reference: layers2.FC / Softmax head matmul)."""

    def __init__(self, n_in: int, n_out: int,
                 w_init=("normal", 0.005), b_init=("constant", 0.0),
                 activation: Optional[str] = "relu",
                 compute_dtype=jnp.bfloat16, name: str = "fc"):
        self.n_in, self.n_out = n_in, n_out
        self.w_init, self.b_init = w_init, b_init
        self.activation = activation
        self.compute_dtype = compute_dtype
        self.name = name

    def init(self, key):
        kw, kb = jax.random.split(key)
        return {"w": init_weight(kw, (self.n_in, self.n_out), self.w_init),
                "b": init_weight(kb, (self.n_out,), self.b_init)}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        cd = self.compute_dtype
        y = jnp.dot(x.astype(cd), params["w"].astype(cd))
        y = y + params["b"].astype(cd)
        return _activate(y, self.activation)


class Pool(Layer):
    """Max/avg pooling via ``lax.reduce_window`` (reference: layers2.Pool)."""

    def __init__(self, size: Union[int, Tuple[int, int]] = 2,
                 stride: Optional[Union[int, Tuple[int, int]]] = None,
                 mode: str = "max", padding: str = "VALID", name: str = "pool"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        stride = stride if stride is not None else self.size
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        self.mode = mode
        self.padding = padding
        self.name = name

    def apply(self, params, x, *, train=False, rng=None, state=None):
        window = (1,) + self.size + (1,)
        strides = (1,) + self.stride + (1,)
        if self.mode == "max":
            return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides,
                                         self.padding)
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, self.padding)
        if self.padding == "VALID":
            return s / (self.size[0] * self.size[1])
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                       self.padding)
        return s / counts


class LRN(Layer):
    """Cross-channel local response normalization (AlexNet-era; reference
    layers2.LRN):  b = a / (k + alpha/n * sum_{window} a^2)^beta.

    TPU mapping: the 5-tap cross-channel sum runs as a 1×1 conv against a
    constant banded matrix — the channel dim is the lane dim on TPU, where a
    sliding ``reduce_window`` is slow, but a tiny matmul rides the MXU and its
    gradient is the same (symmetric) band conv.  Measured ~1.9× faster
    fwd+bwd than ``reduce_window`` at AlexNet's lrn1 shape, bit-accurate in
    fp32.  For β=0.75 the power is composed from ``rsqrt``/``sqrt``
    (d^-0.75 = rsqrt(d)·sqrt(rsqrt(d))) instead of a transcendental pow.
    """

    def __init__(self, n: int = 5, k: float = 2.0, alpha: float = 1e-4,
                 beta: float = 0.75, impl: str = "band", name: str = "lrn"):
        self.n, self.k, self.alpha, self.beta = n, k, alpha, beta
        self.impl = impl      # 'band' (XLA conv, default) | 'pallas' (fused)
        self.name = name

    def apply(self, params, x, *, train=False, rng=None, state=None):
        # both implementations live in ops.lrn (single source of the math;
        # the Pallas kernel is equality-tested against lrn_jnp)
        if self.impl == "pallas":
            from ..ops.lrn import lrn as lrn_fused
            return lrn_fused(x, self.n, self.k, self.alpha, self.beta)
        from ..ops.lrn import lrn_jnp
        return lrn_jnp(x, self.n, self.k, self.alpha, self.beta)


class Dropout(Layer):
    """Train/test-switched dropout (reference: layers2.Dropout)."""

    def __init__(self, rate: float = 0.5, name: str = "dropout"):
        self.rate = rate
        self.name = name

    def apply(self, params, x, *, train=False, rng=None, state=None):
        if not train or self.rate == 0.0:
            return x
        assert rng is not None, "Dropout in train mode needs an rng"
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


class BatchNorm(Layer):
    """Batch normalization with running stats (reference: layers2.BatchNorm).

    Train mode uses batch statistics and returns updated running stats in the
    state pytree; eval mode uses running stats.  Normalizes over all axes but
    the last (NHWC channel).

    ``norm_dtype``: dtype of the normalize arithmetic.  ``None`` (default)
    upcasts activations to fp32 end to end.  ``bfloat16`` keeps the STAT
    math fp32 (reductions upcast on read, which XLA fuses into the producer)
    but folds (mean, inv·scale, bias) into per-channel bf16 vectors and
    normalizes in bf16 — no fp32 activation tensor is materialized between
    bf16 convs.  A/B lever for the BN share of ResNet-50 step time
    (BASELINE.md round-3 analysis, finding 2)."""

    has_state = True

    def __init__(self, n_ch: int, momentum: float = 0.9, eps: float = 1e-5,
                 norm_dtype=None, name: str = "bn"):
        self.n_ch, self.momentum, self.eps = n_ch, momentum, eps
        self.norm_dtype = norm_dtype
        self.name = name

    def init(self, key):
        return {"scale": jnp.ones((self.n_ch,)), "bias": jnp.zeros((self.n_ch,))}

    def init_state(self):
        return {"mean": jnp.zeros((self.n_ch,)), "var": jnp.ones((self.n_ch,))}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        axes = tuple(range(x.ndim - 1))
        if train:
            x32 = x.astype(jnp.float32)
            mean = jnp.mean(x32, axes)
            var = jnp.var(x32, axes)
            m = self.momentum
            new_state = {"mean": m * state["mean"] + (1 - m) * mean,
                         "var": m * state["var"] + (1 - m) * var}
        else:
            mean, var = state["mean"], state["var"]
            new_state = None
        inv = jax.lax.rsqrt(var + self.eps)
        nd = self.norm_dtype
        if nd is not None and x.dtype == nd:
            # per-channel affine in the activation dtype: y = x·a + b with
            # a = inv·scale, b = bias − mean·inv·scale (both fp32 → nd)
            a = (inv * params["scale"]).astype(nd)
            b = (params["bias"] - mean * inv * params["scale"]).astype(nd)
            return x * a + b, new_state
        y = (x.astype(jnp.float32) - mean) * inv * params["scale"] \
            + params["bias"]
        return y.astype(x.dtype), new_state


class LayerNorm(Layer):
    """Layer normalization over the trailing feature dim (transformer zoo;
    the CNN zoo's normalizer is :class:`BatchNorm`).  Stats in fp32."""

    def __init__(self, dim: int, eps: float = 1e-5, name: str = "ln"):
        self.dim, self.eps = dim, eps
        self.name = name

    def init(self, key):
        return {"scale": jnp.ones((self.dim,)), "bias": jnp.zeros((self.dim,))}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + self.eps)
        return (y * params["scale"] + params["bias"]).astype(x.dtype)


class Embedding(Layer):
    """Token embedding lookup; fp32 table, output cast to compute dtype."""

    def __init__(self, vocab: int, dim: int, w_init=("normal", 0.02),
                 compute_dtype=jnp.bfloat16, name: str = "embed"):
        self.vocab, self.dim = vocab, dim
        self.w_init = w_init
        self.compute_dtype = compute_dtype
        self.name = name

    def init(self, key):
        return {"w": init_weight(key, (self.vocab, self.dim), self.w_init)}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        return params["w"].astype(self.compute_dtype)[x]


class MultiHeadAttention(Layer):
    """Causal multi-head self-attention (transformer zoo).

    QKV/output projections ride the MXU in ``compute_dtype``; the softmax
    attention itself runs through :func:`ops.ring_attention.attention_reference`
    (fp32 accumulation) — the sequence-SHARDED variant of the same math is
    :func:`ops.ring_attention.ring_attention` on a 2-D data×seq mesh.

    ``attn_impl='flash'`` (TPU only): the fused Pallas flash-attention
    kernel (``jax.experimental.pallas.ops.tpu.flash_attention`` — tiled
    online-softmax in VMEM, custom VJP, never materializes the [T, T]
    scores) instead of the XLA einsum chain.  Needs seq_len a multiple of
    the kernel's 128-wide blocks."""

    def __init__(self, dim: int, n_head: int, causal: bool = True,
                 w_init=("normal", 0.02), compute_dtype=jnp.bfloat16,
                 attn_impl: str = "reference", name: str = "attn"):
        assert dim % n_head == 0
        assert attn_impl in ("reference", "flash"), attn_impl
        self.dim, self.n_head, self.causal = dim, n_head, causal
        self.w_init = w_init
        self.compute_dtype = compute_dtype
        self.attn_impl = attn_impl
        self.name = name

    def _attend(self, q, k, v):
        """[B, H, T, hd] → [B, H, T, hd] softmax attention."""
        if self.attn_impl == "flash":
            from jax.experimental.pallas.ops.tpu.flash_attention import \
                flash_attention
            hd = q.shape[-1]
            return flash_attention(q, k, v, causal=self.causal,
                                   sm_scale=1.0 / (hd ** 0.5))
        from ..ops.ring_attention import attention_reference
        return attention_reference(q, k, v, causal=self.causal)

    def init(self, key):
        ks = jax.random.split(key, 4)
        mk = lambda k: init_weight(k, (self.dim, self.dim), self.w_init)
        return {"wq": mk(ks[0]), "wk": mk(ks[1]), "wv": mk(ks[2]),
                "wo": mk(ks[3])}

    def _proj(self, params, x, name):
        cd = self.compute_dtype
        b, t, _ = x.shape
        h, hd = self.n_head, self.dim // self.n_head
        y = jnp.dot(x.astype(cd), params[name].astype(cd))
        return y.reshape(b, t, h, hd).transpose(0, 2, 1, 3)    # [B,H,T,hd]

    def apply(self, params, x, *, train=False, rng=None, state=None):
        cd = self.compute_dtype
        b, t, d = x.shape
        q = self._proj(params, x, "wq")
        k = self._proj(params, x, "wk")
        v = self._proj(params, x, "wv")
        o = self._attend(q, k, v)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, d)
        return jnp.dot(o.astype(cd), params["wo"].astype(cd))

    # -- KV-cache decode path (inference; tests pin it against apply) ------

    def apply_prefill(self, params, x):
        """Full causal forward over the prompt buffer that ALSO returns the
        projected K/V as the decode cache: ``(y, (k, v))``,
        k/v ``[B, H, S, hd]``."""
        cd = self.compute_dtype
        b, t, d = x.shape
        q = self._proj(params, x, "wq")
        k = self._proj(params, x, "wk")
        v = self._proj(params, x, "wv")
        o = self._attend(q, k, v)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, d)
        return jnp.dot(o.astype(cd), params["wo"].astype(cd)), (k, v)

    def apply_decode(self, params, x1, cache, pos):
        """One decode step: ``x1`` is the CURRENT token's activation
        ``[B, 1, D]`` at position ``pos``; the projected K/V are written
        into the cache at ``pos`` and the query attends to positions
        ``≤ pos`` only.  Returns ``(y [B, 1, D], new_cache)``."""
        cd = self.compute_dtype
        b, _, d = x1.shape
        k_cache, v_cache = cache                      # [B, H, S, hd]
        s = k_cache.shape[2]
        q = self._proj(params, x1, "wq")              # [B, H, 1, hd]
        k1 = self._proj(params, x1, "wk")
        v1 = self._proj(params, x1, "wv")
        k_cache = jax.lax.dynamic_update_slice(k_cache, k1, (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v1, (0, 0, pos, 0))
        from ..ops.ring_attention import NEG_INF
        hd = self.dim // self.n_head
        scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                            k_cache.astype(jnp.float32)) / (hd ** 0.5)
        mask = jnp.arange(s) <= pos                    # causal over cache
        scores = jnp.where(mask[None, None, None], scores, NEG_INF)
        p = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p,
                       v_cache.astype(jnp.float32)).astype(x1.dtype)
        o = o.transpose(0, 2, 1, 3).reshape(b, 1, d)
        y = jnp.dot(o.astype(cd), params["wo"].astype(cd))
        return y, (k_cache, v_cache)


class Flatten(Layer):
    def __init__(self, name: str = "flatten"):
        self.name = name

    def apply(self, params, x, *, train=False, rng=None, state=None):
        return x.reshape((x.shape[0], -1))


class Reshape(Layer):
    """Reshape trailing dims to ``shape`` (batch dim preserved)."""

    def __init__(self, shape: Tuple[int, ...], name: str = "reshape"):
        self.shape = tuple(shape)
        self.name = name

    def apply(self, params, x, *, train=False, rng=None, state=None):
        return x.reshape((x.shape[0],) + self.shape)


class Activation(Layer):
    def __init__(self, kind: str = "relu", name: str = "act"):
        self.kind = kind
        self.name = name

    def apply(self, params, x, *, train=False, rng=None, state=None):
        return _activate(x, self.kind)


def _activate(x, kind: Optional[str]):
    if kind is None or kind == "linear":
        return x
    if kind == "relu":
        return jax.nn.relu(x)
    if kind == "tanh":
        return jnp.tanh(x)
    if kind == "sigmoid":
        return jax.nn.sigmoid(x)
    if kind == "leaky_relu":
        return jax.nn.leaky_relu(x, 0.2)
    raise ValueError(f"unknown activation {kind!r}")


# ---------------------------------------------------------------------------
# Loss / error heads (reference: layers2.Softmax negative_log_likelihood +
# errors / errors_top_x)
# ---------------------------------------------------------------------------

def softmax_cross_entropy(logits, labels,
                          label_smoothing: float = 0.0) -> jnp.ndarray:
    """Mean NLL of integer ``labels`` under softmax(logits), in float32.

    ``label_smoothing=ε`` mixes the one-hot target with the uniform
    distribution (Szegedy et al. 2016): loss = (1−ε)·NLL + ε·mean_k(−log
    p_k) — exact, not the folded approximation."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    nll = jnp.mean(logz - ll)
    if label_smoothing:
        eps = float(label_smoothing)
        uniform = logz - jnp.mean(logits, axis=-1)            # −mean log p_k
        return (1.0 - eps) * nll + eps * jnp.mean(uniform)
    return nll


def errors(logits, labels) -> jnp.ndarray:
    """Top-1 error rate."""
    return jnp.mean((jnp.argmax(logits, axis=-1) != labels).astype(jnp.float32))


def errors_top_x(logits, labels, x: int = 5) -> jnp.ndarray:
    """Top-x error rate (reference reports top-5 for ImageNet).  Clamped to
    the class count so small smoke models can reuse the standard head."""
    x = min(x, logits.shape[-1])
    _, topk = jax.lax.top_k(logits, x)
    hit = jnp.any(topk == labels[:, None], axis=-1)
    return jnp.mean((~hit).astype(jnp.float32))
