"""VGG-16 (and the VGG-11 "shallow" variant).

Reference: ``theanompi/models/vggnet_16.py`` / ``vggnet_11_shallow.py``
(SURVEY.md §2.7).  ImageNet-1k, 224×224 crops, 3×3 conv stacks with 2×2/2
pooling, 4096-wide dropout-regularized FC head, momentum SGD + weight decay
5e-4.  VGG-16 is BASELINE.json config #3 (EASGD) and #5 (compressed
exchanger) — the parameter-heaviest model in the zoo (~138M), which is what
makes it the communication stress test.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import layers as L
from .data.imagenet import ImageNet_data
from .model_base import ModelBase

# (channels, n_convs) per block — 'D' configuration
_VGG16_BLOCKS = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))
# 'A' configuration (the reference's "shallow" VGG-11)
_VGG11_BLOCKS = ((64, 1), (128, 1), (256, 2), (512, 2), (512, 2))


def _vgg_stack(blocks, cd, n_class):
    layers = []
    in_ch = 3
    for bi, (ch, reps) in enumerate(blocks, start=1):
        for ci in range(reps):
            layers.append(L.Conv(in_ch, ch, 3, padding="SAME", w_init="he",
                                 compute_dtype=cd,
                                 name=f"conv{bi}_{ci + 1}"))
            in_ch = ch
        layers.append(L.Pool(2, 2, mode="max", name=f"pool{bi}"))
    layers += [
        L.Flatten(),
        L.FC(512 * 7 * 7, 4096, w_init=("normal", 0.005),
             b_init=("constant", 0.1), compute_dtype=cd, name="fc6"),
        L.Dropout(0.5, name="drop6"),
        L.FC(4096, 4096, w_init=("normal", 0.005),
             b_init=("constant", 0.1), compute_dtype=cd, name="fc7"),
        L.Dropout(0.5, name="drop7"),
        L.FC(4096, n_class, w_init=("normal", 0.01), activation=None,
             compute_dtype=cd, name="softmax"),
    ]
    return L.Sequential(layers)


class VGGNet_16(ModelBase):
    batch_size = 32          # reference used small per-worker batches (VRAM)
    epochs = 70
    n_subb = 1
    learning_rate = 0.01
    momentum = 0.9
    weight_decay = 0.0005
    lr_adjust_epochs = (25, 50, 65)
    n_class = 1000

    blocks = _VGG16_BLOCKS

    def build_model(self) -> None:
        cd = self.config.get("compute_dtype", jnp.bfloat16)
        nc = self.config.get("n_class", self.n_class)
        self.seq = _vgg_stack(self.blocks, cd, nc)
        self.data = ImageNet_data(self.config, self.batch_size, crop=224)


class VGGNet_11_shallow(VGGNet_16):
    blocks = _VGG11_BLOCKS


VGGNet = VGGNet_16
