"""GoogLeNet (Inception v1) with auxiliary classifiers.

Reference: ``theanompi/models/googlenet.py`` (SURVEY.md §2.7): ImageNet,
batch 32 in the paper's benchmarks, the full Szegedy et al. 2014 graph —
stem, nine inception modules, two auxiliary softmax heads (weighted 0.3 into
the training loss, dropped at eval), global average pooling, dropout 0.4.

The branch-parallel inception module is a composite :class:`Inception`
layer; the aux taps make the trunk a staged pipeline rather than one
Sequential, so this model overrides the ``init_params``/``apply_model`` hooks.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from . import layers as L
from .data.imagenet import ImageNet_data
from .model_base import ModelBase


class Inception(L.Layer):
    """Four-branch inception module: 1×1 / 1×1→3×3 / 1×1→5×5 / pool→1×1,
    channel-concatenated."""

    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, pp, cd, name):
        self.name = name
        self.out_ch = c1 + c3 + c5 + pp
        k = dict(w_init="he", compute_dtype=cd)
        self.b1 = L.Sequential([L.Conv(in_ch, c1, 1, name="1x1", **k)])
        self.b2 = L.Sequential([
            L.Conv(in_ch, c3r, 1, name="3x3r", **k),
            L.Conv(c3r, c3, 3, padding="SAME", name="3x3", **k)])
        self.b3 = L.Sequential([
            L.Conv(in_ch, c5r, 1, name="5x5r", **k),
            L.Conv(c5r, c5, 5, padding="SAME", name="5x5", **k)])
        self.b4_pool = L.Pool(3, 1, mode="max", padding="SAME", name="pool")
        self.b4 = L.Sequential([L.Conv(in_ch, pp, 1, name="poolproj", **k)])

    def init(self, key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        return {"b1": self.b1.init(k1), "b2": self.b2.init(k2),
                "b3": self.b3.init(k3), "b4": self.b4.init(k4)}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        y1, _ = self.b1.apply(params["b1"], x, train=train)
        y2, _ = self.b2.apply(params["b2"], x, train=train)
        y3, _ = self.b3.apply(params["b3"], x, train=train)
        yp = self.b4_pool.apply(None, x)
        y4, _ = self.b4.apply(params["b4"], yp, train=train)
        return jnp.concatenate([y1, y2, y3, y4], axis=-1)


class GoogLeNet(ModelBase):
    batch_size = 32
    epochs = 70
    n_subb = 1
    learning_rate = 0.01
    momentum = 0.9
    weight_decay = 0.0002
    lr_adjust_epochs = (20, 40, 60)
    n_class = 1000
    aux_weight = 0.3

    def build_model(self) -> None:
        cd = self.config.get("compute_dtype", jnp.bfloat16)
        nc = self.config.get("n_class", self.n_class)
        self._nc = nc
        k = dict(w_init="he", compute_dtype=cd)

        self.stem = L.Sequential([
            L.Conv(3, 64, 7, stride=2, padding=3, name="conv1", **k),
            L.Pool(3, 2, mode="max", padding="SAME", name="pool1"),
            L.LRN(name="lrn1"),
            L.Conv(64, 64, 1, name="conv2r", **k),
            L.Conv(64, 192, 3, padding="SAME", name="conv2", **k),
            L.LRN(name="lrn2"),
            L.Pool(3, 2, mode="max", padding="SAME", name="pool2"),
        ])
        self.stage3 = L.Sequential([
            Inception(192, 64, 96, 128, 16, 32, 32, cd, "3a"),
            Inception(256, 128, 128, 192, 32, 96, 64, cd, "3b"),
            L.Pool(3, 2, mode="max", padding="SAME", name="pool3"),
        ])
        self.stage4a = L.Sequential([
            Inception(480, 192, 96, 208, 16, 48, 64, cd, "4a")])
        self.stage4bcd = L.Sequential([
            Inception(512, 160, 112, 224, 24, 64, 64, cd, "4b"),
            Inception(512, 128, 128, 256, 24, 64, 64, cd, "4c"),
            Inception(512, 112, 144, 288, 32, 64, 64, cd, "4d"),
        ])
        self.stage4e = L.Sequential([
            Inception(528, 256, 160, 320, 32, 128, 128, cd, "4e"),
            L.Pool(3, 2, mode="max", padding="SAME", name="pool4"),
        ])
        self.stage5 = L.Sequential([
            Inception(832, 256, 160, 320, 32, 128, 128, cd, "5a"),
            Inception(832, 384, 192, 384, 48, 128, 128, cd, "5b"),
        ])
        self.head = L.Sequential([
            L.Dropout(0.4, name="drop"),
            L.FC(1024, nc, w_init=("normal", 0.01), activation=None,
                 compute_dtype=cd, name="softmax"),
        ])

        # the aux taps sit after four stride-2 stages (conv1, pool1, pool2,
        # pool3 — all ceil-mode), so their spatial side is crop/16 rounded
        # up, and the aux 5×5/3 VALID avg-pool shrinks it again; 224 → 14 → 4
        crop = int(self.config.get("crop_size", 224))
        s = crop
        for _ in range(4):
            s = (s + 1) // 2
        aux_sp = (s - 5) // 3 + 1
        assert aux_sp >= 1, f"crop {crop} too small for the aux heads"

        def aux_head(in_ch, name):
            # avgpool 5×5/3 → 1×1 conv 128 → FC 1024 → dropout .7 → FC nc
            return L.Sequential([
                L.Pool(5, 3, mode="avg", name=f"{name}_pool"),
                L.Conv(in_ch, 128, 1, name=f"{name}_conv", **k),
                L.Flatten(name=f"{name}_flat"),
                L.FC(128 * aux_sp * aux_sp, 1024, w_init="he",
                     compute_dtype=cd, name=f"{name}_fc"),
                L.Dropout(0.7, name=f"{name}_drop"),
                L.FC(1024, nc, w_init=("normal", 0.01), activation=None,
                     compute_dtype=cd, name=f"{name}_out"),
            ])

        self.aux1 = aux_head(512, "aux1")   # taps output of 4a
        self.aux2 = aux_head(528, "aux2")   # taps output of 4d
        self._parts = {
            "stem": self.stem, "stage3": self.stage3,
            "stage4a": self.stage4a,
            "stage4bcd": self.stage4bcd, "stage4e": self.stage4e,
            "stage5": self.stage5, "head": self.head,
            "aux1": self.aux1, "aux2": self.aux2,
        }
        self.data = ImageNet_data(self.config, self.batch_size, crop=224)

    # -- composite-model hooks --------------------------------------------

    def init_params(self, key):
        keys = jax.random.split(key, len(self._parts))
        return {name: part.init(k)
                for (name, part), k in zip(self._parts.items(), keys)}

    def init_bn_state(self):
        return {}

    def _trunk(self, params, x, train, rng):
        def r():
            nonlocal rng
            if rng is None:
                return None
            rng, sub = jax.random.split(rng)
            return sub

        x, _ = self.stem.apply(params["stem"], x, train=train, rng=r())
        x, _ = self.stage3.apply(params["stage3"], x, train=train, rng=r())
        x, _ = self.stage4a.apply(params["stage4a"], x, train=train, rng=r())
        t4a = x
        x, _ = self.stage4bcd.apply(params["stage4bcd"], x, train=train,
                                    rng=r())
        t4d = x
        x, _ = self.stage4e.apply(params["stage4e"], x, train=train, rng=r())
        x, _ = self.stage5.apply(params["stage5"], x, train=train, rng=r())
        x = jnp.mean(x, axis=(1, 2))            # global average pool 7×7
        logits, _ = self.head.apply(params["head"], x, train=train, rng=r())
        return logits, t4a, t4d, rng

    def apply_model(self, params, x, *, train, rng, state):
        logits, _, _, _ = self._trunk(params, x, train, rng)
        return logits, state

    def loss_and_metrics(self, params, bn_state, batch, rng, train):
        logits, t4a, t4d, rng = self._trunk(
            params, self.stage_input(batch["x"]), train, rng)
        ls = self._label_smoothing(train)
        cost = L.softmax_cross_entropy(logits, batch["y"], ls)
        if train:
            r1, r2 = (jax.random.split(rng) if rng is not None
                      else (None, None))
            a1, _ = self.aux1.apply(params["aux1"], t4a, train=True, rng=r1)
            a2, _ = self.aux2.apply(params["aux2"], t4d, train=True, rng=r2)
            cost = cost + self.aux_weight * (
                L.softmax_cross_entropy(a1, batch["y"], ls) +
                L.softmax_cross_entropy(a2, batch["y"], ls))
        err = L.errors(logits, batch["y"])
        return cost, (err, bn_state)
