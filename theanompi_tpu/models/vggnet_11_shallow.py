"""VGG-11 "shallow" — reference-path alias module.

Reference: ``theanompi/models/vggnet_11_shallow.py`` (SURVEY.md §2.7).  The
model itself lives in :mod:`theanompi_tpu.models.vggnet_16` (the two VGG
configurations share the stack builder); this module preserves the
reference's import path so dotted-path configs
(``theanompi_tpu.models.vggnet_11_shallow:VGGNet_11_shallow``) run
unmodified.
"""

from .vggnet_16 import VGGNet_11_shallow

__all__ = ["VGGNet_11_shallow"]
