"""CIFAR-10 CNN — the smoke-test model.

Reference: ``theanompi/models/cifar10.py`` (SURVEY.md §2.7) — the
``Cifar10_model`` used in the README quick-start and every rule's session
test.  Same role here: a small conv net following the full model contract,
fast enough to train on an 8-device CPU mesh in CI.

Architecture (conv-pool ×3 + FC, ReLU, momentum SGD with step decay): kept in
the reference's AlexNet-era style; hyperparameters live as class attributes —
the module-level-dict config system of the reference (SURVEY.md §5.6).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import layers as L
from .data.cifar10 import Cifar10_data
from .model_base import ModelBase


class Cifar10_model(ModelBase):
    batch_size = 128
    epochs = 30
    n_subb = 1
    learning_rate = 0.05
    momentum = 0.9
    weight_decay = 0.0001
    lr_adjust_epochs = (20, 25)

    def build_model(self) -> None:
        cd = self.config.get("compute_dtype", jnp.bfloat16)
        self.seq = L.Sequential([
            L.Conv(3, 64, 5, padding="SAME", w_init="he",
                   compute_dtype=cd, name="conv1"),
            L.Pool(3, 2, mode="max", name="pool1"),
            L.Conv(64, 128, 5, padding="SAME", w_init="he",
                   compute_dtype=cd, name="conv2"),
            L.Pool(3, 2, mode="max", name="pool2"),
            L.Conv(128, 128, 3, padding="SAME", w_init="he",
                   compute_dtype=cd, name="conv3"),
            L.Pool(3, 2, mode="max", name="pool3"),
            L.Flatten(),
            L.FC(128 * 3 * 3, 256, w_init="he", compute_dtype=cd, name="fc1"),
            L.Dropout(0.5, name="drop1"),
            L.FC(256, 10, w_init=("normal", 0.01), activation=None,
                 compute_dtype=cd, name="softmax"),
        ])
        self.data = Cifar10_data(self.config, self.batch_size)
