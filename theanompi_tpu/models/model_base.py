"""The duck-typed model contract.

Theano-MPI's actual public API is its model contract (SURVEY.md §2.5): a
model exposes ``params``, ``data``, ``compile_iter_fns()``,
``train_iter(count, recorder)``, ``val_iter(count, recorder)``,
``adjust_hyperp(epoch)``, ``scale_lr(size)``, ``epochs``, ``n_subb``.  The
worker loop drives any object with that shape.  :class:`ModelBase` implements
the contract once over the TPU step machinery; concrete models
(``cifar10.py``, ``alex_net.py``, ...) only define their layer stack, data
object, and hyperparameters — mirroring how reference model files were layer
lists plus a module-level hyperparameter dict.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..jax_compat import shard_map
from ..parallel import steps
from ..parallel.mesh import WORKER_AXIS, worker_mesh
from ..utils import checkpoint as ckpt_lib
from ..utils import helper_funcs
from ..utils import numerics as numerics_lib
from ..utils.opt import get_optimizer
from . import layers as L


class ModelBase:
    """Implements the reference model contract over compiled SPMD steps."""

    # hyperparameter defaults; concrete models override (these mirror the
    # module-level dicts that served as the reference's config system, §5.6)
    batch_size: int = 128          # per-worker, as in the reference
    epochs: int = 60
    n_subb: int = 1                # sub-batches per comm step (grad accum)
    steps_per_call: int = 1        # full steps per dispatch (any rule —
                                   # cadenced exchanges fuse into the scan)
    learning_rate: float = 0.01
    momentum: float = 0.9
    weight_decay: float = 0.0001
    optimizer: str = "momentum"
    lr_adjust_epochs: tuple = ()   # epochs at which lr /= 10 (step schedule)
    seed: int = 42

    def __init__(self, config: Optional[dict] = None):
        self.config = dict(config or {})
        self.verbose = self.config.get("verbose", True)
        self.rank = self.config.get("rank", 0)
        self.size = self.config.get("size", 1)
        self.mesh = self.config.get("mesh")
        if self.mesh is None:
            self.mesh = worker_mesh(self.config.get("n_workers"),
                                    tp=int(self.config.get("tp", 1)),
                                    pp=int(self.config.get("pp", 1)),
                                    sp=int(self.config.get("sp", 1)))
            self.size = self.mesh.shape[WORKER_AXIS]
            # build_model()'s data object reads size from config — keep it
            # coherent when the model is constructed standalone (no Worker).
            self.config.setdefault("rank", self.rank)
            self.config["size"] = self.size
        for k in ("batch_size", "epochs", "n_subb", "learning_rate", "seed",
                  "optimizer", "momentum", "weight_decay", "steps_per_call"):
            if k in self.config:
                setattr(self, k, self.config[k])
        self.seed = int(self.config.get("seed", self.seed))
        self.current_lr = float(self.learning_rate)

        self.seq: L.Sequential = None
        self.data = None
        self.build_model()            # subclass hook: set self.seq, self.data
        if self.config.get("para_load", False) and self.data is not None:
            # reference's para_load=True flag → background parallel loader.
            # The producer thread stages batches onto the mesh itself
            # (device_put_fn), double-buffered — the TPU analogue of the
            # reference's loader child writing into the trainer's GPU buffer
            # via CUDA IPC: train_iter consumes device-resident batches and
            # the host→device copy overlaps compute.
            from .data.prefetch import PrefetchLoader
            # steps_per_call > 1 goes WINDOW-granular instead of staging
            # per batch: compile_iter_fns wires set_window so the producer
            # stacks+stages whole spc windows off the hot path (avoids a
            # stage-then-restack double copy; docs/design.md §9)
            put = None if int(self.steps_per_call) > 1 \
                else (lambda b: steps.put_batch(self.mesh, b,
                                                self.batch_spec()))
            # para_load_workers > 1: pooled materialization for file-based
            # data (plans stay sequential — bit-identical stream)
            self.data = PrefetchLoader(
                self.data, device_put_fn=put,
                n_workers=int(self.config.get("para_load_workers", 4)))

        key = jax.random.key(self.seed)
        self.params = self.init_params(key)
        self.bn_state = self.init_bn_state()
        self.opt = get_optimizer(self.optimizer, mu=self.momentum,
                                 weight_decay=self.weight_decay) \
            if self.optimizer in ("momentum", "nesterov") \
            else get_optimizer(self.optimizer, weight_decay=self.weight_decay)
        if self.config.get("ema_decay"):
            # EMA shadow params (utils/opt.py ema_wrap); validation and
            # generate() read the shadow.  Composes with tensor/pipeline
            # param specs (the shadow is laid out exactly like the params —
            # steps.state_partition_specs) and sits INSIDE the ZeRO wrapper
            # below: under zero_opt the shadow then tracks each worker's
            # parameter CHUNK — EMA memory shards with the optimizer state,
            # and the full shadow is assembled only at read time.
            from ..utils.opt import ema_wrap
            self.opt = ema_wrap(self.opt, float(self.config["ema_decay"]))
        # the replicated-layout optimizer, BEFORE any chunking wrapper:
        # devprof.update_state_report eval_shapes it to price the
        # replicated-equivalent update plane (the EMA shadow, when on, is
        # honestly part of that plane, so the capture sits after ema_wrap)
        self._replicated_opt = self.opt
        self._zero_layout = None
        if self.config.get("zero_opt", False):
            # ZeRO-1 (parallel/zero.py): optimizer state sharded over the
            # workers axis — per-chip optimizer memory /N, bit-equal updates.
            # Under tensor/pipeline specs the per-device params are already
            # the LOCAL shard: chunk the local flat layout and hand init the
            # model-group shard count so the host template is global-shaped
            # (one chunk per model-group rank, P(workers, <model axes>)).
            assert not getattr(self, "gates_opt_state_by_path", False), (
                "zero_opt flattens the optimizer state into per-worker "
                "chunks, losing the param paths — models that gate "
                "optimizer-state subtrees by path (the GANs' n_critic>1 "
                "cadence) cannot compose with it")
            from ..parallel.zero import zero1
            pspecs = self.param_specs()
            if pspecs is None:
                template, shards, maxes = self.params, 1, ()
            else:
                template = steps.local_param_template(self.params, pspecs,
                                                      self.mesh)
                maxes = tuple(a for a in self.mesh.axis_names
                              if a != WORKER_AXIS)
                shards = 1
                for a in maxes:
                    shards *= self.mesh.shape[a]
            self.opt = zero1(self.opt, self.mesh.shape[WORKER_AXIS],
                             template, model_shards=shards,
                             pspecs=pspecs, model_axes=maxes)
            # layout facts for worker-count-portable resume (load() refit)
            self._zero_layout = {
                "n": self.mesh.shape[WORKER_AXIS], "shards": shards,
                "local_total": helper_funcs.tree_size(template)}

        self._ushard_plan = None
        if self.config.get("update_sharding", False) and \
                str(self.config.get("rule", "bsp")).lower() == "bsp":
            # Leaf-wise update-plane sharding (parallel/update_sharding.py,
            # docs/design.md §23): optimizer moments chunk per leaf over
            # the workers axis, one fused allgather rebuilds full params
            # inside the step.  Wrapped HERE (not at compile time) so the
            # prewarm venue's `_state_avals` → `self.opt.init` sees the
            # chunked shapes and every venue requests byte-identical
            # programs.  Under a non-BSP `rule` only the exchanger's
            # shardable extra (EASGD/ASGD centers) shards — async rules'
            # moments diverge per worker and must stay local.
            assert not self.config.get("zero_opt", False), (
                "update_sharding IS the generalization of zero_opt "
                "(leaf-wise chunks vs one flat chunk) — enable one, not "
                "both")
            assert not self.config.get("fsdp", False), (
                "fsdp=true already holds optimizer state on the parameter "
                "chunk — drop update_sharding")
            assert not self.config.get("ema_decay"), (
                "update_sharding does not yet carry the EMA shadow's "
                "chunked read path (zero_opt does) — use zero_opt with "
                "ema_decay, or drop one")
            assert not getattr(self, "gates_opt_state_by_path", False), (
                "update_sharding chunks optimizer-state leaves — models "
                "that gate optimizer-state subtrees by path (the GANs' "
                "n_critic>1 cadence) cannot compose with it")
            assert self.param_specs() is None and all(
                self.mesh.shape[a] == 1 for a in self.mesh.axis_names
                if a != WORKER_AXIS), (
                "update_sharding currently supports pure data-parallel "
                "layouts — tensor/pipeline models use zero_opt (the flat "
                "configuration carries model_shards/pspecs)")
            n_w = self.mesh.shape[WORKER_AXIS]
            if n_w > 1:
                from ..parallel import update_sharding
                plan = update_sharding.plan_tree(
                    self.params, n_w,
                    min_bytes=int(self.config.get(
                        "ushard_min_bytes",
                        update_sharding.DEFAULT_MIN_BYTES)))
                if plan.any_sharded:
                    self._ushard_plan = plan
                    self.opt = update_sharding.shard_opt(self.opt, plan)

        self._fsdp = None
        if self.config.get("fsdp", False):
            # FSDP / ZeRO-3 (parallel/fsdp.py): params themselves shard over
            # the workers axis as flat [chunk] shards; the step gathers the
            # full tree transiently and the AD transpose reduce-scatters the
            # grads.  The optimizer (incl. an EMA wrapper above) operates on
            # the chunk natively, so zero_opt is subsumed, not composed.
            assert not self.config.get("zero_opt", False), (
                "fsdp=true subsumes zero_opt (the optimizer state already "
                "lives on the parameter chunk) — drop zero_opt")
            assert self.param_specs() is None, (
                "fsdp shards params over the workers axis; tensor/pipeline "
                "models already shard them over the model axes — unsupported")
            assert all(self.mesh.shape[a] == 1 for a in self.mesh.axis_names
                       if a != WORKER_AXIS), (
                "fsdp currently supports pure data-parallel meshes")
            assert not getattr(self, "gates_opt_state_by_path", False) and \
                type(self).postprocess_grads is ModelBase.postprocess_grads \
                and type(self).postprocess_update is \
                ModelBase.postprocess_update, (
                "fsdp flattens params into per-worker chunks — models that "
                "transform grads/updates tree-wise (the GANs) cannot compose")
            from ..parallel.fsdp import FsdpLayout
            self._fsdp = FsdpLayout(self.params,
                                    self.mesh.shape[WORKER_AXIS])

        self.step_state: Optional[Dict[str, Any]] = None
        self._state_specs = None
        self.train_fn = None
        self.val_fn = None
        self.exchanger = None
        self._ckpt_thread = None
        self._exch_key = jax.random.key(self.seed + 1)
        self._val_params_boxed = None
        self._val_bn_boxed = None
        self.current_info: Dict[str, Any] = {}

    # -- subclass hooks ----------------------------------------------------

    def build_model(self) -> None:
        raise NotImplementedError

    # Simple chain models set self.seq in build_model(); composite models
    # (GoogLeNet's aux heads, ResNet's residual graph) override these three
    # hooks instead and may leave self.seq unset.
    def init_params(self, key):
        assert self.seq is not None, "build_model() must set self.seq or " \
                                     "override init_params/apply_model"
        return self.seq.init(key)

    def init_bn_state(self):
        return self.seq.init_state() if self.seq is not None else {}

    def apply_model(self, params, x, *, train, rng, state):
        """Returns (logits, new_state)."""
        return self.seq.apply(params, x, train=train, rng=rng, state=state)

    def _label_smoothing(self, train: bool) -> float:
        """The smoothing ε the loss should use — the config knob applies to
        the TRAINING loss only (validation scores the clean NLL)."""
        return float(self.config.get("label_smoothing", 0.0)) if train \
            else 0.0

    def _u8_input_mean(self):
        """Constant for the u8-wire input path: the mean image's
        center-crop window (or the scalar mean).  The HOST numpy value is
        cached per model; the jnp conversion happens per call so each
        trace owns its constant — caching the jnp array on ``self`` leaks
        a tracer on jax versions that stage constant creation (first
        touched inside the train trace, reused by the val trace →
        UnexpectedTracerError; this was the u8-wire smoke seed failure).
        NOTE: for shared-window crops with a full mean image this deviates
        from the f32 pass's window-exact mean (see data/imagenet.py)."""
        m = getattr(self, "__u8_mean_host", None)
        if m is None:
            d = getattr(self, "data", None)
            mi = getattr(d, "img_mean", np.float32(122.0))
            if isinstance(mi, np.ndarray) and mi.ndim == 3:
                c = int(getattr(d, "crop", mi.shape[0]))
                cy, cx = (mi.shape[0] - c) // 2, (mi.shape[1] - c) // 2
                m = np.asarray(mi[cy:cy + c, cx:cx + c, :], np.float32)
            else:
                m = np.float32(mi)
            setattr(self, "__u8_mean_host", m)
        return jnp.asarray(m, jnp.float32)

    def stage_input(self, x):
        """Shared input staging for EVERY loss/metrics path (models with
        custom heads call this too): u8-wire batches (data/imagenet.py
        aug_wire_u8) are cast and mean-subtracted on device — the same
        float32 arithmetic as the host fused pass, fused into the first
        conv by XLA.  Float inputs pass through untouched."""
        if x.dtype == jnp.uint8:
            return x.astype(jnp.float32) - self._u8_input_mean()
        return x

    def loss_and_metrics(self, params, bn_state, batch, rng, train):
        """Default head: softmax cross-entropy + top-1 error."""
        logits, new_bn = self.apply_model(params, self.stage_input(batch["x"]),
                                          train=train, rng=rng,
                                          state=bn_state)
        cost = L.softmax_cross_entropy(logits, batch["y"],
                                       self._label_smoothing(train))
        err = L.errors(logits, batch["y"])
        return cost, (err, new_bn)

    def param_specs(self):
        """Per-leaf PartitionSpecs over the ``'model'`` mesh axis for tensor
        -parallel models (``parallel/tp.py``), or None for pure data
        parallelism (the whole CNN zoo — the reference's only mode)."""
        return None

    def batch_spec(self):
        """PartitionSpec for batch leaves, or None for the default
        ``P(workers)`` row split.  Sequence-parallel models
        (``parallel/sp.py``) also shard the time dim."""
        return None

    def postprocess_grads(self, grads, count):
        """Traced hook before the exchange: transform gradients."""
        return grads

    def postprocess_update(self, old_params, old_opt, new_params, new_opt,
                           count):
        """Traced hook after the optimizer step: gate or project the update.
        GAN models freeze the generator (params AND optimizer state) off the
        critic cadence; WGAN clips critic weights.  Must return
        ``(params, opt_state)``."""
        return new_params, new_opt

    def val_metrics(self, params, bn_state, batch):
        logits, _ = self.apply_model(params, self.stage_input(batch["x"]),
                                     train=False, rng=None, state=bn_state)
        cost = L.softmax_cross_entropy(logits, batch["y"])
        return cost, (L.errors(logits, batch["y"]),
                      L.errors_top_x(logits, batch["y"], 5))

    # -- contract: compile -------------------------------------------------

    def compile_iter_fns(self, exchanger=None) -> None:
        """≙ reference ``model.compile_iter_fns()`` → ``theano.function``;
        here: jit the SPMD train/val steps and box the state onto the mesh."""
        from ..parallel.exchanger import BSP_Exchanger
        self.exchanger = exchanger or BSP_Exchanger(self.config)
        if self._fsdp is not None:
            # the gradient reduction is the all_gather's AD transpose — a
            # plain fp32 sum.  Any OTHER configured strategy (wire casts,
            # compression) would be silently ignored: the exchanger's
            # strategy hook never runs on the fsdp path.
            assert (isinstance(self.exchanger, BSP_Exchanger)
                    and self.exchanger.mode == "grads"
                    and self.exchanger.strategy.name == "allreduce"), (
                "fsdp=true fuses the exchange as all_gather/psum_scatter — "
                "only BSP grads mode with the exact 'allreduce' strategy "
                f"composes; got {type(self.exchanger).__name__} mode="
                f"{getattr(self.exchanger, 'mode', '?')} strategy="
                f"{getattr(getattr(self.exchanger, 'strategy', None), 'name', '?')}")
            # same silently-ignored class of knob: the bucketed wire
            # (parallel/buckets.py) lives in the strategy/exchange_body
            # hooks the fsdp path never runs — a bucketed-looking row
            # measuring a monolithic wire would corrupt the r9 analysis
            assert int(self.config.get("bucket_bytes", 0) or 0) == 0, (
                "fsdp=true has no exchanger wire to bucket (grads arrive "
                "via the all_gather transpose) — drop bucket_bytes")
        if self.config.get("zero_opt", False) or self.config.get("ema_decay") \
                or self._ushard_plan is not None:
            # ZeRO-1 / leaf-wise update sharding assume every worker sees
            # the SAME reduced gradient and holds identical params — true
            # only under BSP grads mode with a real collective; params mode
            # / the 'none' strategy would slice UN-reduced per-worker grads
            # and train silently wrong (and the EMA shadow would track
            # per-worker divergent params), and async rules' workers would
            # never update chunks other ranks own (their canonical/center
            # validation also never reads a shadow).  A sharded-opt model
            # handed a non-BSP exchanger means the config `rule` gate in
            # __init__ disagrees with the exchanger actually compiled —
            # set config['rule'] to the rule in use.
            which = "zero_opt" if self.config.get("zero_opt") else (
                "ema_decay" if self.config.get("ema_decay")
                else "update_sharding")
            assert (isinstance(self.exchanger, BSP_Exchanger)
                    and self.exchanger.mode == "grads"
                    and self.exchanger.strategy.name != "none"), (
                f"{which} requires BSP grads mode with a gradient "
                "collective (identical grads across workers); got "
                f"{type(self.exchanger).__name__} mode="
                f"{getattr(self.exchanger, 'mode', '?')} strategy="
                f"{getattr(getattr(self.exchanger, 'strategy', None), 'name', '?')}")
        self.exchanger.prepare(self.mesh, self)
        n = self.mesh.shape[WORKER_AXIS]

        extra = self.exchanger.extra_state_template()
        if self._fsdp is not None:
            # optimizer state lives on THIS worker's flat chunk (identical
            # zeros template per worker — broadcast replicates it; the real
            # per-worker chunks land below via place_boxed)
            opt_state = self.opt.init(
                jnp.zeros((self._fsdp.chunk,), jnp.float32))
            params_init = np.zeros((self._fsdp.chunk,), np.float32)
        else:
            opt_state = self.opt.init(self.params)
            params_init = self.params
        unboxed = {"params": params_init, "opt_state": opt_state,
                   "bn_state": self.bn_state, "extra": extra}
        self._state_specs = None if self.param_specs() is None else \
            steps.state_partition_specs(self, self.exchanger)
        self.step_state = {
            k: steps.replicate_tree(
                v, n, self.mesh,
                None if self._state_specs is None else self._state_specs[k])
            for k, v in unboxed.items()}
        if self._fsdp is not None:
            self.step_state["params"] = steps.place_boxed(
                self._fsdp.chunk_host(self.params), self.mesh)
        if getattr(self.exchanger, "update_plan", lambda: None)() is not None:
            # plan-sharded extra (EASGD/ASGD centers under update_sharding):
            # each worker's init chunk is a DIFFERENT window of the center —
            # replicate_tree above broadcast the zero template; overwrite
            # with the genuinely partitioned rows
            self.step_state["extra"] = steps.place_boxed(
                self.exchanger.extra_host_boxed(n), self.mesh)
        spc = int(self.steps_per_call)
        # multi-step dispatch fuses the exchange cadence INTO the scanned
        # step for every rule with a post-step collective (EASGD/ASGD/
        # GoSGD, BSP params mode — build_train_step wraps exchange_body in
        # lax.cond on the in-scan count); BSP grads mode has no post-step
        # hook to begin with.  The worker-loop Python exchange() must then
        # not run the collective a second time: exchange() no-ops while
        # exchanger.fused is set.  Assigned UNCONDITIONALLY so a recompile
        # back to spc=1 clears a stale flag (which would silently disable
        # the rule's exchanges outright).
        self.exchanger.fused = spc > 1 and self.exchanger.has_exchange()
        if spc > 1:
            # fail-loud guard for out-of-tree exchangers still on the
            # pre-round-6 pattern (jitting _exchange_fn directly in
            # prepare() without declaring has_exchange): their cadence
            # would neither fuse nor fire per-step from the spc-strided
            # worker loop — silently undersampled exchanges
            assert not (self.exchanger._exchange_fn is not None
                        and not self.exchanger.has_exchange()), (
                f"{type(self.exchanger).__name__} builds _exchange_fn but "
                "has_exchange() is False — steps_per_call > 1 fuses the "
                "cadence via exchange_body/has_exchange (see "
                "Exchanger._build_exchange_fn); declare them or keep "
                "steps_per_call=1")
            if self.data is not None:
                assert spc <= self.data.n_batch_train, (
                    f"steps_per_call={spc} exceeds n_batch_train="
                    f"{self.data.n_batch_train}: every epoch would train "
                    f"zero steps")
        if hasattr(self.data, "set_window"):
            # para_load + steps_per_call > 1: window-granular staging —
            # the PrefetchLoader producer assembles whole spc windows (k
            # sequential draws, host stack, steps.stage_window) so
            # train_iter dequeues mesh-resident dispatch inputs and the
            # recorder's `stage` bucket goes to ~0.  Re-wired on every
            # compile so a recompile back to spc=1 reverts to per-batch
            # production (a stale window setting would wedge the queue
            # granularity).  para_load_window=false opts out (A/B).
            # The fresh stage_fn closure makes set_window restart a live
            # producer every recompile — deliberate: the closure may bind
            # a new mesh/spec, and queued windows staged under the old
            # one must not survive (the loader rewinds, nothing is lost).
            if spc > 1 and self.config.get("para_load_window", True):
                self.data.set_window(
                    spc, lambda w: steps.stage_window(self.mesh, w,
                                                      self.batch_spec()))
            else:
                self.data.set_window(0)
        self.train_fn = steps.build_train_step(self.mesh, self,
                                               self.exchanger, n_steps=spc)
        # numerics health plane (§25): the dispatch returns a 4th aux
        # output exactly when the build sampled one (same gate as
        # steps.graph_plan — fsdp has no params-shaped replica view)
        self._numerics_on = numerics_lib.enabled(self.config) \
            and self._fsdp is None
        self.numerics_aux = None
        self.val_fn = steps.build_val_step(self.mesh, self)
        self._step_rng = jax.random.key(self.seed + 2)
        # Persistent AOT executable cache (utils/compile_cache.py): when a
        # cache dir is configured (config `compile_cache` or the
        # THEANOMPI_COMPILE_CACHE env var), every compile surface switches
        # from lazy first-call jit to explicit lower → get_or_compile —
        # a warm cache turns minutes of XLA compile into seconds of
        # deserialize (wedge-recovery restarts, checkpoint resume, the
        # prewarm-then-measure hardware-window workflow).  Unconfigured,
        # behavior is the pre-cache lazy jit, bit for bit.
        self._aot_from_cache()

    # -- AOT executable cache ---------------------------------------------

    _peek_aval_cache = None

    def _peek_batch_aval(self, val: bool = False):
        """Shape/dtype of one batch WITHOUT disturbing the stream: peek the
        underlying source (bypassing a PrefetchLoader's queue) and rewind
        its cursor — the same round-trip checkpoint resume relies on.

        Memoized per (train/val): the peek-and-rewind touches the wrapped
        source directly, which is only safe while no PrefetchLoader
        producer thread is drawing from it — true on the FIRST
        compile_iter_fns (it precedes the first shuffle_data in every
        venue), not on a mid-run recompile, where an unsynchronized
        set_cursor would yank the live producer's cursor/augmentation RNG
        backward.  Batch shapes are fixed for the life of the data object,
        so recompiles reuse the first compile's avals instead of peeking."""
        if self._peek_aval_cache is None:
            self._peek_aval_cache = {}
        if val not in self._peek_aval_cache:
            inner = getattr(self.data, "_data", None) or self.data
            cursor = inner.get_cursor() if hasattr(inner, "get_cursor") \
                else None
            batch = inner.next_val_batch(0) if val \
                else inner.next_train_batch(0)
            if cursor is not None and hasattr(inner, "set_cursor"):
                inner.set_cursor(cursor)
            self._peek_aval_cache[val] = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                               np.asarray(x).dtype), batch)
        return self._peek_aval_cache[val]

    def _sds_like(self, tree):
        """Abstract avals mirroring a placed pytree, shardings included —
        what `.lower()` needs so the cached executable's expected input
        shardings match the live arrays exactly."""
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), tree)

    def _state_avals(self, exchanger=None):
        """Boxed-state avals: from the live ``step_state`` when placed, else
        from host templates (the off-line topology-AOT venue of
        ``scripts/prewarm_cache.py``, whose mesh is non-addressable)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self.step_state is not None:
            return self._sds_like(self.step_state)
        exchanger = exchanger or self.exchanger
        n = self.mesh.shape[WORKER_AXIS]
        if self._fsdp is not None:
            chunk = jax.ShapeDtypeStruct((self._fsdp.chunk,), jnp.float32)
            unboxed = {"params": chunk,
                       "opt_state": jax.eval_shape(self.opt.init, chunk),
                       "bn_state": self.bn_state,
                       "extra": exchanger.extra_state_template()}
        else:
            unboxed = {"params": self.params,
                       "opt_state": jax.eval_shape(self.opt.init,
                                                   self.params),
                       "bn_state": self.bn_state,
                       "extra": exchanger.extra_state_template()}
        specs = steps.state_partition_specs(self, exchanger) \
            if self.param_specs() is not None \
            else {k: P(WORKER_AXIS) for k in unboxed}

        def mk(x, s):
            shape = tuple(getattr(x, "shape", None) if hasattr(x, "shape")
                          else np.shape(x))
            dtype = getattr(x, "dtype", None) or np.asarray(x).dtype
            return jax.ShapeDtypeStruct(
                (n,) + shape, dtype, sharding=NamedSharding(self.mesh, s))

        out = {}
        for k, v in unboxed.items():
            s = specs[k]
            if steps._is_spec(s):
                out[k] = jax.tree.map(lambda x: mk(x, s), v)
            else:
                out[k] = jax.tree.map(mk, v, s, is_leaf=lambda x: x is None)
        return out

    def _train_input_avals(self, spc: int, exchanger=None):
        """The abstract input signature of one train dispatch at the given
        ``steps_per_call`` — the lowering avals shared by compile_iter_fns,
        bench.py's flop-count path, and scripts/prewarm_cache.py, so every
        venue requests byte-identical programs from the executable cache."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        peek = self._peek_batch_aval(val=False)
        bs = self.batch_spec()
        base = tuple(bs) if bs is not None else (WORKER_AXIS,)
        spec = P(*base) if spc == 1 else P(None, *base)
        sh = NamedSharding(self.mesh, spec)
        batch_avals = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape if spc == 1 else (spc,) + a.shape, a.dtype,
                sharding=sh), peek)
        return (self._state_avals(exchanger), batch_avals,
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jax.random.key(0).dtype),
                jax.ShapeDtypeStruct((), jnp.int32))

    def aot_train_program(self, cache, spc: Optional[int] = None,
                          exchanger=None, load: bool = True):
        """The ONE lower → ``get_or_compile`` sequence for THE train
        program at a given ``steps_per_call`` — shared by
        ``compile_iter_fns`` (below), bench.py's spc=1 flop-count path,
        and both venues of ``scripts/prewarm_cache.py``.  The cache key is
        content-addressed, so a drifted label/avals/extras composition in
        any one venue silently forfeits the prewarm hit this subsystem
        exists to guarantee — the composition therefore lives here, once.

        Returns ``(compiled, info)`` as ``get_or_compile`` does
        (``compiled`` is ``None`` on a ``load=False`` hit)."""
        from ..utils import compile_cache
        exchanger = exchanger if exchanger is not None else self.exchanger
        spc = int(self.steps_per_call if spc is None else spc)
        train_fn = steps.build_train_step(self.mesh, self, exchanger,
                                          n_steps=spc)
        if not compile_cache.donated_load_safe(self.mesh):
            # donation-free twin where deserialized aliased execution is
            # untrusted (see compile_cache.donated_load_safe)
            train_fn = jax.jit(train_fn.__wrapped__)
        lowered = train_fn.lower(*self._train_input_avals(spc, exchanger))
        return cache.get_or_compile(
            lowered, label=f"train:{type(self).__name__}:spc{spc}",
            mesh=self.mesh,
            extra=compile_cache.key_extra("train", self, exchanger,
                                          spc=spc), load=load)

    def _aot_from_cache(self) -> None:
        """Explicit lower → ``get_or_compile`` for every compile surface:
        train, val, the standalone exchange collective (unfused runs), and
        the zero-shadow / fsdp-val read paths.  Each surface falls back to
        its plain lazy jit independently on ANY failure — the cache can
        slow nothing down and break nothing."""
        from ..utils import compile_cache
        cache = compile_cache.resolve(self.config)
        self.compile_cache = cache
        self.compile_info: Dict[str, Any] = {
            "cache_dir": cache.cache_dir if cache.enabled else None,
            "train": {"cache": "off", "compile_secs": None}}
        self._train_compiled = None
        if not cache.enabled:
            return
        if jax.process_count() > 1:
            # per-host lowering avals are local shapes; the cached global
            # program would never match — lazy jit handles multi-host
            self.compile_info["note"] = "off (multi-host)"
            return
        spc = int(self.steps_per_call)
        name = type(self).__name__
        # donated programs are cached/loaded only where deserialized
        # aliased execution is trusted (TPU); elsewhere a donation-free
        # twin of the same program is cached — identical math, its own
        # key (see compile_cache.donated_load_safe)
        donate_ok = compile_cache.donated_load_safe(self.mesh)

        def undonated(jit_fn):
            return jit_fn if donate_ok else jax.jit(jit_fn.__wrapped__)

        def attempt(fn_name, build):
            try:
                compiled, info = build()
                self.compile_info[fn_name] = info
                return compiled
            except Exception as e:
                self.compile_info[fn_name] = {"cache": "error",
                                              "error": repr(e)[:300]}
                if self.verbose:
                    print(f"compile cache: {fn_name} AOT failed "
                          f"({repr(e)[:200]}) — lazy jit fallback",
                          flush=True)
                return None

        compiled = attempt("train",
                           lambda: self.aot_train_program(cache, spc=spc))
        if compiled is not None:
            self.train_fn = compiled
            self._train_compiled = compiled

        def build_val():
            n = self.mesh.shape[WORKER_AXIS]
            if self._fsdp is not None:
                # begin_val assembles FULL boxed param trees from the chunks
                from jax.sharding import NamedSharding, PartitionSpec as P
                sh = NamedSharding(self.mesh, P(WORKER_AXIS))
                pav = jax.tree.map(
                    lambda p: jax.ShapeDtypeStruct(
                        (n,) + tuple(np.shape(p)), np.asarray(p).dtype,
                        sharding=sh), self.params)
            else:
                pav = self._sds_like(self.step_state["params"])
            bn_av = self._sds_like(self.step_state["bn_state"])
            batch_av = self._val_batch_avals()
            lowered = self.val_fn.lower(pav, bn_av, batch_av)
            return cache.get_or_compile(
                lowered, label=f"val:{name}", mesh=self.mesh,
                extra=compile_cache.key_extra("val", self, self.exchanger))

        compiled = attempt("val", build_val)
        if compiled is not None:
            self.val_fn = compiled

        exch = self.exchanger
        if exch is not None and getattr(exch, "_exchange_fn", None) \
                is not None and not getattr(exch, "fused", False):
            # the standalone collective the worker loop dispatches between
            # steps (spc=1); fused runs carry the cadence inside the train
            # program and never call it on the hot path
            def build_exchange():
                lowered = undonated(exch._exchange_fn).lower(
                    self._state_avals(),
                    jax.ShapeDtypeStruct((), jax.random.key(0).dtype),
                    jax.ShapeDtypeStruct((), jnp.int32))
                return cache.get_or_compile(
                    lowered, label=f"exchange:{name}", mesh=self.mesh,
                    extra=compile_cache.key_extra("exchange", self, exch))

            compiled = attempt("exchange", build_exchange)
            if compiled is not None:
                exch._exchange_fn = compiled

        if self.config.get("zero_opt", False) and self.config.get(
                "ema_decay"):
            def build_shadow():
                # a prior _aot_from_cache pass stored the AOT Compiled in
                # the memo — reset so _zero_shadow_fn rebuilds the lazy
                # jit wrapper (a Compiled has no .lower) on recompile
                self._zero_shadow_jit = None
                lowered = self._zero_shadow_fn().lower(self._state_avals())
                return cache.get_or_compile(
                    lowered, label=f"zero_shadow:{name}", mesh=self.mesh,
                    extra=compile_cache.key_extra("zero_shadow", self))

            compiled = attempt("zero_shadow", build_shadow)
            if compiled is not None:
                self._zero_shadow_jit = compiled

        if self._fsdp is not None:
            def build_fsdp_val():
                self._fsdp_val_jit = None     # same memo reset as above
                lowered = self._fsdp_val_fn().lower(self._state_avals())
                return cache.get_or_compile(
                    lowered, label=f"fsdp_val:{name}", mesh=self.mesh,
                    extra=compile_cache.key_extra("fsdp_val", self))

            compiled = attempt("fsdp_val", build_fsdp_val)
            if compiled is not None:
                self._fsdp_val_jit = compiled
        secs = [v.get("compile_secs") for v in self.compile_info.values()
                if isinstance(v, dict) and v.get("compile_secs")]
        self.compile_info["total_compile_secs"] = round(sum(secs), 3)

    def _val_batch_avals(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        peek = self._peek_batch_aval(val=True)
        bs = self.batch_spec()
        base = tuple(bs) if bs is not None else (WORKER_AXIS,)
        sh = NamedSharding(self.mesh, P(*base))
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
            peek)

    # -- contract: iteration -----------------------------------------------

    def train_iter(self, count: int, recorder=None) -> None:
        """One dispatch: one training step, or ``steps_per_call`` of them
        (``count`` then names the LAST step of the call).

        Recorder buckets: ``load`` = waiting on the data source (pure
        dequeue wait under para_load), ``stage`` = consumer-thread host
        stack + ``device_put`` (~0 in window mode, where the producer
        staged the window already), ``train`` = the dispatch itself."""
        k = int(self.steps_per_call)
        # window mode (compile_iter_fns wired set_window): the loader
        # dequeues a whole mesh-resident [k, ...] window
        use_window = k > 1 and getattr(self.data, "window", 0) == k
        if recorder:
            recorder.start()
        if k == 1:
            batch = self.data.next_train_batch(count)
        elif use_window:
            batch = self.data.next_train_window(count)
        else:
            batches = [self.data.next_train_batch(count - k + 1 + j)
                       for j in range(k)]
            batch = batches[0]       # row accounting below
        if recorder:
            recorder.end("load")
            recorder.start()
        if k == 1:
            dev_batch = batch if steps.is_device_batch(batch) \
                else steps.put_batch(self.mesh, batch, self.batch_spec())
        else:
            # put_batch_stack passes a pre-staged device window through
            dev_batch = steps.put_batch_stack(
                self.mesh, batch if use_window else batches,
                self.batch_spec())
        if recorder:
            recorder.end("stage")
            recorder.start()
        if getattr(self, "_numerics_on", False):
            # the aux stays device-resident (async dispatch preserved) —
            # the worker materializes it at print cadence, alongside
            # cost/error
            (self.step_state, cost, err,
             self.numerics_aux) = self.train_fn(
                self.step_state, dev_batch, jnp.float32(self.current_lr),
                self._step_rng, jnp.int32(count))
        else:
            self.step_state, cost, err = self.train_fn(
                self.step_state, dev_batch, jnp.float32(self.current_lr),
                self._step_rng, jnp.int32(count))
        cost, err = jnp.mean(cost), jnp.mean(err)
        if recorder:
            recorder.end("train")
        if self.config.get("sync_each_iter", False):
            # Reference-style blocking loop: t_train above is the host
            # dispatch, and the device-bound remainder lands in the ``wait``
            # bucket (≙ the reference's MPI-wait time) — together they sum
            # to wall time per iteration.
            if recorder:
                recorder.start()
            cost, err = float(cost), float(err)
            if recorder:
                recorder.end("wait")
        # else: device scalars flow to the recorder and materialize at print
        # cadence, keeping dispatch asynchronous (device queue stays full).
        if recorder:
            # local rows, consistently: a device-resident (para_load-staged)
            # batch has the GLOBAL shape, a host batch the per-host shape;
            # a device window's leaves are [k, global_rows, ...]
            if use_window:
                n_images = int(batch["y"].shape[0]) * int(batch["y"].shape[1])
            else:
                n_images = int(batch["y"].shape[0]) * k
            if steps.is_device_batch(batch):
                n_images //= jax.process_count()
            recorder.train_error(count, cost, err, n_images)
        self.current_info.update(cost=cost, error=err)

    def begin_val(self) -> None:
        """Snapshot the parameters validation should score: the canonical
        params for async rules (EASGD center, GoSGD consensus), the replica
        set itself for BSP (already identical)."""
        n = self.mesh.shape[WORKER_AXIS]
        if self.exchanger is not None and hasattr(self.exchanger,
                                                  "canonical_params"):
            canon = self.exchanger.canonical_params(self.step_state)
            pspec = None if self._state_specs is None \
                else self._state_specs["params"]
            self._val_params_boxed = steps.replicate_tree(canon, n, self.mesh,
                                                          pspec)
            # Consistent statistics for the consensus model: score the center
            # with the replica-MEAN running stats, not each worker's divergent
            # local ones (the reference's server validated its own center
            # model end to end).  BN state is tiny — host round-trip is fine
            # (tree_to_host: plain device_get can't span hosts).
            bn = steps.tree_to_host(self.step_state["bn_state"])
            bn_mean = jax.tree.map(lambda x: np.mean(np.asarray(x), axis=0),
                                   bn)
            self._val_bn_boxed = steps.replicate_tree(bn_mean, n, self.mesh)
        elif self._fsdp is not None:
            # FSDP: assemble the full tree on-device from the chunks (the
            # EMA shadow's chunks when enabled and seeded, else the live
            # ones) — the val step then sees the standard boxed params.
            self._val_params_boxed = self._fsdp_val_fn()(self.step_state)
            self._val_bn_boxed = self.step_state["bn_state"]
        else:
            # BSP: validate the EMA shadow when enabled, else the replicas
            if self.config.get("ema_decay"):
                # _ema_host_params handles the sharded layout and the
                # unseeded t==0 edge uniformly; re-box with the model's
                # param specs so tensor/pipeline shards land where the
                # val step expects them
                self._val_params_boxed = steps.replicate_tree(
                    self._ema_host_params(), n, self.mesh,
                    None if self._state_specs is None
                    else self._state_specs["params"])
            else:
                self._val_params_boxed = self.step_state["params"]
            self._val_bn_boxed = self.step_state["bn_state"]

    def val_iter(self, count: int, recorder=None) -> None:
        if self._val_params_boxed is None:
            self.begin_val()
        if recorder:
            recorder.start()
        batch = self.data.next_val_batch(count)
        dev_batch = batch if steps.is_device_batch(batch) \
            else steps.put_batch(self.mesh, batch, self.batch_spec())
        cost, err, err5 = self.val_fn(self._val_params_boxed,
                                      self._val_bn_boxed, dev_batch)
        # per-worker metric vectors span hosts — gather, don't device_get
        cost = float(np.mean(np.asarray(steps.tree_to_host(cost))))
        err = float(np.mean(np.asarray(steps.tree_to_host(err))))
        err5 = float(np.mean(np.asarray(steps.tree_to_host(err5))))
        if recorder:
            recorder.end("val")
            recorder.val_error(count, cost, err, err5)

    def end_val(self) -> None:
        self._val_params_boxed = None
        self._val_bn_boxed = None

    # -- contract: hyperparameters ----------------------------------------

    def adjust_hyperp(self, epoch: int) -> None:
        """LR schedule per epoch.  ``lr_schedule='step'`` (default): decay
        ÷10 at the epochs in ``lr_adjust_epochs`` — the schedule style every
        reference zoo model used.  ``'cosine'``: cosine decay from the base
        LR to ``min_lr_frac``·base over ``epochs`` (the modern LM default).

        ``warmup_epochs`` (config, default 0 = reference behavior) ramps the
        LR-scale factor linearly over the first epochs: the reference's
        linear ``scale_lr(size)`` rule applied instantly, which at high
        worker counts diverges before the first decay (Goyal et al.'s
        gradual-warmup fix postdates it)."""
        base = float(self.learning_rate)
        sched = str(self.config.get("lr_schedule", "step"))
        if sched == "cosine":
            import math
            frac = float(self.config.get("min_lr_frac", 0.1))
            total = max(1, int(self.config.get("epochs", self.epochs)))
            t = min(epoch, total) / total
            lr = base * (frac + (1.0 - frac) * 0.5
                         * (1.0 + math.cos(math.pi * t)))
        else:
            if sched != "step":
                raise ValueError(f"unknown lr_schedule {sched!r}; "
                                 f"have 'step', 'cosine'")
            lr = base
            for e in self.lr_adjust_epochs:
                if epoch >= e:
                    lr /= 10.0
        scale = self._lr_scale
        warmup = int(self.config.get("warmup_epochs", 0))
        if warmup > 0 and epoch < warmup and scale > 1.0:
            scale = 1.0 + (scale - 1.0) * (epoch + 1) / warmup
        self.current_lr = lr * scale

    _lr_scale: float = 1.0

    def scale_lr(self, size: int) -> None:
        """Linear LR scaling by worker count (reference ``scale_lr``)."""
        self._lr_scale = float(size)
        self.current_lr = self.current_lr * size

    def canonical_host_params(self):
        """Host copy of the parameters inference/analysis should use: the
        EASGD center / GoSGD consensus via the exchanger's
        ``canonical_params`` (fed only the params+extra it reads — not the
        optimizer state), replica 0 for BSP, or the init params before
        ``compile_iter_fns``."""
        if self.step_state is None:
            return self.params
        if self.exchanger is not None and hasattr(self.exchanger,
                                                  "canonical_params"):
            state = {k: steps.tree_to_host(self.step_state[k])
                     for k in ("params", "extra")}
            return jax.device_get(self.exchanger.canonical_params(state))
        if self.config.get("ema_decay"):
            return self._ema_host_params()
        if self._fsdp is not None:
            return self._fsdp.host_params_from_chunks(np.asarray(
                steps.tree_to_host(self.step_state["params"])))
        return steps.unbox(jax.device_get(
            steps.tree_to_host(self.step_state["params"])))

    def _ema_host_params(self):
        """The EMA shadow as an unboxed host pytree.  Plain EMA stores the
        full tree; under zero_opt the shadow is SHARDED chunks, gathered and
        unflattened here (read-time only).  Before the first update the
        shadow is unseeded (zeros) — fall back to the live params."""
        if self._fsdp is not None:
            st = self.step_state["opt_state"]
            t = int(np.asarray(jax.device_get(
                steps.tree_to_host(st["t"])))[0])
            src = self.step_state["params"] if t == 0 else st["ema"]
            return self._fsdp.host_params_from_chunks(
                np.asarray(steps.tree_to_host(src)))
        st = self.step_state["opt_state"]
        inner = st if "ema" in st else st["opt"]
        t = int(np.asarray(jax.device_get(
            steps.tree_to_host(inner["t"])))[0])
        if t == 0:
            return steps.unbox(jax.device_get(
                steps.tree_to_host(self.step_state["params"])))
        if "ema" in st:
            # plain EMA (incl. tensor/pipeline specs): the boxed shadow is
            # laid out like the params — device_get assembles the global tree
            return steps.unbox(jax.device_get(
                steps.tree_to_host(st["ema"])))
        # zero_opt layout: assemble on DEVICE with the exact gather the
        # update itself uses (all_gather over workers within each
        # model-group rank) — a host reshape of the boxed chunks would
        # misorder the flat layout under tensor/pipeline sharding.
        # tree_to_host, not device_get: model-sharded leaves span
        # non-addressable devices on multi-host
        return jax.device_get(steps.tree_to_host(
            self._zero_shadow_fn()(self.step_state)))

    def _zero_shadow_fn(self):
        if getattr(self, "_zero_shadow_jit", None) is None:
            from jax.sharding import PartitionSpec as P
            pspecs = self.param_specs()
            out_specs = pspecs if pspecs is not None else \
                jax.tree.map(lambda _: P(), self.params)
            state_spec = self._state_specs or {
                k: P(WORKER_AXIS)
                for k in ("params", "opt_state", "bn_state", "extra")}

            maxes = tuple(a for a in self.mesh.axis_names
                          if a != WORKER_AXIS)

            def body(state):
                params = steps.unbox(state["params"])
                shadow = steps.unbox(state["opt_state"])["opt"]["ema"]
                full = jax.lax.all_gather(shadow, WORKER_AXIS, tiled=True)
                tree = helper_funcs.unflatten_like(params, full)
                # the gather makes leaves worker-invariant (and replicated
                # leaves model-invariant) SEMANTICALLY, but the vma tracking
                # can't prove it — anchor each leaf bit-exactly over the
                # axes its out_spec claims replication on
                if pspecs is None:
                    return jax.tree.map(
                        lambda v: steps.anchor_invariant(
                            v, (WORKER_AXIS,) + maxes), tree)
                return jax.tree.map(
                    lambda s, v: steps.anchor_invariant(
                        v, (WORKER_AXIS,) + tuple(
                            a for a in maxes
                            if not steps.spec_mentions(s, (a,)))),
                    pspecs, tree, is_leaf=steps._is_spec)

            self._zero_shadow_jit = jax.jit(shard_map(
                body, mesh=self.mesh, in_specs=(state_spec,),
                out_specs=out_specs))
        return self._zero_shadow_jit

    def _fsdp_val_fn(self):
        """Jitted on-device assemble of the full boxed params from the FSDP
        chunks — the EMA shadow chunks when enabled AND seeded (``t > 0``),
        else the live ones; the two branches share one traced program via a
        ``where`` on the shadow's step counter."""
        if getattr(self, "_fsdp_val_jit", None) is None:
            from jax.sharding import PartitionSpec as P
            fsdp = self._fsdp
            ema = bool(self.config.get("ema_decay"))
            state_spec = {k: P(WORKER_AXIS)
                          for k in ("params", "opt_state", "bn_state",
                                    "extra")}

            def body(state):
                chunk = steps.unbox(state["params"])
                if ema:
                    st = steps.unbox(state["opt_state"])
                    chunk = jnp.where(st["t"] == 0, chunk, st["ema"])
                tree = fsdp.gather_params(chunk)
                return jax.tree.map(lambda v: v[None], tree)   # box/worker

            self._fsdp_val_jit = jax.jit(shard_map(
                body, mesh=self.mesh, in_specs=(state_spec,),
                out_specs=jax.tree.map(lambda _: P(WORKER_AXIS),
                                       self.params)))
        return self._fsdp_val_jit

    def next_exchange_key(self):
        self._exch_key, sub = jax.random.split(self._exch_key)
        return sub

    # -- contract: persistence --------------------------------------------

    def save(self, ckpt_dir: str, epoch: int, count: int = 0) -> str:
        """Checkpoint the FULL boxed state (every worker's replica + the
        exchanger's extras — diverged async-rule replicas and GoSGD α survive
        a resume), both PRNG keys, and the data cursor.  The reference-style
        per-leaf ``.npy`` snapshot holds the canonical params (the EASGD
        center / GoSGD consensus, ≙ the reference saving the server's
        center; replica 0 for BSP, where replicas are identical)."""
        state = {k: steps.tree_to_host(v) for k, v in self.step_state.items()}
        if hasattr(self.exchanger, "canonical_params"):
            # canonical_params is pure tree algebra (unbox / weighted mean) —
            # feed it the GATHERED host state: the device step_state spans
            # non-addressable shards on multi-host
            params_npy = jax.device_get(
                self.exchanger.canonical_params(state))
        elif self.config.get("ema_decay"):
            # the .npy snapshot holds what inference should use — the shadow
            params_npy = self._ema_host_params()
        elif self._fsdp is not None:
            params_npy = self._fsdp.host_params_from_chunks(
                np.asarray(state["params"]))
        else:
            params_npy = steps.unbox(state["params"])
        # PER-PART dedup: bit-identical parts persist ONE replica instead of
        # n (an 8-chip VGG-16 checkpoint shrinks 8×); parts that genuinely
        # differ per worker (async replicas, EF buffers, ZeRO optimizer
        # chunks) stay boxed.  load() re-shapes from the meta list.
        ident = set(getattr(self.exchanger, "identical_parts", tuple)())
        state = {k: (steps.unbox(v) if k in ident else v)
                 for k, v in state.items()}
        cursor = self.data.get_cursor() \
            if hasattr(self.data, "get_cursor") else None
        import os
        if jax.process_index() != 0:
            # rank 0 writes, as the reference did — concurrent writers on a
            # shared filesystem would corrupt the archive
            return os.path.join(ckpt_dir, f"ckpt_epoch{epoch}.npz")
        extra_meta = {"boxed_parts": sorted(k for k in state
                                            if k not in ident),
                      # lets load() give a targeted error (not a raw shape
                      # mismatch) when per-worker state meets a different
                      # worker count (round-4 ADVICE #3)
                      "n_workers": self.mesh.shape[WORKER_AXIS]}
        if self._fsdp is not None:
            # the chunk layout facts, so a resume on a DIFFERENT worker
            # count can re-partition the flat vector (load() refit path)
            extra_meta["fsdp"] = {"n": self._fsdp.n_workers,
                                  "chunk": self._fsdp.chunk,
                                  "total": self._fsdp.n_total}
        if self._zero_layout is not None:
            extra_meta["zero"] = self._zero_layout
        kwargs = dict(
            rng_keys={"step": self._step_rng, "exch": self._exch_key},
            cursor=cursor, params_npy=params_npy, extra_meta=extra_meta)
        if self.config.get("async_ckpt", False):
            # the device→host gather above is the only part that must block
            # the training loop; the disk write runs on a background thread
            # (one in flight at a time — a newer save joins the older first)
            import threading
            self.wait_pending_ckpt()

            def _write():
                try:
                    ckpt_lib.save_checkpoint(ckpt_dir, state, epoch, count,
                                             **kwargs)
                except BaseException as e:   # surfaced by wait_pending_ckpt
                    self._ckpt_exc = e

            self._ckpt_exc = None
            self._ckpt_thread = threading.Thread(target=_write, daemon=True)
            self._ckpt_thread.start()
            return os.path.join(ckpt_dir, f"ckpt_epoch{epoch}.npz")
        return ckpt_lib.save_checkpoint(ckpt_dir, state, epoch, count,
                                        **kwargs)

    def wait_pending_ckpt(self) -> None:
        """Block until an in-flight async checkpoint write (if any) lands;
        re-raise its failure here — a swallowed write error would let a
        supervisor resume from an older epoch with no signal."""
        if self._ckpt_thread is not None:
            self._ckpt_thread.join()
            self._ckpt_thread = None
            exc, self._ckpt_exc = getattr(self, "_ckpt_exc", None), None
            if exc is not None:
                raise RuntimeError("async checkpoint write failed") from exc

    def load(self, ckpt_dir: str, epoch: Optional[int] = None) -> Optional[int]:
        """Restore state (call after ``compile_iter_fns``). Returns the epoch
        restored from, or None.  Restores the boxed per-worker state, the
        PRNG keys, and the data cursor, so training replays bit-identically
        from the save point (tested for BSP and GoSGD)."""
        self.wait_pending_ckpt()    # async_ckpt: never read a mid-write file
        n = self.mesh.shape[WORKER_AXIS]

        def shape_of(x, boxed):
            shape = x.shape if boxed else x.shape[1:]
            return jax.ShapeDtypeStruct(shape, x.dtype)

        # peek at the meta to learn the stored layout (which parts are boxed
        # per-worker state vs one dedup'd replica) before shaping templates
        peek = ckpt_lib.peek_meta(ckpt_dir, epoch)
        if peek is None:
            return None
        if "boxed_parts" in peek:
            boxed_parts = set(peek["boxed_parts"])
        elif peek.get("boxed", False):      # older all-or-nothing flag
            boxed_parts = set(self.step_state)
        else:                               # legacy: always saved unboxed
            boxed_parts = set()
        # Worker-count refit (the BSP elastic-resume story extended to
        # chunked state): FSDP and ZeRO chunking are pure partitions of a
        # padded flat layout, so a checkpoint from n_saved workers
        # re-partitions onto n — shape the load template by the SAVED
        # layout, then re-chunk below.  Chunk-vector leaves re-slice; boxed
        # scalar counters (identical across workers) broadcast one row.
        refit_parts: tuple = ()
        if self._fsdp is not None:
            fs = peek.get("fsdp")
            if fs is not None and int(fs["n"]) != n:
                assert int(fs["total"]) == self._fsdp.n_total, (
                    f"fsdp checkpoint holds {fs['total']} params, model "
                    f"has {self._fsdp.n_total} — different model config")
                refit_parts = ("params", "opt_state")
                n_s = int(fs["n"])
                cur_chunk_shape = (n, self._fsdp.chunk)
                saved_chunk_shape = (n_s, int(fs["chunk"]))
                rechunk = self._fsdp.rechunk
        elif self._zero_layout is not None:
            zs = peek.get("zero")
            if zs is not None and int(zs["n"]) != n:
                from ..parallel import zero as zero_lib
                lay = self._zero_layout
                assert (int(zs["shards"]) == lay["shards"] and
                        int(zs["local_total"]) == lay["local_total"]), (
                    f"zero checkpoint layout {zs} does not match the "
                    f"model's {lay} — different model/mesh config")
                refit_parts = ("opt_state",)       # params dedup portably
                n_s = int(zs["n"])
                shards, local_total = lay["shards"], lay["local_total"]
                cur_chunk_shape = (
                    n, shards * zero_lib.chunk_size(local_total, n))
                saved_chunk_shape = (
                    n_s, shards * zero_lib.chunk_size(local_total, n_s))
                rechunk = (lambda x: zero_lib.rechunk_boxed(
                    x, n, shards, local_total))

        def shape_of_saved(x):
            if x.shape == cur_chunk_shape:
                return jax.ShapeDtypeStruct(saved_chunk_shape, x.dtype)
            assert x.shape == (n,), (
                f"unexpected chunked state leaf shape {x.shape}")
            return jax.ShapeDtypeStruct((n_s,), x.dtype)

        # Per-worker state with NO refit path (exchange-strategy error-
        # feedback buffers, async diverged replicas) cannot cross a
        # worker-count change — fail with the real reason instead of a
        # raw leaf-shape mismatch deep in load_checkpoint (round-4
        # ADVICE #3).  Worker-count-portable layouts: dedup'd replicas
        # (BSP), and the FSDP/ZeRO chunked parts handled by refit above.
        n_saved = peek.get("n_workers")
        if n_saved is not None and int(n_saved) != n:
            stuck = sorted(set(boxed_parts) - set(refit_parts))
            if stuck:
                raise ValueError(
                    f"checkpoint was saved on {n_saved} workers; part(s) "
                    f"{stuck} hold per-worker state (exchange-strategy "
                    f"error feedback / diverged replicas) with no "
                    f"worker-count refit — resume on {n_saved} workers, "
                    f"or use elastic resume with the portable layouts "
                    f"(BSP / ZeRO-1 / FSDP; see docs/api.md)")
        template = {
            k: jax.tree.map(
                (shape_of_saved if k in refit_parts
                 else lambda x: shape_of(x, k in boxed_parts)), v)
            for k, v in self.step_state.items()}
        restored = ckpt_lib.load_checkpoint(ckpt_dir, template, epoch)
        if restored is None:
            return None

        if refit_parts:
            def refit_leaf(x):
                x = np.asarray(x)
                if x.shape == saved_chunk_shape:
                    return rechunk(x)
                return np.broadcast_to(x[:1], (n,) + x.shape[1:]).copy()

            for k in refit_parts:
                restored[k] = jax.tree.map(refit_leaf, restored[k])
        meta = restored.pop("_meta")
        rngs = restored.pop("_rng_keys", None)
        cursor = restored.pop("_cursor", None)
        sp = self._state_specs
        self.step_state = {
            k: (steps.place_boxed(v, self.mesh,
                                  None if sp is None else sp[k])
                if k in boxed_parts else
                steps.replicate_tree(v, n, self.mesh,
                                     None if sp is None else sp[k]))
            for k, v in restored.items()}
        if rngs:
            self._step_rng = rngs.get("step", self._step_rng)
            self._exch_key = rngs.get("exch", self._exch_key)
        if cursor and hasattr(self.data, "set_cursor"):
            self.data.set_cursor(cursor)
        return int(meta["epoch"])

    @property
    def n_params(self) -> int:
        return helper_funcs.tree_size(self.params)
