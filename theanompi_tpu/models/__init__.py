"""Model zoo following the duck-typed Theano-MPI contract (SURVEY.md §2.5).

Models are imported lazily by dotted path (the reference's importlib
convention), e.g. ``theanompi_tpu.models.cifar10:Cifar10_model``.
"""
