"""WGAN — reference-path alias module (``theanompi/models/wgan.py``,
SURVEY.md §2.7).  Implementation in :mod:`theanompi_tpu.models.gan`."""

from .gan import WGAN

__all__ = ["WGAN"]
