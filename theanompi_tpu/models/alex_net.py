"""AlexNet — the reference's main benchmark model.

Reference: ``theanompi/models/alex_net.py`` (SURVEY.md §2.7): ImageNet-1k,
batch 128, 3×227×227 input, the historical two-group convolutions, LRN,
overlapping 3×3/2 max-pooling, dropout-regularized 4096-wide FC head,
momentum SGD (0.9) + weight decay (5e-4), step LR schedule (÷10 at epochs
20/40/60), 70 epochs.  The paper's scaling tables (time per 5120 images) are
measured on this model.

TPU-first departures: NHWC layout, bfloat16 compute with fp32 params (MXU
native), and the whole fwd+bwd+update as one fused XLA program per step.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import layers as L
from .data.imagenet import ImageNet_data
from .model_base import ModelBase


class AlexNet(ModelBase):
    batch_size = 128
    epochs = 70
    n_subb = 1
    learning_rate = 0.01
    momentum = 0.9
    weight_decay = 0.0005
    lr_adjust_epochs = (20, 40, 60)

    n_class = 1000

    def build_model(self) -> None:
        cd = self.config.get("compute_dtype", jnp.bfloat16)
        nc = self.config.get("n_class", self.n_class)
        lrn_impl = self.config.get("lrn_impl", "band")
        self.seq = L.Sequential([
            # conv1: 96 kernels 11×11 stride 4, LRN, pool 3/2  (227→55→27)
            L.Conv(3, 96, 11, stride=4, padding="VALID",
                   w_init=("normal", 0.01), b_init=("constant", 0.0),
                   compute_dtype=cd, name="conv1"),
            L.LRN(impl=lrn_impl, name="lrn1"),
            L.Pool(3, 2, mode="max", name="pool1"),
            # conv2: 256 kernels 5×5 pad 2, 2 groups, LRN, pool  (27→13)
            L.Conv(96, 256, 5, padding=2, groups=2,
                   w_init=("normal", 0.01), b_init=("constant", 0.1),
                   compute_dtype=cd, name="conv2"),
            L.LRN(impl=lrn_impl, name="lrn2"),
            L.Pool(3, 2, mode="max", name="pool2"),
            # conv3/4/5  (13→13, pool→6)
            L.Conv(256, 384, 3, padding=1,
                   w_init=("normal", 0.01), b_init=("constant", 0.0),
                   compute_dtype=cd, name="conv3"),
            L.Conv(384, 384, 3, padding=1, groups=2,
                   w_init=("normal", 0.01), b_init=("constant", 0.1),
                   compute_dtype=cd, name="conv4"),
            L.Conv(384, 256, 3, padding=1, groups=2,
                   w_init=("normal", 0.01), b_init=("constant", 0.1),
                   compute_dtype=cd, name="conv5"),
            L.Pool(3, 2, mode="max", name="pool5"),
            L.Flatten(),
            L.FC(256 * 6 * 6, 4096, w_init=("normal", 0.005),
                 b_init=("constant", 0.1), compute_dtype=cd, name="fc6"),
            L.Dropout(0.5, name="drop6"),
            L.FC(4096, 4096, w_init=("normal", 0.005),
                 b_init=("constant", 0.1), compute_dtype=cd, name="fc7"),
            L.Dropout(0.5, name="drop7"),
            L.FC(4096, nc, w_init=("normal", 0.01),
                 b_init=("constant", 0.0), activation=None,
                 compute_dtype=cd, name="softmax"),
        ])
        self.data = ImageNet_data(self.config, self.batch_size, crop=227)


# Reference exposes the class as AlexNet; keep an alias matching the
# modelclass string style used in its session scripts.
Alex_net = AlexNet
