"""Data pipeline.

TPU-native rebuild of Theano-MPI's ``theanompi/models/data/``
(SURVEY.md §2.8): sharded train/val file lists, common-seed shuffling (all
workers permute identically, each takes its stride), CPU-side augmentation,
and a parallel loader that overlaps I/O + augment with compute.

The reference's flagship loader spawned a child process per worker via
``MPI.COMM_SELF.Spawn`` that wrote augmented batches straight into the
trainer's GPU buffer through a CUDA IPC handle.  The TPU equivalent is a
background prefetch pipeline per host (``theanompi_tpu.models.data.prefetch``)
that double-buffers ``jax.device_put`` onto the local shards — async
host→device transfer replaces the IPC trick.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def _host_topology(config: dict):
    """(process_count, process_index) — config overrides (tests, dry-runs)
    win over the live ``jax.distributed`` topology.

    Multi-host semantics (reference: each MPI rank loaded only its own shard
    of the shuffled filename list, SURVEY.md §2.8): every host's data object
    produces only the HOST-LOCAL slice of the global batch; the common-seed
    permutation makes the slices disjoint, and
    ``mesh.make_per_host_array`` stitches them into one global ``jax.Array``
    with no cross-host copies.
    """
    procs = config.get("process_count")
    proc_id = config.get("process_index")
    # resolve each independently: a config that sets only process_count must
    # not silently pin every host to index 0
    if procs is None:
        import jax
        procs = jax.process_count()
    if proc_id is None:
        import jax
        proc_id = jax.process_index()
    procs, proc_id = int(procs or 1), int(proc_id or 0)
    assert 0 <= proc_id < procs, (proc_id, procs)
    return procs, proc_id


class DataBase:
    """In-memory dataset with the reference's sharding/shuffle semantics.

    A "global batch" is ``size × batch_size`` samples (each worker consumed
    its own ``batch_size``-image file batch in the reference); the mesh
    splits it so chip *i* sees the *i*-th contiguous block — the stride-style
    partition the reference used on its shuffled filename list.  Under
    multi-host each host emits only its contiguous sub-block (see
    :func:`_host_topology`).
    """

    def __init__(self, config: Optional[dict] = None, batch_size: int = 128):
        self.config = dict(config or {})
        self.size = self.config.get("size", 1)
        self.batch_size = batch_size
        self.global_batch = self.size * batch_size
        self.procs, self.proc_id = _host_topology(self.config)
        # host sub-blocks must align with worker boundaries, or per-host data
        # won't match the hosts' addressable shards
        assert self.size % self.procs == 0, (
            f"{self.size} workers not divisible by {self.procs} hosts")
        self.x_train = self.y_train = self.x_val = self.y_val = None
        self._perm = None
        self._train_ptr = 0
        self._val_ptr = 0
        self._shuffle_seed = None

    # subclasses populate x/y arrays then call _finalize()
    def _finalize(self) -> None:
        n_train, n_val = len(self.y_train), len(self.y_val)
        self.n_batch_train = n_train // self.global_batch
        self.n_batch_val = max(1, n_val // self.global_batch)
        self._perm = np.arange(n_train)
        assert self.n_batch_train > 0, (
            f"{n_train} train samples < one global batch {self.global_batch}")
        # single-host tolerates a short final val batch; multi-host cannot
        # (per-process shards must be equal-sized to stitch)
        assert self.procs == 1 or n_val >= self.global_batch, (
            f"{n_val} val samples < one global batch {self.global_batch} "
            f"with {self.procs} hosts")

    def shuffle_data(self, seed: int) -> None:
        """Common-seed shuffle (reference: identical RNG on all ranks so the
        strided shards are disjoint)."""
        rng = np.random.RandomState(seed)
        self._perm = rng.permutation(len(self.y_train))
        self._shuffle_seed = int(seed)
        self._train_ptr = 0
        self._val_ptr = 0

    # -- checkpoint cursor (SURVEY.md §5: resume must replay the data stream)
    def get_cursor(self) -> Dict:
        """Everything needed to resume the data stream exactly: the shuffle
        seed regenerates the permutation, the pointers reposition it."""
        return {"shuffle_seed": self._shuffle_seed,
                "train_ptr": int(self._train_ptr),
                "val_ptr": int(self._val_ptr)}

    def set_cursor(self, cursor: Dict) -> None:
        if cursor.get("shuffle_seed") is not None:
            self.shuffle_data(int(cursor["shuffle_seed"]))
        self._train_ptr = int(cursor.get("train_ptr", 0))
        self._val_ptr = int(cursor.get("val_ptr", 0))

    def _local(self, lo: int) -> slice:
        """This host's contiguous sub-block of the global batch starting at
        global offset ``lo`` (device order in the mesh is process-grouped, so
        block h of the global array belongs to host h)."""
        per = self.global_batch // self.procs
        start = lo + self.proc_id * per
        return slice(start, start + per)

    def next_train_batch(self, count: int) -> Dict[str, np.ndarray]:
        i = self._train_ptr % self.n_batch_train
        self._train_ptr += 1
        idx = self._perm[self._local(i * self.global_batch)]
        return self._make_batch(self.x_train[idx], self.y_train[idx], train=True)

    def next_val_batch(self, count: int) -> Dict[str, np.ndarray]:
        i = self._val_ptr % self.n_batch_val
        self._val_ptr += 1
        sl = self._local(i * self.global_batch)
        x, y = self.x_val[sl], self.y_val[sl]
        # single-host short final batch: trim to a worker-divisible row count
        # (the mesh splits axis 0 across `size` workers)
        keep = (len(y) // self.size) * self.size
        assert keep > 0, (f"{len(y)} val rows can't split across "
                          f"{self.size} workers")
        return self._make_batch(x[:keep], y[:keep], train=False)

    def _make_batch(self, x, y, train: bool) -> Dict[str, np.ndarray]:
        """Hook for augmentation; default: cast only."""
        return {"x": np.ascontiguousarray(x, dtype=np.float32),
                "y": np.ascontiguousarray(y, dtype=np.int32)}
