"""ImageNet data object.

Reference: ``theanompi/models/data/imagenet.py`` (SURVEY.md §2.8) — ImageNet
pre-processed offline into hickle ``.hkl`` files (one file = one 128-image
uint8 batch, inherited from ``uoguelph-mlrg/theano_alexnet``), a mean image
``.npy``, shuffled shard lists with a common seed, and random-crop(256→227)
+ horizontal-mirror augmentation on CPU.

This rebuild keeps that on-disk contract so existing data prep works:
``config['data_dir']`` (or ``$IMAGENET_DIR``) must contain ``train_hkl/`` and
``val_hkl/`` of batch files plus ``img_mean.npy``.  ``.hkl`` is read via
hickle when installed, with a ``.npy``/``.npz`` fallback per file extension.
Without a data dir it synthesizes deterministic random uint8 image batches —
enough for throughput benchmarking (bench.py) and pipeline tests, where only
shapes and rates matter.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

RAW = 256       # stored image side (reference batch files are 256×256)
CROP = 227      # AlexNet crop (VGG uses 224; configurable)
N_CLASS = 1000


def _load_hkl_h5py(path: str) -> np.ndarray:
    """hickle ``.hkl`` files ARE HDF5 files: read the payload with h5py
    directly (hickle itself is not in this environment).  All hickle versions
    store the array as an HDF5 dataset — commonly named ``data`` or
    ``data_0`` at the root (v1–v3, the era of the reference's files) or
    nested under a group (v4+); take the first dataset found."""
    import h5py

    with h5py.File(path, "r") as f:
        for name in ("data", "data_0"):
            if name in f and isinstance(f[name], h5py.Dataset):
                return np.asarray(f[name])
        found = []

        def visit(name, obj):
            if isinstance(obj, h5py.Dataset):
                found.append((obj.size, name))

        f.visititems(visit)
        if not found:
            raise ValueError(f"{path}: no dataset inside the HDF5/.hkl file")
        # v4+ nests the payload among small metadata datasets — the image
        # batch is by far the largest one.
        return np.asarray(f[max(found)[1]])


def _load_batch_file(path: str) -> np.ndarray:
    if path.endswith(".hkl"):
        try:
            import hickle  # optional dep, as in the reference
            return np.asarray(hickle.load(path))
        except ImportError:
            return _load_hkl_h5py(path)
        except Exception as hickle_err:
            # File is HDF5 but not hickle-shaped (plain h5py-written batch
            # files). If the h5py reader can't make sense of it either,
            # surface the ORIGINAL hickle error, not the fallback's.
            try:
                return _load_hkl_h5py(path)
            except Exception:
                raise hickle_err
    if path.endswith(".npz"):
        with np.load(path) as z:
            return z[list(z.files)[0]]
    return np.load(path)


class ImageNet_data:
    """Sharded batch-file loader with reference augmentation semantics.

    Unlike the in-memory :class:`DataBase`, this is file-batch oriented like
    the reference: an epoch is a shuffled list of batch FILES; each training
    step concatenates ``size`` files' worth of images into the global batch.
    """

    def __init__(self, config: Optional[dict] = None, batch_size: int = 128,
                 crop: int = CROP):
        from . import _host_topology
        self.config = dict(config or {})
        self.size = self.config.get("size", 1)
        self.batch_size = batch_size
        self.global_batch = self.size * batch_size
        self.procs, self.proc_id = _host_topology(self.config)
        assert self.size % self.procs == 0, (
            f"{self.size} workers not divisible by {self.procs} hosts")
        self.crop = int(self.config.get("crop_size", crop))
        self.rng = np.random.RandomState(self.config.get("seed", 42))

        d = self.config.get("data_dir") or os.environ.get("IMAGENET_DIR")
        if d and os.path.isdir(os.path.join(d, "train_hkl")):
            self._init_real(d)
            self.synthetic = False
        else:
            self._init_synthetic()
            self.synthetic = True
        self._train_ptr = 0
        self._val_ptr = 0
        self._shuffle_seed = None
        self._perm = np.arange(len(self.train_files)) if not self.synthetic \
            else None

    # -- real batch files ---------------------------------------------------

    def _init_real(self, d: str) -> None:
        def listdir(sub):
            p = os.path.join(d, sub)
            return sorted(os.path.join(p, f) for f in os.listdir(p)
                          if f.split(".")[-1] in ("hkl", "npy", "npz"))

        self.train_files: List[str] = listdir("train_hkl")
        self.val_files: List[str] = listdir("val_hkl")
        self.train_labels = np.load(os.path.join(d, "train_labels.npy"))
        self.val_labels = np.load(os.path.join(d, "val_labels.npy"))
        mean_path = os.path.join(d, "img_mean.npy")
        self.img_mean = (np.load(mean_path).astype(np.float32)
                         if os.path.exists(mean_path) else
                         np.float32(122.0))
        if isinstance(self.img_mean, np.ndarray) and self.img_mean.ndim == 3:
            # normalize a reference c01 (CHW) mean to HWC once, not per batch
            self.img_mean = self._mean_to_hwc(self.img_mean)
        files_per_step = self.size
        self.n_batch_train = len(self.train_files) // files_per_step
        self.n_batch_val = max(1, len(self.val_files) // files_per_step)
        # multi-host needs equal per-host file shards every val step (the
        # max(1, ...) single-host fallback would index past the list)
        assert self.procs == 1 or len(self.val_files) >= files_per_step, (
            f"{len(self.val_files)} val files < {files_per_step} per step "
            f"with {self.procs} hosts")

    # -- synthetic ----------------------------------------------------------

    def _init_synthetic(self) -> None:
        self.n_batch_train = int(self.config.get("synthetic_batches", 64))
        self.n_batch_val = int(self.config.get("synthetic_val_batches", 4))
        self.train_files = self.val_files = []
        self.img_mean = np.float32(122.0)
        # One cached uint8 batch, re-used every step (throughput only).  Each
        # host draws ONLY its local rows from a host-keyed stream — O(local)
        # time and RAM (at pod scale the full global megabatch would be GBs
        # of dead work per host); distinct hosts get distinct data.
        per = self.global_batch // self.procs
        r = np.random.RandomState([0, self.proc_id])
        self._synth_x = r.randint(0, 256, (per, RAW, RAW, 3), dtype=np.uint8)
        n_class = int(self.config.get("n_class", N_CLASS))
        self._synth_y = r.randint(0, n_class, per).astype(np.int32)

    # -- contract ------------------------------------------------------------

    def shuffle_data(self, seed: int) -> None:
        """Common-seed shuffle of the batch-FILE list (reference semantics:
        all ranks shuffle identically, each takes its stride)."""
        if not self.synthetic:
            self._perm = np.random.RandomState(seed).permutation(
                len(self.train_files))
        self._shuffle_seed = int(seed)
        self._train_ptr = 0
        self._val_ptr = 0

    # -- checkpoint cursor --------------------------------------------------
    def get_cursor(self) -> Dict:
        """Shuffle seed + batch pointers + augmentation RNG state: enough to
        resume the exact sample/crop/mirror stream mid-epoch."""
        keys, pos, has_gauss, cached = self.rng.get_state()[1:]
        return {"shuffle_seed": self._shuffle_seed,
                "train_ptr": int(self._train_ptr),
                "val_ptr": int(self._val_ptr),
                "aug_rng_keys": np.asarray(keys),
                "aug_rng_pos": int(pos),
                "aug_rng_has_gauss": int(has_gauss),
                "aug_rng_cached": float(cached)}

    def set_cursor(self, cursor: Dict) -> None:
        if cursor.get("shuffle_seed") is not None:
            self.shuffle_data(int(cursor["shuffle_seed"]))
        self._train_ptr = int(cursor.get("train_ptr", 0))
        self._val_ptr = int(cursor.get("val_ptr", 0))
        if "aug_rng_keys" in cursor:
            self.rng.set_state(("MT19937",
                                np.asarray(cursor["aug_rng_keys"], np.uint32),
                                int(cursor["aug_rng_pos"]),
                                int(cursor["aug_rng_has_gauss"]),
                                float(cursor["aug_rng_cached"])))

    def _local_files(self, lo: int):
        """This host's slice of the step's ``size`` batch files (each MPI
        rank in the reference loaded only its own file — here each HOST
        loads only its chips' files)."""
        per = self.size // self.procs
        start = lo + self.proc_id * per
        return range(start, start + per)

    def plan_train_batch(self, count: int) -> Dict:
        """Advance the cursor AND the augmentation RNG, returning a pure
        PLAN (round-4 parallel producer): :meth:`materialize` turns a plan
        into the batch statelessly, so a thread pool can materialize
        several plans concurrently while the draws stay sequential — the
        batch stream is bit-identical to the serial path."""
        if self.synthetic:    # _synth_x/_synth_y are already host-local
            n = self._synth_x.shape[0]
            return {"files": None,
                    "draws": self._draw(n, RAW, RAW, train=True)}
        i = self._train_ptr % self.n_batch_train
        self._train_ptr += 1
        idx = [int(self._perm[j]) for j in self._local_files(i * self.size)]
        n = len(idx) * self.batch_size
        h, w = self._stored_hw()
        return {"files": idx, "draws": self._draw(n, h, w, train=True)}

    def _stored_hw(self):
        """Stored image dims, read ONCE from the first batch file (plan-time
        draws must match what materialize will load; the .npy fallback
        accepts non-256 sizes)."""
        if getattr(self, "_hw", None) is None:
            x0 = self._to_nhwc(_load_batch_file(self.train_files[0]))
            self._hw = (int(x0.shape[1]), int(x0.shape[2]))
        return self._hw

    def materialize(self, plan: Dict) -> Dict[str, np.ndarray]:
        """Stateless plan → batch (thread-safe: reads only immutable
        fields; all RNG happened at plan time)."""
        if plan["files"] is None:
            return self._transform(self._synth_x, self._synth_y,
                                   plan["draws"])
        idx = plan["files"]
        xs = np.concatenate([_load_batch_file(self.train_files[j])
                             for j in idx])
        ys = np.concatenate([self.train_labels[j * self.batch_size:
                                               (j + 1) * self.batch_size]
                             for j in idx])
        return self._transform(self._to_nhwc(xs), ys.astype(np.int32),
                               plan["draws"])

    def next_train_batch(self, count: int) -> Dict[str, np.ndarray]:
        return self.materialize(self.plan_train_batch(count))

    def next_val_batch(self, count: int) -> Dict[str, np.ndarray]:
        if self.synthetic:
            return self._augment(self._synth_x, self._synth_y, train=False)
        i = self._val_ptr % self.n_batch_val
        self._val_ptr += 1
        # single-host tolerates fewer val files than workers (short final
        # batch, trimmed below so it still splits across the mesh);
        # multi-host asserts at init
        idx = [j for j in self._local_files(i * self.size)
               if j < len(self.val_files)]
        xs = np.concatenate([_load_batch_file(self.val_files[j])
                             for j in idx])
        ys = np.concatenate([self.val_labels[j * self.batch_size:
                                             (j + 1) * self.batch_size]
                             for j in idx])
        keep = (len(ys) // self.size) * self.size
        assert keep > 0, (f"{len(ys)} val images can't split across "
                          f"{self.size} workers")
        return self._augment(self._to_nhwc(xs[:keep]),
                             ys[:keep].astype(np.int32), train=False)

    @staticmethod
    def _to_nhwc(x: np.ndarray) -> np.ndarray:
        """Reference .hkl files are bc01 (N,C,H,W) or c01b; normalize."""
        from ... import native
        if native.is_nchw(x):
            return np.ascontiguousarray(x.transpose(0, 2, 3, 1))
        # c01b legacy layout (C,H,W,B): channel count leads AND the trailing
        # dim is not a channel count (else it's a small NHWC batch)
        if x.ndim == 4 and x.shape[0] in (1, 3) and x.shape[-1] not in (1, 3):
            return np.ascontiguousarray(x.transpose(3, 1, 2, 0))
        return x

    @staticmethod
    def _mean_to_hwc(m: np.ndarray) -> np.ndarray:
        """Normalize a 3-D mean image to (H, W, C)."""
        if m.shape[-1] in (1, 3):
            return m
        if m.shape[0] in (1, 3):      # CHW (the reference's c01 mean)
            return np.ascontiguousarray(m.transpose(1, 2, 0))
        return m

    def _draw(self, n: int, h: int, w: int, train: bool):
        """The augmentation RNG draws — SEQUENTIAL state (plan time)."""
        c = self.crop
        if train:
            per_img = bool(self.config.get("aug_per_image", False))
            m = n if per_img else 1
            oy = self.rng.randint(0, h - c + 1, size=m).astype(np.int32)
            ox = self.rng.randint(0, w - c + 1, size=m).astype(np.int32)
            flip = self.rng.randint(0, 2, size=m).astype(np.uint8)
        else:
            oy = np.full(1, (h - c) // 2, np.int32)
            ox = np.full(1, (w - c) // 2, np.int32)
            flip = np.zeros(1, np.uint8)
        return oy, ox, flip

    def _augment(self, x: np.ndarray, y: np.ndarray,
                 train: bool) -> Dict[str, np.ndarray]:
        """Reference augmentation: random 256→crop window + horizontal
        mirror at train time (one draw per batch, as the reference's
        per-batch ``param_rand``); center crop at val; mean subtraction.
        ``aug_per_image=True`` in config upgrades to independent per-image
        draws.  The fused crop/mirror/mean/cast pass runs in the native C++
        library when available (``theanompi_tpu.native``), NumPy otherwise.
        """
        return self._transform(
            x, y, self._draw(x.shape[0], x.shape[1], x.shape[2], train))

    def _transform(self, x: np.ndarray, y: np.ndarray,
                   draws) -> Dict[str, np.ndarray]:
        """Stateless tail of the augmentation (thread-safe given draws)."""
        from ... import native
        n, h, w = x.shape[0], x.shape[1], x.shape[2]
        c = self.crop
        oy, ox, flip = draws
        assert int(oy.max()) + c <= h and int(ox.max()) + c <= w, (
            f"crop window ({int(oy.max())},{int(ox.max())})+{c} exceeds the "
            f"loaded batch's {h}x{w} — heterogeneous batch-file sizes?")
        if self.config.get("aug_wire_u8", False):
            # u8-wire mode (round-4 perf lever): host does ONLY crop+mirror
            # on uint8 (a gather); mean-subtract+cast happen ON DEVICE,
            # fused into the first conv by XLA — the host→device transfer
            # shrinks 4×.  Mean semantics (ModelBase.stage_input): always
            # the mean image's CENTER-crop window — bit-equal to the fused
            # f32 pass for scalar means and for aug_per_image mode; a
            # DOCUMENTED deviation for shared-window draws with a full mean
            # image, where the f32 pass subtracts the window-exact mean
            # (shipping the per-batch window would need a replicated batch
            # leaf; the center window is the aug_per_image approximation).
            m = oy.shape[0]
            if m == 1:                     # shared window: one vector slice
                win = x[:, oy[0]:oy[0] + c, ox[0]:ox[0] + c, :]
                if flip[0]:
                    win = win[:, :, ::-1, :]
                out = np.ascontiguousarray(win)
            else:
                out = np.empty((n, c, c, x.shape[3]), np.uint8)
                for i in range(n):
                    win = x[i, oy[i]:oy[i] + c, ox[i]:ox[i] + c, :]
                    out[i] = win[:, ::-1, :] if flip[i] else win
            return {"x": out,
                    "y": np.ascontiguousarray(y, dtype=np.int32)}
        mean, mean_scalar = None, 0.0
        m_img = self.img_mean
        if isinstance(m_img, np.ndarray) and m_img.size > 1:
            if m_img.ndim == 3:
                full = self._mean_to_hwc(m_img)
                if oy.shape[0] == 1:
                    mean = full[oy[0]:oy[0] + c, ox[0]:ox[0] + c, :]
                else:
                    # per-image windows: use the mean image's center crop for
                    # all (window-exact per-image mean would defeat the fused
                    # pass)
                    cy, cx = (h - c) // 2, (w - c) // 2
                    mean = full[cy:cy + c, cx:cx + c, :]
            else:
                # per-channel mean (shape (C,) or broadcastable): expand to
                # the window shape the fused pass expects
                n_chan = x.shape[-1]
                mean = np.broadcast_to(
                    np.asarray(m_img, np.float32).reshape(-1)[:n_chan],
                    (c, c, n_chan))
        else:
            mean_scalar = float(m_img)
        out = native.augment_batch(x, oy, ox, flip, c, mean=mean,
                                   mean_scalar=mean_scalar)
        return {"x": out, "y": np.ascontiguousarray(y, dtype=np.int32)}
