"""CIFAR-10 data object.

Reference: ``theanompi/models/data/cifar10.py`` (SURVEY.md §2.8) — loaded the
python-pickle CIFAR-10 batches, mean-subtracted, and sharded across ranks.

Loads the standard ``cifar-10-batches-py`` pickle files when present
(``config['data_dir']``, ``$CIFAR10_DIR``, or ``./data/cifar-10-batches-py``);
otherwise falls back to a DETERMINISTIC SYNTHETIC set (per-class prototype
images + gaussian noise) so smoke tests and benchmarks run with zero data
setup.  The synthetic task is genuinely learnable, which the convergence
tests rely on.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional

import numpy as np

from . import DataBase

N_CLASS = 10
IMG = 32


class Cifar10_data(DataBase):
    def __init__(self, config: Optional[dict] = None, batch_size: int = 128):
        super().__init__(config, batch_size)
        d = self._find_dir()
        if d:
            self._load_real(d)
            self.synthetic = False
        else:
            n_train = int(self.config.get("synthetic_train", 4096))
            n_val = int(self.config.get("synthetic_val", 1024))
            self._make_synthetic(n_train, n_val)
            self.synthetic = True
        # channel-mean subtraction (reference subtracted the mean image)
        self.mean = self.x_train.mean(axis=(0, 1, 2), keepdims=True)
        self._finalize()

    def _find_dir(self) -> Optional[str]:
        cands = [self.config.get("data_dir"),
                 os.environ.get("CIFAR10_DIR"),
                 "./data/cifar-10-batches-py"]
        for c in cands:
            if c and os.path.isdir(c) and \
                    os.path.exists(os.path.join(c, "data_batch_1")):
                return c
        return None

    def _load_real(self, d: str) -> None:
        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(d, f"data_batch_{i}"), "rb") as f:
                b = pickle.load(f, encoding="bytes")
            xs.append(b[b"data"])
            ys.append(b[b"labels"])
        x = np.concatenate(xs).reshape(-1, 3, IMG, IMG).transpose(0, 2, 3, 1)
        self.x_train = x.astype(np.float32) / 255.0
        self.y_train = np.concatenate(ys).astype(np.int32)
        with open(os.path.join(d, "test_batch"), "rb") as f:
            b = pickle.load(f, encoding="bytes")
        xv = np.asarray(b[b"data"]).reshape(-1, 3, IMG, IMG).transpose(0, 2, 3, 1)
        self.x_val = xv.astype(np.float32) / 255.0
        self.y_val = np.asarray(b[b"labels"], dtype=np.int32)

    def _make_synthetic(self, n_train: int, n_val: int) -> None:
        rng = np.random.RandomState(1234)
        protos = rng.randn(N_CLASS, IMG, IMG, 3).astype(np.float32) * 0.8

        def make(n, seed):
            r = np.random.RandomState(seed)
            y = r.randint(0, N_CLASS, n).astype(np.int32)
            x = protos[y] + 0.25 * r.randn(n, IMG, IMG, 3).astype(np.float32)
            return x, y

        self.x_train, self.y_train = make(n_train, 5678)
        self.x_val, self.y_val = make(n_val, 91011)

    def _make_batch(self, x, y, train):
        return {"x": np.ascontiguousarray(x - self.mean, dtype=np.float32),
                "y": np.ascontiguousarray(y, dtype=np.int32)}
