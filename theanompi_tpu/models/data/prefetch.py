"""Background prefetch pipeline — the parallel loader.

TPU-native rebuild of Theano-MPI's flagship data-pipeline feature
(SURVEY.md §2.8): the reference spawned a child process per worker via
``MPI.COMM_SELF.Spawn`` that loaded the next ``.hkl`` batch, augmented it on
CPU, and wrote it straight into the trainer's GPU input buffer through a CUDA
IPC handle, overlapping I/O+augment with compute behind a per-batch
handshake.

On TPU the IPC trick is unnecessary: a background thread runs the (host,
numpy) load+augment for the NEXT batches while the device computes, and
``jax.device_put`` streams the result to the chips asynchronously.  The
"icomm barrier" handshake becomes a bounded queue: depth 2 = classic double
buffering.

Wrap any data object:  ``data = PrefetchLoader(Cifar10_data(cfg))`` — the
wrapper exposes the same duck-typed surface (``next_train_batch``,
``next_val_batch``, ``shuffle_data``, ``n_batch_train``, ``n_batch_val``), so
``para_load`` is a config flag exactly as in the reference.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional


class PrefetchLoader:
    """Double-buffered background loader over any DataBase-shaped object."""

    def __init__(self, data, depth: int = 2, device_put_fn=None):
        self._data = data
        self.depth = depth
        self._device_put_fn = device_put_fn  # optional: stage host→device too
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._epoch_batches = 0

    # duck-typed passthrough surface ---------------------------------------
    @property
    def n_batch_train(self):
        return self._data.n_batch_train

    @property
    def n_batch_val(self):
        return self._data.n_batch_val

    @property
    def batch_size(self):
        return self._data.batch_size

    @property
    def global_batch(self):
        return self._data.global_batch

    def shuffle_data(self, seed: int) -> None:
        """Reference cadence: called at epoch start; (re)starts the producer
        for one epoch's worth of train batches."""
        self._shutdown()
        self._data.shuffle_data(seed)
        self._q = queue.Queue(maxsize=self.depth)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._producer, args=(self._data.n_batch_train,),
            daemon=True)
        self._thread.start()

    def next_train_batch(self, count: int):
        if self._q is None:          # shuffle_data not called yet (smoke use)
            return self._maybe_put(self._data.next_train_batch(count))
        item = self._q.get()
        if isinstance(item, BaseException):
            raise item
        return item

    def next_val_batch(self, count: int):
        # Validation is per-epoch and cheap relative to training — served
        # synchronously (the reference's loader also only covered train).
        return self._maybe_put(self._data.next_val_batch(count))

    # producer -------------------------------------------------------------
    def _producer(self, n_batches: int) -> None:
        try:
            for i in range(n_batches):
                if self._stop.is_set():
                    return
                batch = self._maybe_put(self._data.next_train_batch(i + 1))
                self._q.put(batch)
        except BaseException as e:    # surface loader errors in the consumer
            self._q.put(e)

    def _maybe_put(self, batch):
        return self._device_put_fn(batch) if self._device_put_fn else batch

    def _shutdown(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            try:                      # drain so the producer can observe stop
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
        self._thread = None
        self._q = None

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass
