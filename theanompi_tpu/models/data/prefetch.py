"""Background prefetch pipeline — the parallel loader.

TPU-native rebuild of Theano-MPI's flagship data-pipeline feature
(SURVEY.md §2.8): the reference spawned a child process per worker via
``MPI.COMM_SELF.Spawn`` that loaded the next ``.hkl`` batch, augmented it on
CPU, and wrote it straight into the trainer's GPU input buffer through a CUDA
IPC handle, overlapping I/O+augment with compute behind a per-batch
handshake.

On TPU the IPC trick is unnecessary: a background thread runs the (host,
numpy) load+augment for the NEXT batches while the device computes, and
``jax.device_put`` streams the result to the chips asynchronously.  The
"icomm barrier" handshake becomes a bounded queue: depth 2 = classic double
buffering.

Wrap any data object:  ``data = PrefetchLoader(Cifar10_data(cfg))`` — the
wrapper exposes the same duck-typed surface (``next_train_batch``,
``next_val_batch``, ``shuffle_data``, ``n_batch_train``, ``n_batch_val``), so
``para_load`` is a config flag exactly as in the reference.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from ...utils import telemetry


def _stack_host(batches):
    """Host-side ``[k, ...]`` stack of k per-step batches — delegates to
    ``steps.stack_host`` (lazy: keeps this module importable without
    jax) so the window layout has exactly one definition."""
    from ...parallel.steps import stack_host
    return stack_host(batches)


class PrefetchLoader:
    """Double-buffered background loader over any DataBase-shaped object.

    ``n_workers > 1`` (round-4, SURVEY §7 "input pipeline at AlexNet
    speeds"): when the wrapped data object exposes the ``plan_train_batch``
    / ``materialize`` split (``ImageNet_data``), the producer draws plans
    SEQUENTIALLY (cursor + augmentation RNG stay exact) and a thread pool
    materializes several in flight — disk reads and the native augment
    release the GIL, so file-based pipelines scale near-linearly.  The
    bounded queue holds ordered futures: the batch STREAM is bit-identical
    to the serial path, whatever the pool size.

    ``set_window(k, stage_fn)`` (``steps_per_call`` > 1): production goes
    WINDOW-granular — the queue holds whole ``[k, ...]`` dispatch inputs,
    staged to the mesh by the producer, consumed via
    ``next_train_window`` (docs/design.md §9)."""

    def __init__(self, data, depth: int = 2, device_put_fn=None,
                 n_workers: int = 1):
        self._data = data
        self.depth = depth
        self.n_workers = max(1, int(n_workers))
        self._device_put_fn = device_put_fn  # optional: stage host→device too
        self.window = 0                      # set_window: spc window mode
        self._stage_window_fn = None
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        # per-producer stop event: a timed-out old producer must keep seeing
        # ITS stop flag set after a restart (a shared cleared Event would
        # revive it against the new queue / shared data object)
        self._stop: Optional[threading.Event] = None
        self._consumed_cursor: dict = {}

    def set_window(self, k: int, stage_fn=None) -> None:
        """Switch to WINDOW-granular production (``steps_per_call`` > 1):
        the producer assembles whole spc windows — k sequential draws
        (cursor/augmentation RNG stay exact), ONE host ``np.stack`` to
        ``[k, ...]`` leaves, one ``stage_fn(window)`` (normally
        ``steps.stage_window`` bound to the mesh) — so the bounded queue
        holds DEVICE-RESIDENT windows, depth 2 = double buffering of
        entire dispatch inputs, and the consumer dequeues via
        :meth:`next_train_window` and dispatches immediately.

        ``k <= 1`` reverts to per-batch production.  ``stage_fn=None``
        leaves the window on the host (tests; the consumer's
        ``put_batch_stack`` then stages it).  ``device_put_fn`` (per-batch
        staging) is ignored while window mode is on — staging happens once
        per window.  A live producer is restarted so the queue granularity
        switches immediately; ``model_base.compile_iter_fns`` calls this
        before the first ``shuffle_data``."""
        k = int(k)
        was = (self.window, self._stage_window_fn)
        self.window = k if k > 1 else 0
        self._stage_window_fn = stage_fn if self.window else None
        if self._thread is not None and \
                (self.window, self._stage_window_fn) != was:
            self._shutdown()
            # rewind to the last CONSUMED position before restarting: the
            # old producer ran ahead and the drained queue held up to
            # ``depth`` unconsumed items — resuming from the wrapped
            # data's live cursor would silently skip them.  Cursor-less
            # duck-typed data can't rewind and degrades to the wrapped
            # object's live position (the set_cursor contract above).
            if self._consumed_cursor and hasattr(self._data, "set_cursor"):
                self._data.set_cursor(self.get_cursor())
            self._restart_producer()

    # duck-typed passthrough surface ---------------------------------------
    @property
    def n_batch_train(self):
        return self._data.n_batch_train

    @property
    def n_batch_val(self):
        return self._data.n_batch_val

    @property
    def batch_size(self):
        return self._data.batch_size

    @property
    def global_batch(self):
        return self._data.global_batch

    def __getattr__(self, name):
        # duck-typed passthrough for anything the wrapper doesn't override
        # (img_mean/crop for the u8-wire device mean, synthetic, …) —
        # __getattr__ fires only for MISSING attributes, so the wrapper's
        # own surface wins.  Private/dunder lookups raise normally (also
        # prevents recursion before __init__ sets _data).
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._data, name)

    def shuffle_data(self, seed: int) -> None:
        """Reference cadence: called at epoch start; (re)starts the producer
        for one epoch's worth of train batches."""
        self._shutdown()
        self._data.shuffle_data(seed)
        self._restart_producer()

    # -- checkpoint cursor --------------------------------------------------
    # The producer runs AHEAD of training, so the wrapped data object's
    # cursor is up to ``depth`` batches past what the trainer has consumed.
    # Each queue item therefore carries the wrapped cursor as of *after* that
    # batch was generated; get_cursor reports the last consumed one, making
    # mid-epoch save/resume exact even with para_load on.

    def get_cursor(self):
        c = dict(self._consumed_cursor)
        # val batches are served synchronously on the consumer thread, so the
        # wrapped object's val_ptr is live and authoritative — the producer
        # snapshot only tracks the train stream
        if hasattr(self._data, "get_cursor"):
            c["val_ptr"] = self._data.get_cursor().get("val_ptr", 0)
        return c

    def set_cursor(self, cursor) -> None:
        self._shutdown()
        if hasattr(self._data, "set_cursor"):
            self._data.set_cursor(cursor)
        # else: cursor-less duck-typed data — resume degrades gracefully to
        # wherever the wrapped object stands (same contract as get_cursor's
        # empty dict)
        self._restart_producer()

    def _restart_producer(self) -> None:
        self._consumed_cursor = self._data.get_cursor() \
            if hasattr(self._data, "get_cursor") else {}
        n = self._data.n_batch_train
        # batches left in the current epoch (ptr%n == 0 → a fresh epoch)
        remaining = n - int(self._consumed_cursor.get("train_ptr", 0)) % n
        # pooled producer: the queue must hold one future per in-flight
        # materialization or q.put blocks the submit loop at depth+1 and
        # caps the effective pool (review finding)
        pooled = self.n_workers > 1 and hasattr(self._data,
                                                "plan_train_batch") \
            and not self.window
        self._q = queue.Queue(
            maxsize=self.depth + (self.n_workers if pooled else 0))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._producer, args=(remaining, self._q, self._stop),
            daemon=True)
        self._thread.start()

    def next_train_batch(self, count: int):
        if self.window > 1 and self._q is not None:
            raise RuntimeError(
                "window mode is active — the queue holds whole "
                f"[{self.window}, ...] windows; consume via "
                "next_train_window (or set_window(0) first)")
        if self._q is None:          # shuffle_data not called yet (smoke use)
            return self._maybe_put(self._data.next_train_batch(count))
        item = self._dequeue()
        if isinstance(item, BaseException):
            raise item
        batch, cursor = item
        if hasattr(batch, "result"):     # pooled producer: an ordered future
            batch = batch.result()       # (re-raises materialize errors)
        # commit the cursor only AFTER the batch is in hand — a failed
        # materialize must not mark its batch consumed
        self._consumed_cursor = cursor
        return batch

    def next_train_window(self, count: int):
        """Dequeue one whole spc window — ALREADY staged to the mesh when
        ``set_window`` got a ``stage_fn`` (queue items are device-resident:
        the consumer's only cost is the dequeue wait).  ``count`` names the
        LAST step of the window, as in ``train_iter``."""
        assert self.window > 1, "set_window(k) first"
        if self._q is None:          # shuffle_data not called yet (smoke use)
            batches = [self._data.next_train_batch(count - self.window + 1 + j)
                       for j in range(self.window)]
            return self._stage(_stack_host(batches))
        item = self._dequeue()
        if isinstance(item, BaseException):
            raise item
        window, cursor = item
        # commit only after the window is in hand (same contract as the
        # per-batch path); the cursor is AT WINDOW GRANULARITY — as of
        # after this window's k-th batch was drawn
        self._consumed_cursor = cursor
        return window

    def next_val_batch(self, count: int):
        # Validation is per-epoch and cheap relative to training — served
        # synchronously (the reference's loader also only covered train).
        return self._maybe_put(self._data.next_val_batch(count))

    def _dequeue(self):
        """One queue pop, instrumented: queue depth at dequeue (min/p50 in
        the report — 0 means the consumer is about to starve) and a
        starved-dequeue counter.  Disabled telemetry ≡ one attribute
        check."""
        tm = telemetry.active()
        if tm.enabled:
            depth = self._q.qsize()
            tm.gauge("prefetch.queue_depth", depth)
            tm.observe("prefetch.queue_depth", depth)
            tm.counter("prefetch.dequeues")
            if depth == 0:
                tm.counter("prefetch.starved_dequeues")
        return self._q.get()

    # producer -------------------------------------------------------------
    def _producer(self, n_batches: int, q: queue.Queue,
                  stop: threading.Event) -> None:
        # q/stop are THIS producer's own (not read from self): a restart
        # swaps self._q/_stop, and a slow old producer must neither feed the
        # new queue nor be revived by the new (cleared) event
        try:
            if self.window > 1:
                self._producer_windows(n_batches, q, stop)
                return
            if self.n_workers > 1 and hasattr(self._data,
                                              "plan_train_batch"):
                self._producer_pooled(n_batches, q, stop)
                return
            tm = telemetry.active()
            for i in range(n_batches):
                if stop.is_set():
                    return
                t0 = time.time()
                batch = self._maybe_put(self._data.next_train_batch(i + 1))
                cursor = self._data.get_cursor() \
                    if hasattr(self._data, "get_cursor") else {}
                if tm.enabled:
                    # produce time up (relative to the consumer's step
                    # time) = the producer becoming the bottleneck
                    tm.observe("prefetch.produce_secs", time.time() - t0)
                if stop.is_set():     # restart raced the load: drop, don't put
                    return
                t0 = time.time()
                q.put((batch, cursor))
                if tm.enabled:
                    # blocked on a full queue = the producer is AHEAD
                    # (healthy overlap); ~0 everywhere + starved dequeues
                    # = the producer can't keep up
                    tm.observe("prefetch.producer_blocked_secs",
                               time.time() - t0)
        except BaseException as e:    # surface loader errors in the consumer
            q.put(e)

    def _producer_pooled(self, n_batches: int, q: queue.Queue,
                         stop: threading.Event) -> None:
        """Sequential plans, pooled materialization: at most ``depth``
        queued + ``n_workers`` executing batches in flight; the queue keeps
        plan order, so the stream equals the serial producer's exactly."""
        from concurrent.futures import ThreadPoolExecutor
        failed = []                    # any materialize error aborts the

        def on_done(f):                # epoch, matching the serial producer
            if not f.cancelled() and f.exception() is not None:
                failed.append(f)

        with ThreadPoolExecutor(self.n_workers) as pool:
            for i in range(n_batches):
                if stop.is_set() or failed:
                    return             # consumer hits the error at .result()
                plan = self._data.plan_train_batch(i + 1)
                cursor = self._data.get_cursor() \
                    if hasattr(self._data, "get_cursor") else {}
                fut = pool.submit(
                    lambda p: self._maybe_put(self._data.materialize(p)),
                    plan)
                fut.add_done_callback(on_done)
                if stop.is_set():
                    return
                q.put((fut, cursor))   # bounded: blocks at depth+n_workers

    def _producer_windows(self, n_batches: int, q: queue.Queue,
                          stop: threading.Event) -> None:
        """Window-granular producer: k sequential draws, one host stack,
        one mesh staging per window — all OFF the consumer thread, so
        ``train_iter`` dequeues a mesh-resident window and dispatches
        immediately.  Leftover batches < k roll to the next epoch's
        shuffle (the worker loop's ``n_batch_train // spc`` drop-last
        convention).  When the wrapped data exposes the plan/materialize
        split and ``n_workers > 1``, a window's k batches materialize
        concurrently in the pool (plans stay sequential — the batch
        stream is bit-identical to the serial path)."""
        from concurrent.futures import ThreadPoolExecutor
        k = self.window
        pooled = self.n_workers > 1 and hasattr(self._data,
                                                "plan_train_batch")
        pool = ThreadPoolExecutor(self.n_workers) if pooled else None
        tm = telemetry.active()
        try:
            for w in range(n_batches // k):
                if stop.is_set():
                    return
                t0 = time.time()
                if pooled:
                    plans = [self._data.plan_train_batch(w * k + j + 1)
                             for j in range(k)]
                    futs = [pool.submit(self._data.materialize, p)
                            for p in plans]
                    batches = [f.result() for f in futs]  # re-raises, ordered
                else:
                    batches = [self._data.next_train_batch(w * k + j + 1)
                               for j in range(k)]
                cursor = self._data.get_cursor() \
                    if hasattr(self._data, "get_cursor") else {}
                window = self._stage(_stack_host(batches))
                if tm.enabled:
                    tm.observe("prefetch.produce_secs", time.time() - t0)
                if stop.is_set():     # restart raced the stage: drop
                    return
                t0 = time.time()
                q.put((window, cursor))
                if tm.enabled:
                    tm.observe("prefetch.producer_blocked_secs",
                               time.time() - t0)
        finally:
            if pool is not None:
                pool.shutdown(wait=False)

    def _stage(self, window):
        return self._stage_window_fn(window) if self._stage_window_fn \
            else window

    def _maybe_put(self, batch):
        return self._device_put_fn(batch) if self._device_put_fn else batch

    def _shutdown(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            try:                      # drain so the producer can observe stop
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            # Best effort: a producer stuck >5s in one load stays orphaned,
            # but its own stop event is set and it holds the OLD queue, so it
            # can neither feed the restarted pipeline nor be revived.
            self._thread.join(timeout=5)
        self._thread = None
        self._q = None

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass
