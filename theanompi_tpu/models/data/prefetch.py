"""Background prefetch pipeline — the parallel loader.

TPU-native rebuild of Theano-MPI's flagship data-pipeline feature
(SURVEY.md §2.8): the reference spawned a child process per worker via
``MPI.COMM_SELF.Spawn`` that loaded the next ``.hkl`` batch, augmented it on
CPU, and wrote it straight into the trainer's GPU input buffer through a CUDA
IPC handle, overlapping I/O+augment with compute behind a per-batch
handshake.

On TPU the IPC trick is unnecessary: a background thread runs the (host,
numpy) load+augment for the NEXT batches while the device computes, and
``jax.device_put`` streams the result to the chips asynchronously.  The
"icomm barrier" handshake becomes a bounded queue: depth 2 = classic double
buffering.

Wrap any data object:  ``data = PrefetchLoader(Cifar10_data(cfg))`` — the
wrapper exposes the same duck-typed surface (``next_train_batch``,
``next_val_batch``, ``shuffle_data``, ``n_batch_train``, ``n_batch_val``), so
``para_load`` is a config flag exactly as in the reference.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional


class PrefetchLoader:
    """Double-buffered background loader over any DataBase-shaped object.

    ``n_workers > 1`` (round-4, SURVEY §7 "input pipeline at AlexNet
    speeds"): when the wrapped data object exposes the ``plan_train_batch``
    / ``materialize`` split (``ImageNet_data``), the producer draws plans
    SEQUENTIALLY (cursor + augmentation RNG stay exact) and a thread pool
    materializes several in flight — disk reads and the native augment
    release the GIL, so file-based pipelines scale near-linearly.  The
    bounded queue holds ordered futures: the batch STREAM is bit-identical
    to the serial path, whatever the pool size."""

    def __init__(self, data, depth: int = 2, device_put_fn=None,
                 n_workers: int = 1):
        self._data = data
        self.depth = depth
        self.n_workers = max(1, int(n_workers))
        self._device_put_fn = device_put_fn  # optional: stage host→device too
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        # per-producer stop event: a timed-out old producer must keep seeing
        # ITS stop flag set after a restart (a shared cleared Event would
        # revive it against the new queue / shared data object)
        self._stop: Optional[threading.Event] = None
        self._consumed_cursor: dict = {}

    # duck-typed passthrough surface ---------------------------------------
    @property
    def n_batch_train(self):
        return self._data.n_batch_train

    @property
    def n_batch_val(self):
        return self._data.n_batch_val

    @property
    def batch_size(self):
        return self._data.batch_size

    @property
    def global_batch(self):
        return self._data.global_batch

    def __getattr__(self, name):
        # duck-typed passthrough for anything the wrapper doesn't override
        # (img_mean/crop for the u8-wire device mean, synthetic, …) —
        # __getattr__ fires only for MISSING attributes, so the wrapper's
        # own surface wins.  Private/dunder lookups raise normally (also
        # prevents recursion before __init__ sets _data).
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._data, name)

    def shuffle_data(self, seed: int) -> None:
        """Reference cadence: called at epoch start; (re)starts the producer
        for one epoch's worth of train batches."""
        self._shutdown()
        self._data.shuffle_data(seed)
        self._restart_producer()

    # -- checkpoint cursor --------------------------------------------------
    # The producer runs AHEAD of training, so the wrapped data object's
    # cursor is up to ``depth`` batches past what the trainer has consumed.
    # Each queue item therefore carries the wrapped cursor as of *after* that
    # batch was generated; get_cursor reports the last consumed one, making
    # mid-epoch save/resume exact even with para_load on.

    def get_cursor(self):
        c = dict(self._consumed_cursor)
        # val batches are served synchronously on the consumer thread, so the
        # wrapped object's val_ptr is live and authoritative — the producer
        # snapshot only tracks the train stream
        if hasattr(self._data, "get_cursor"):
            c["val_ptr"] = self._data.get_cursor().get("val_ptr", 0)
        return c

    def set_cursor(self, cursor) -> None:
        self._shutdown()
        if hasattr(self._data, "set_cursor"):
            self._data.set_cursor(cursor)
        # else: cursor-less duck-typed data — resume degrades gracefully to
        # wherever the wrapped object stands (same contract as get_cursor's
        # empty dict)
        self._restart_producer()

    def _restart_producer(self) -> None:
        self._consumed_cursor = self._data.get_cursor() \
            if hasattr(self._data, "get_cursor") else {}
        n = self._data.n_batch_train
        # batches left in the current epoch (ptr%n == 0 → a fresh epoch)
        remaining = n - int(self._consumed_cursor.get("train_ptr", 0)) % n
        # pooled producer: the queue must hold one future per in-flight
        # materialization or q.put blocks the submit loop at depth+1 and
        # caps the effective pool (review finding)
        pooled = self.n_workers > 1 and hasattr(self._data,
                                                "plan_train_batch")
        self._q = queue.Queue(
            maxsize=self.depth + (self.n_workers if pooled else 0))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._producer, args=(remaining, self._q, self._stop),
            daemon=True)
        self._thread.start()

    def next_train_batch(self, count: int):
        if self._q is None:          # shuffle_data not called yet (smoke use)
            return self._maybe_put(self._data.next_train_batch(count))
        item = self._q.get()
        if isinstance(item, BaseException):
            raise item
        batch, cursor = item
        if hasattr(batch, "result"):     # pooled producer: an ordered future
            batch = batch.result()       # (re-raises materialize errors)
        # commit the cursor only AFTER the batch is in hand — a failed
        # materialize must not mark its batch consumed
        self._consumed_cursor = cursor
        return batch

    def next_val_batch(self, count: int):
        # Validation is per-epoch and cheap relative to training — served
        # synchronously (the reference's loader also only covered train).
        return self._maybe_put(self._data.next_val_batch(count))

    # producer -------------------------------------------------------------
    def _producer(self, n_batches: int, q: queue.Queue,
                  stop: threading.Event) -> None:
        # q/stop are THIS producer's own (not read from self): a restart
        # swaps self._q/_stop, and a slow old producer must neither feed the
        # new queue nor be revived by the new (cleared) event
        try:
            if self.n_workers > 1 and hasattr(self._data,
                                              "plan_train_batch"):
                self._producer_pooled(n_batches, q, stop)
                return
            for i in range(n_batches):
                if stop.is_set():
                    return
                batch = self._maybe_put(self._data.next_train_batch(i + 1))
                cursor = self._data.get_cursor() \
                    if hasattr(self._data, "get_cursor") else {}
                if stop.is_set():     # restart raced the load: drop, don't put
                    return
                q.put((batch, cursor))
        except BaseException as e:    # surface loader errors in the consumer
            q.put(e)

    def _producer_pooled(self, n_batches: int, q: queue.Queue,
                         stop: threading.Event) -> None:
        """Sequential plans, pooled materialization: at most ``depth``
        queued + ``n_workers`` executing batches in flight; the queue keeps
        plan order, so the stream equals the serial producer's exactly."""
        from concurrent.futures import ThreadPoolExecutor
        failed = []                    # any materialize error aborts the

        def on_done(f):                # epoch, matching the serial producer
            if not f.cancelled() and f.exception() is not None:
                failed.append(f)

        with ThreadPoolExecutor(self.n_workers) as pool:
            for i in range(n_batches):
                if stop.is_set() or failed:
                    return             # consumer hits the error at .result()
                plan = self._data.plan_train_batch(i + 1)
                cursor = self._data.get_cursor() \
                    if hasattr(self._data, "get_cursor") else {}
                fut = pool.submit(
                    lambda p: self._maybe_put(self._data.materialize(p)),
                    plan)
                fut.add_done_callback(on_done)
                if stop.is_set():
                    return
                q.put((fut, cursor))   # bounded: blocks at depth+n_workers

    def _maybe_put(self, batch):
        return self._device_put_fn(batch) if self._device_put_fn else batch

    def _shutdown(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            try:                      # drain so the producer can observe stop
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            # Best effort: a producer stuck >5s in one load stays orphaned,
            # but its own stop event is set and it holds the OLD queue, so it
            # can neither feed the restarted pipeline nor be revived.
            self._thread.join(timeout=5)
        self._thread = None
        self._q = None

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass
