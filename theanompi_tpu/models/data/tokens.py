"""Token-file dataset for the transformer family.

Real-data counterpart of ``transformer_lm.LMData``'s synthetic stream, in
the de-facto standard flat-token-file format (nanoGPT's ``train.bin`` /
``val.bin``: one raw little-endian token array per split; ``.npy`` accepted
too).  The files are memory-mapped — nothing is loaded until a batch
gathers its windows, so corpora far larger than RAM stream fine.

Integration is pure :class:`..DataBase`: a "sample" is a NON-OVERLAPPING
``seq_len+1`` token window, represented as a window id in the base class's
index arrays — the common-seed shuffle, multi-host contiguous sub-blocks,
and the exact-resume cursor all apply unchanged (reference semantics,
SURVEY.md §2.8); only ``_make_batch`` turns ids into gathered token
windows (one fancy-indexed mmap read, next-token targets shifted by one).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from . import DataBase


def _load_tokens(path_base: str, dtype) -> np.ndarray:
    """Memory-map ``<base>.bin`` (raw) or ``<base>.npy`` / ``<base>_tokens.npy``."""
    for p, loader in ((path_base + ".bin",
                       lambda p: np.memmap(p, dtype=dtype, mode="r")),
                      (path_base + ".npy",
                       lambda p: np.load(p, mmap_mode="r")),
                      (path_base + "_tokens.npy",
                       lambda p: np.load(p, mmap_mode="r"))):
        if os.path.exists(p):
            return loader(p)
    raise FileNotFoundError(
        f"no token file at {path_base}.bin/.npy/_tokens.npy")


class TokenFileData(DataBase):
    """``data_dir/train.bin`` + ``data_dir/val.bin`` next-token dataset."""

    def __init__(self, config: Optional[dict] = None, batch_size: int = 16,
                 seq_len: int = 64, vocab: Optional[int] = None):
        super().__init__(config, batch_size)
        self.seq_len = int(self.config.get("seq_len", seq_len))
        # the model passes its RESOLVED vocab so the out-of-range guard in
        # _make_batch always fires — relying on config['vocab'] alone missed
        # the class-default case, training silently wrong on an oversized
        # corpus via clamped embedding gathers
        v = self.config.get("vocab", vocab)
        self._vocab = int(v) if v is not None else None
        data_dir = self.config["data_dir"]
        dtype = np.dtype(self.config.get("token_dtype", "uint16"))
        self._toks = {
            True: _load_tokens(os.path.join(data_dir, "train"), dtype),
            False: _load_tokens(os.path.join(data_dir, "val"), dtype),
        }

        def n_windows(split):
            return max(0, (len(self._toks[split]) - 1) // self.seq_len)

        # DataBase's index arrays hold WINDOW IDS; _make_batch gathers them
        self.x_train = self.y_train = np.arange(n_windows(True))
        self.x_val = self.y_val = np.arange(n_windows(False))
        self._finalize()

    def _make_batch(self, ids, _ids, train: bool):
        toks = self._toks[train]
        starts = np.asarray(ids, dtype=np.int64) * self.seq_len
        seq = np.asarray(
            toks[starts[:, None] + np.arange(self.seq_len + 1)],
            dtype=np.int32)
        if self._vocab is not None:
            # jit-side embedding gathers CLAMP out-of-range ids — a corpus
            # tokenized with a larger vocabulary would train silently wrong
            mx = int(seq.max())
            assert mx < self._vocab, (
                f"token id {mx} >= vocab={self._vocab} — the corpus was "
                f"tokenized with a larger vocabulary than the model's")
        return {"x": np.ascontiguousarray(seq[:, :-1]),
                "y": np.ascontiguousarray(seq[:, 1:])}
