"""LSGAN — reference-path alias module (``theanompi/models/lsgan.py``,
SURVEY.md §2.7).  Implementation in :mod:`theanompi_tpu.models.gan`."""

from .gan import LSGAN

__all__ = ["LSGAN"]
