"""Decoder-only transformer language model.

Beyond-parity model family (the reference zoo, SURVEY.md §2.7, is CNN-only):
a GPT-style causal LM following the SAME duck-typed model contract as the
CNN zoo, so every rule/exchanger/worker/bench path drives it unchanged —
``rule.init(modelfile='theanompi_tpu.models.transformer_lm',
modelclass='TransformerLM')``.

Attention runs in-model over the full (replicated) sequence; the
sequence-SHARDED path for long contexts is ``ops/ring_attention.py``'s ring
algorithm on a 2-D data×seq mesh (same math, pinned equal in
``tests/test_ring_attention.py``).

Without a data dir it synthesizes a deterministic, genuinely learnable token
stream (noisy modular-increment chains) so convergence smokes run with zero
setup, like the CIFAR-10 synthetic fallback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import layers as L
from .data import DataBase
from .model_base import ModelBase


class LMData(DataBase):
    """Synthetic next-token-prediction data: x[t+1] = x[t] + 1 (mod V) with
    ``noise`` probability of a random token — learnable one-step rule."""

    def __init__(self, config=None, batch_size=16, seq_len=64, vocab=64,
                 n_train=1024, n_val=256, noise=0.05):
        super().__init__(config, batch_size)
        seq_len = int(self.config.get("seq_len", seq_len))
        vocab = int(self.config.get("vocab", vocab))
        n_train = int(self.config.get("synthetic_train", n_train))
        n_val = int(self.config.get("synthetic_val", n_val))
        noise = float(self.config.get("noise", noise))

        def make(n, seed):
            r = np.random.RandomState(seed)
            start = r.randint(0, vocab, (n, 1))
            seq = (start + np.arange(seq_len + 1)) % vocab
            flip = r.rand(n, seq_len + 1) < noise
            seq = np.where(flip, r.randint(0, vocab, seq.shape), seq)
            return seq.astype(np.int32)

        self._train_seq = make(n_train, 101)
        self._val_seq = make(n_val, 202)
        # DataBase bookkeeping keys off x/y arrays
        self.x_train = self._train_seq[:, :-1]
        self.y_train = self._train_seq[:, 1:]
        self.x_val = self._val_seq[:, :-1]
        self.y_val = self._val_seq[:, 1:]
        self._finalize()

    def _make_batch(self, x, y, train):
        # token ids stay int32 (the base class casts images to float32)
        return {"x": np.ascontiguousarray(x, dtype=np.int32),
                "y": np.ascontiguousarray(y, dtype=np.int32)}


class Block(L.Layer):
    """Pre-LN transformer block: LN→MHA→residual, LN→MLP→residual.

    ``tp > 1`` (tensor parallelism, ``parallel/tp.py``): the attention is
    head-sharded and the MLP column→row-parallel over the ``'model'`` mesh
    axis — same init and math as the dense block (pinned equal in
    ``tests/test_tp.py``), two psums per block."""

    has_state = False
    supports_kv_decode = True     # apply_prefill/apply_decode work (dense)

    def __init__(self, dim, n_head, mlp_ratio=4, cd=jnp.bfloat16, tp=1,
                 sp=1, attn_impl="reference", name="block"):
        from ..parallel import tp as tplib
        self.name = name
        self.tp = tp
        self.ln1 = L.LayerNorm(dim, name="ln1")
        if tp > 1 and sp > 1:
            # 3-D data×seq×model: local heads (tp) over local token blocks
            # (sp) — ring attention on the head shard, row-parallel out psum
            assert attn_impl == "reference", (
                f"attn_impl={attn_impl!r} does not apply under sp>1 "
                "(sequence-sharded attention is the ring kernel)")
            from ..parallel.sp import TPRingMultiHeadAttention
            self.attn = TPRingMultiHeadAttention(dim, n_head, tp,
                                                 compute_dtype=cd,
                                                 name="attn")
        elif tp > 1:
            self.attn = tplib.TPMultiHeadAttention(dim, n_head, tp,
                                                   compute_dtype=cd,
                                                   attn_impl=attn_impl,
                                                   name="attn")
        elif sp > 1:
            # sequence-sharded activations: ring attention over 'seq' — the
            # blockwise accumulate is its own kernel, so a flash request
            # must fail fast rather than silently measure the ring path
            assert attn_impl == "reference", (
                f"attn_impl={attn_impl!r} does not apply under sp>1 "
                "(sequence-sharded attention is the ring kernel)")
            from ..parallel.sp import RingMultiHeadAttention
            self.attn = RingMultiHeadAttention(dim, n_head, compute_dtype=cd,
                                               name="attn")
        else:
            self.attn = L.MultiHeadAttention(dim, n_head, compute_dtype=cd,
                                             attn_impl=attn_impl,
                                             name="attn")
        self.ln2 = L.LayerNorm(dim, name="ln2")
        # fc1 is column-parallel under tp: a plain FC applied to the local
        # weight shard IS the column-parallel layer (only the spec differs)
        self.fc1 = L.FC(dim, mlp_ratio * dim, w_init=("normal", 0.02),
                        activation="relu", compute_dtype=cd, name="fc1")
        fc2_cls = tplib.RowFC if tp > 1 else L.FC
        self.fc2 = fc2_cls(mlp_ratio * dim, dim, w_init=("normal", 0.02),
                           activation=None, compute_dtype=cd, name="fc2")

    def specs(self):
        """Per-leaf PartitionSpecs over the 'model' axis (None when dense)."""
        if self.tp == 1:
            return None
        from jax.sharding import PartitionSpec as P
        from ..parallel.mesh import MODEL_AXIS as M
        ln = {"scale": P(), "bias": P()}
        col = {"w": P(None, M), "b": P(M)}
        return {"ln1": ln, "ln2": ln,
                "attn": {"wq": P(None, M), "wk": P(None, M),
                         "wv": P(None, M), "wo": P(M, None)},
                "fc1": col, "fc2": {"w": P(M, None), "b": P()}}

    def init(self, key):
        ks = jax.random.split(key, 5)
        return {"ln1": self.ln1.init(ks[0]), "attn": self.attn.init(ks[1]),
                "ln2": self.ln2.init(ks[2]), "fc1": self.fc1.init(ks[3]),
                "fc2": self.fc2.init(ks[4])}

    def apply(self, params, x, *, train=False, rng=None, state=None):
        h = self.ln1.apply(params["ln1"], x)
        x = x + self.attn.apply(params["attn"], h, train=train)
        return x + self._mlp(params, self.ln2.apply(params["ln2"], x))

    def _mlp(self, params, h):
        h = self.fc1.apply(params["fc1"], h)
        return self.fc2.apply(params["fc2"], h)

    def apply_prefill(self, params, x):
        """Forward + the attention K/V cache (dense decode path)."""
        h = self.ln1.apply(params["ln1"], x)
        a, cache = self.attn.apply_prefill(params["attn"], h)
        x = x + a
        return x + self._mlp(params, self.ln2.apply(params["ln2"], x)), cache

    def apply_decode(self, params, x1, cache, pos):
        h = self.ln1.apply(params["ln1"], x1)
        a, cache = self.attn.apply_decode(params["attn"], h, cache, pos)
        x1 = x1 + a
        return (x1 + self._mlp(params, self.ln2.apply(params["ln2"], x1)),
                cache)


class MoEBlock(Block):
    """Transformer block whose MLP is a Switch-style top-1 mixture of
    experts (``parallel/moe.py``), expert-parallel over ``'model'`` when
    ``ep > 1``.  ``apply`` returns ``(y, aux)`` — the load-balance loss rides
    up to the model's loss head."""

    def __init__(self, dim, n_head, n_experts, mlp_ratio=4, cd=jnp.bfloat16,
                 tp=1, sp=1, capacity_factor=1.25, top_k=1,
                 attn_impl="reference", name="moe_block"):
        # attention (and its specs) come from Block; tp doubles as the
        # expert-parallel degree — both shard over the same 'model' axis.
        # sp>1 (round-4): tokens are sequence-sharded — with tp==1 the
        # experts shard over 'seq' instead (all-to-all dispatch,
        # parallel/moe.py); with tp>1 they stay on 'model' and only the
        # aux statistic averages over 'seq'.
        super().__init__(dim, n_head, mlp_ratio=mlp_ratio, cd=cd, tp=tp,
                         sp=sp, attn_impl=attn_impl, name=name)
        from ..parallel.moe import MoE
        self.moe = MoE(dim, n_experts, mlp_ratio=mlp_ratio, ep=tp,
                       seq_shards=sp, top_k=top_k,
                       capacity_factor=capacity_factor, compute_dtype=cd,
                       name="moe")
        del self.fc1, self.fc2

    def init(self, key):
        ks = jax.random.split(key, 4)
        return {"ln1": self.ln1.init(ks[0]), "attn": self.attn.init(ks[1]),
                "ln2": self.ln2.init(ks[2]), "moe": self.moe.init(ks[3])}

    def specs(self):
        s = super().specs()
        ms = self.moe.specs()
        if s is None and ms is None:
            return None
        if s is None:
            # dense attention under sp-sharded experts: attention/LN leaves
            # replicate, only the expert tables shard (over 'seq') — derive
            # the replicated skeleton from the real param structure
            from jax.sharding import PartitionSpec as P

            def skel(layer):
                return jax.tree.map(lambda _: P(), jax.eval_shape(
                    layer.init, jax.random.key(0)))

            s = {"ln1": skel(self.ln1), "ln2": skel(self.ln2),
                 "attn": skel(self.attn)}
        else:
            del s["fc1"], s["fc2"]
        s["moe"] = ms
        return s

    def apply(self, params, x, *, train=False, rng=None, state=None):
        h = self.ln1.apply(params["ln1"], x)
        x = x + self.attn.apply(params["attn"], h, train=train)
        h = self.ln2.apply(params["ln2"], x)
        y, aux = self.moe.apply(params["moe"], h, train=train)
        return x + y, aux

    # the MoE FFN is per-token (routing included), so the KV-decode path
    # works like the dense block's — the load-balance aux is a TRAINING
    # statistic and is discarded at inference
    supports_kv_decode = True

    def _mlp(self, params, h):
        y, _aux = self.moe.apply(params["moe"], h)
        return y


class TransformerLM(ModelBase):
    batch_size = 16
    epochs = 10
    n_subb = 1
    learning_rate = 3e-3
    optimizer = "adam"
    weight_decay = 0.0
    momentum = 0.9
    vocab = 64
    d_model = 128
    n_head = 4
    n_layer = 2
    seq_len = 64

    tp = 1          # tensor-parallel degree (mesh gains a 'model' axis)
    pp = 1          # pipeline-parallel degree (mesh gains a 'pipe' axis)
    sp = 1          # sequence-parallel degree (mesh gains a 'seq' axis)
    pp_microbatches = 0   # microbatches streamed per step (0 → 2·pp)
    pp_interleave = 1     # virtual layer chunks per pipeline stage (v):
    #   v>1 interleaves non-contiguous chunks so the pipeline bubble drops
    #   from (pp−1)/(M+pp−1) to (pp−1)/(v·M+pp−1) — parallel/pipeline.py

    def build_model(self) -> None:
        cd = self.config.get("compute_dtype", jnp.bfloat16)
        for k in ("vocab", "d_model", "n_head", "n_layer", "seq_len", "tp",
                  "pp", "sp", "pp_microbatches", "pp_interleave"):
            if k in self.config:
                setattr(self, k, int(self.config[k]))
        if self.sp > 1:
            from ..parallel.mesh import SEQ_AXIS
            assert self.mesh.shape.get(SEQ_AXIS) == self.sp, (
                f"sp={self.sp} needs a mesh with a '{SEQ_AXIS}' axis of "
                f"that size (worker_mesh(n, sp={self.sp})); got "
                f"{dict(self.mesh.shape)}")
            assert self.seq_len % self.sp == 0, (
                f"seq_len={self.seq_len} not divisible by sp={self.sp}")
        if self.pp > 1:
            from ..parallel.mesh import PIPE_AXIS
            assert self.mesh.shape.get(PIPE_AXIS) == self.pp, (
                f"pp={self.pp} needs a mesh with a '{PIPE_AXIS}' axis of "
                f"that size (worker_mesh(n, pp={self.pp})); got "
                f"{dict(self.mesh.shape)}")
            assert self.n_layer % self.pp == 0, (
                f"n_layer={self.n_layer} not divisible by pp={self.pp}")
            if not self.pp_microbatches:
                self.pp_microbatches = 2 * self.pp
        if self.pp_interleave > 1:
            # interleaved virtual stages: v chunks of L/(pp·v) layers per
            # device — pipeline_apply re-validates at trace time; fail at
            # build time with the config knobs named
            if self.pp == 1:
                raise ValueError(
                    f"pp_interleave={self.pp_interleave} needs pipeline "
                    f"parallelism — set the 'pp' config knob > 1 (got "
                    f"pp={self.pp})")
            if self.n_layer % (self.pp * self.pp_interleave):
                raise ValueError(
                    f"n_layer={self.n_layer} not divisible by "
                    f"pp*pp_interleave={self.pp * self.pp_interleave} "
                    f"(config knobs 'n_layer', 'pp', 'pp_interleave')")
            if self.pp_microbatches % self.pp:
                raise ValueError(
                    f"pp_microbatches={self.pp_microbatches} not divisible "
                    f"by pp={self.pp} — the interleaved schedule streams "
                    f"microbatches in groups of pp (config knob "
                    f"'pp_microbatches')")
        if self.tp > 1:
            from ..parallel import tp as tplib
            assert self.mesh.shape.get(tplib.MODEL_AXIS) == self.tp, (
                f"tp={self.tp} needs a mesh with a '{tplib.MODEL_AXIS}' axis "
                f"of that size (worker_mesh(n, tp={self.tp})); got "
                f"{dict(self.mesh.shape)}")
            self.embed = tplib.VocabParallelEmbedding(
                self.vocab, self.d_model, self.tp, compute_dtype=cd)
        else:
            self.embed = L.Embedding(self.vocab, self.d_model,
                                     compute_dtype=cd)
        self.pos = L.Embedding(self.seq_len, self.d_model, compute_dtype=cd,
                               name="pos")
        attn_impl = str(self.config.get("attn_impl", "reference"))
        if attn_impl == "flash":
            # fail at build time, not with an opaque Pallas lowering error
            assert self.seq_len % 128 == 0, (
                f"attn_impl='flash' needs seq_len a multiple of the "
                f"kernel's 128-wide blocks; got {self.seq_len}")
        self.blocks = [Block(self.d_model, self.n_head, cd=cd, tp=self.tp,
                             sp=self.sp, attn_impl=attn_impl,
                             name=f"block{i}")
                       for i in range(self.n_layer)]
        self.ln_f = L.LayerNorm(self.d_model, name="ln_f")
        # under tp the head is column-parallel over the VOCAB; the loss works
        # directly on the sharded logits (vocab-parallel cross-entropy)
        self.head = L.FC(self.d_model, self.vocab, w_init=("normal", 0.02),
                         activation=None, compute_dtype=cd, name="head")
        if self.config.get("data_dir"):
            # real corpus: nanoGPT-style flat token files, memory-mapped
            from .data.tokens import TokenFileData
            self.data = TokenFileData(self.config, self.batch_size,
                                      self.seq_len, vocab=self.vocab)
        else:
            self.data = LMData(self.config, self.batch_size)

    def param_specs(self):
        from jax.sharding import PartitionSpec as P
        if self.pp == 1 and self.tp == 1:
            blk = {b.name: b.specs() for b in self.blocks}
            if all(v is None for v in blk.values()):
                return None
            # sp-sharded MoE experts in an otherwise replicated model
            # (round-4 all-to-all dispatch): dense/attention leaves get a
            # replicated skeleton, expert tables their 'seq' specs
            def skel(b):
                struct = jax.eval_shape(b.init, jax.random.key(0))
                return jax.tree.map(lambda _: P(), struct)

            top = {"embed": {"w": P()}, "pos": {"w": P()},
                   "ln_f": {"scale": P(), "bias": P()},
                   "head": {"w": P(), "b": P()}}
            return {**top,
                    **{b.name: (blk[b.name] if blk[b.name] is not None
                                else skel(b)) for b in self.blocks}}
        if self.tp > 1:
            from ..parallel.mesh import MODEL_AXIS as M
            top = {"embed": {"w": P(M, None)},     # vocab-sharded table
                   "pos": {"w": P()},
                   "ln_f": {"scale": P(), "bias": P()},
                   "head": {"w": P(None, M), "b": P(M)}}
        else:
            top = {"embed": {"w": P()}, "pos": {"w": P()},
                   "ln_f": {"scale": P(), "bias": P()},
                   "head": {"w": P(), "b": P()}}
        if self.pp == 1:
            return {**top, **{blk.name: blk.specs()
                              for blk in self.blocks}}
        # pp: stacked [n_layer, ...] leaves, layer dim over stages — under
        # tp×pp the per-layer tp specs shift right by the stacking dim
        from ..parallel.mesh import PIPE_AXIS
        from ..parallel.steps import _is_spec
        blk = self.blocks[0].specs()
        if blk is None:
            struct = jax.eval_shape(self.blocks[0].init, jax.random.key(0))
            blk = jax.tree.map(lambda _: P(), struct)
        stacked = jax.tree.map(lambda s: P(PIPE_AXIS, *(s or ())), blk,
                               is_leaf=_is_spec)
        return {**top, "blocks": stacked}

    def init_params(self, key):
        ks = jax.random.split(key, len(self.blocks) + 4)
        p = {"embed": self.embed.init(ks[0]), "pos": self.pos.init(ks[1]),
             "ln_f": self.ln_f.init(ks[2]), "head": self.head.init(ks[3])}
        if self.pp > 1:
            # stack the per-layer params [n_layer, ...] from the SAME keys
            # the dense layout would use — pp=k and pp=1 are the same model.
            # The stack order is the interleaved stage permutation (identity
            # at pp_interleave=1): device r's contiguous 'pipe' shard rows
            # ARE its v virtual chunks, so pipeline_apply slices chunks
            # without any runtime gather
            from ..parallel import pipeline as pl
            perm = pl.stage_permutation(self.n_layer, self.pp,
                                        self.pp_interleave)
            p["blocks"] = jax.vmap(self.blocks[0].init)(ks[4:][perm])
            return p
        for i, blk in enumerate(self.blocks):
            p[blk.name] = blk.init(ks[4 + i])
        return p

    def init_bn_state(self):
        return {}

    def batch_spec(self):
        if self.sp > 1:
            from jax.sharding import PartitionSpec as P
            from ..parallel.mesh import SEQ_AXIS, WORKER_AXIS
            return P(WORKER_AXIS, SEQ_AXIS)    # [B rows, T tokens] both cut
        return None

    def _pos_ids(self, t):
        """Position ids for a [B, t] token block: global positions — under
        sp the block is this chip's SLICE of the sequence, offset by the
        seq rank (shared by every forward path, incl. the MoE subclass)."""
        pos_idx = jnp.arange(t)
        if self.sp > 1:
            from ..parallel.mesh import SEQ_AXIS
            pos_idx = pos_idx + jax.lax.axis_index(SEQ_AXIS) * t
        return pos_idx

    def apply_model(self, params, x, *, train, rng, state):
        t = x.shape[1]
        pos_idx = self._pos_ids(t)
        h = self.embed.apply(params["embed"], x) + \
            self.pos.apply(params["pos"], pos_idx)[None]
        if self.pp > 1:
            from ..parallel import pipeline as pl
            tpl = self.blocks[0]

            def stage_fn(stack, hm):
                def body(hh, lp):
                    return tpl.apply(lp, hh, train=train), None
                hh, _ = jax.lax.scan(body, hm, stack)
                return hh

            hm = pl.microbatch(h, self.pp_microbatches)
            hm = pl.pipeline_apply(stage_fn, params["blocks"], hm,
                                   interleave=self.pp_interleave)
            h = pl.unmicrobatch(hm)
        else:
            remat = train and self.config.get("remat", False)
            for blk in self.blocks:
                if remat:
                    # rematerialize each block on the backward pass —
                    # activation memory per block trades for recompute
                    # (jax.checkpoint; the pp path already remats per stage)
                    h = jax.checkpoint(
                        lambda p, x, _b=blk: _b.apply(p, x, train=True))(
                            params[blk.name], h)
                else:
                    h = blk.apply(params[blk.name], h, train=train)
        h = self.ln_f.apply(params["ln_f"], h)
        return self.head.apply(params["head"], h), state

    def loss_and_metrics(self, params, bn_state, batch, rng, train):
        logits, _ = self.apply_model(params, batch["x"], train=train,
                                     rng=rng, state=bn_state)
        v = logits.shape[-1]
        flat = logits.reshape(-1, v)
        y = batch["y"].reshape(-1)
        ls = self._label_smoothing(train)
        if self.tp > 1:
            from ..parallel import tp as tplib
            cost = tplib.tp_softmax_cross_entropy(flat, y,
                                                  label_smoothing=ls)
            err = tplib.tp_errors(flat, y)
        else:
            cost = L.softmax_cross_entropy(flat, y, ls)
            err = L.errors(flat, y)
        if self.sp > 1:
            # per-token means are over the LOCAL token block; average the
            # equal-sized blocks over 'seq' (composes with the tp
            # vocab-parallel CE above: the two reductions are orthogonal)
            from ..parallel.sp import sp_mean
            cost, err = sp_mean(cost), sp_mean(err)
        return cost, (err, bn_state)

    def val_metrics(self, params, bn_state, batch):
        logits, _ = self.apply_model(params, batch["x"], train=False,
                                     rng=None, state=bn_state)
        v = logits.shape[-1]
        flat = logits.reshape(-1, v)
        y = batch["y"].reshape(-1)
        if self.tp > 1:
            from ..parallel import tp as tplib
            cost = tplib.tp_softmax_cross_entropy(flat, y)
            err = tplib.tp_errors(flat, y)
            err5 = tplib.tp_errors_top_x(flat, y, 5)
        else:
            cost = L.softmax_cross_entropy(flat, y)
            err, err5 = L.errors(flat, y), L.errors_top_x(flat, y, 5)
        if self.sp > 1:
            from ..parallel.sp import sp_mean
            cost, err, err5 = sp_mean(cost), sp_mean(err), sp_mean(err5)
        return cost, (err, err5)


    # -- inference ---------------------------------------------------------

    def generate(self, prompt, max_new_tokens: int, temperature: float = 0.0,
                 seed: int = 0, kv_cache: bool = True, params=None):
        """Sample continuations — greedy (``temperature=0``) or categorical.

        One jit-compiled ``lax.scan`` over decode steps on a fixed
        ``[B, seq_len]`` token buffer (static shapes).  ``kv_cache=True``
        (default — dense AND MoE stacks; MoE routing is per-token and
        drop-free at inference): prefill the prompt once, then each step
        projects only the new token and attends to the cached K/V — O(T)
        per token instead of the full O(T²) forward.  ``kv_cache=False``
        keeps the full-forward sampler (pinned near-token-equal).
        Uses the canonical params (EASGD center / GoSGD consensus / BSP
        replica 0 / the EMA shadow) gathered to one device, so it works
        after training under any rule; model-parallel layouts (tp/pp/sp)
        gather the global params and sample through a single-device dense
        twin (same model — dense-parity-pinned).
        """
        if self.tp > 1 or self.pp > 1 or self.sp > 1:
            # model-parallel layouts: gather the global params and sample on
            # a DENSE single-device twin (the layouts are the same model —
            # dense-parity-pinned — so the twin's forward IS this model's)
            return self._dense_twin().generate(
                prompt, max_new_tokens, temperature=temperature, seed=seed,
                kv_cache=kv_cache, params=self._gathered_dense_params())
        import numpy as np

        prompt = np.asarray(prompt, dtype=np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        b, p_len = prompt.shape
        assert p_len >= 1, "generate() needs at least one prompt token"
        assert max_new_tokens >= 1, "generate() needs max_new_tokens >= 1"
        assert p_len + max_new_tokens <= self.seq_len, (
            f"prompt {p_len} + {max_new_tokens} new tokens exceeds "
            f"seq_len={self.seq_len} (the position-embedding table)")

        if params is None:
            params = self.canonical_host_params()
        toks0 = np.zeros((b, self.seq_len), np.int32)
        toks0[:, :p_len] = prompt

        use_kv = kv_cache and all(
            getattr(b, "supports_kv_decode", False) for b in self.blocks)
        if getattr(self, "_gen_jit", None) is None:
            # bound methods + static max_new: jit's own cache memoizes per
            # length, one sampler object per model instance
            self._gen_jit = jax.jit(self._gen_body,
                                    static_argnames=("max_new",))
            self._gen_jit_kv = jax.jit(self._gen_body_kv,
                                       static_argnames=("max_new",))
        fn = self._gen_jit_kv if use_kv else self._gen_jit
        toks, new = fn(params, jnp.asarray(toks0),
                       jnp.int32(p_len), jax.random.key(seed),
                       jnp.float32(temperature),
                       max_new=int(max_new_tokens))
        return np.asarray(jax.device_get(new))

    def _gen_body(self, params, toks, start_pos, key, temp, *, max_new):
        def body(carry, _):
            toks, pos, key = carry
            logits, _ = self.apply_model(params, toks, train=False,
                                         rng=None, state={})
            row = jax.lax.dynamic_index_in_dim(
                logits, pos - 1, axis=1, keepdims=False)       # [B, V]
            nxt, key = self._next_token(row, key, temp)
            toks = jax.lax.dynamic_update_slice(toks, nxt[:, None], (0, pos))
            return (toks, pos + 1, key), nxt

        (toks, _, _), out = jax.lax.scan(body, (toks, start_pos, key), None,
                                         length=max_new)
        return toks, out.T              # [B, max_new]

    def _dense_twin(self):
        """A single-device tp=pp=sp=1 copy of this model (same dims/class),
        built once — the sampler target for model-parallel layouts."""
        if getattr(self, "_twin", None) is None:
            from ..parallel.mesh import worker_mesh
            cfg = {k: v for k, v in self.config.items()
                   if k not in ("mesh", "tp", "pp", "sp", "size", "rank",
                                "pp_microbatches", "pp_interleave",
                                "data_dir")}
            # the sampler never touches the twin's data object — keep its
            # synthetic stream (and memory) minimal instead of re-opening
            # the corpus or materializing the full synthetic arrays
            cfg.update(mesh=worker_mesh(1, devices=jax.devices()[:1]),
                       size=1, rank=0, verbose=False, batch_size=1,
                       synthetic_train=2, synthetic_val=2)
            self._twin = type(self)(cfg)
        return self._twin

    def _gathered_dense_params(self):
        """Global host params reshaped to the DENSE layout: tp/sp gathers
        are already dense-shaped; pp's stacked ``blocks`` leaves unstack
        into per-block subtrees."""
        params = self.canonical_host_params()
        if self.pp == 1:
            return params
        # copy before restructuring: before compile_iter_fns the host params
        # ARE self.params by reference — popping would corrupt the model
        params = dict(params)
        stacked = params.pop("blocks")
        # stacked row j holds depth-order layer perm[j] (interleaved layout;
        # identity at pp_interleave=1) — unstack through the inverse map
        from ..parallel import pipeline as pl
        perm = pl.stage_permutation(self.n_layer, self.pp,
                                    self.pp_interleave)
        inv = np.argsort(perm)
        for i in range(self.n_layer):
            j = int(inv[i])
            params[f"block{i}"] = jax.tree.map(lambda x: x[j], stacked)
        return params

    def _next_token(self, row, key, temp):
        """Greedy/categorical selection from one [B, V] logit row."""
        key, sub = jax.random.split(key)
        greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
        sampled = jax.random.categorical(
            sub, row.astype(jnp.float32) /
            jnp.maximum(temp, 1e-6)).astype(jnp.int32)
        return jnp.where(temp > 0, sampled, greedy), key

    def _gen_body_kv(self, params, toks, start_pos, key, temp, *, max_new):
        """KV-cache sampler: one prefill forward builds the per-layer K/V
        caches, then each decode step projects only the new token."""
        t = toks.shape[1]
        h = self.embed.apply(params["embed"], toks) + \
            self.pos.apply(params["pos"], jnp.arange(t))[None]
        caches = []
        for blk in self.blocks:
            h, cache = blk.apply_prefill(params[blk.name], h)
            caches.append(cache)
        # only the row at start_pos-1 is consumed — index BEFORE the [D, V]
        # head projection so prefill doesn't pay a full-buffer head matmul
        h_row = jax.lax.dynamic_index_in_dim(h, start_pos - 1, axis=1)
        row0 = self.head.apply(params["head"],
                               self.ln_f.apply(params["ln_f"], h_row))[:, 0]
        nxt0, key = self._next_token(row0, key, temp)
        toks = jax.lax.dynamic_update_slice(toks, nxt0[:, None],
                                            (0, start_pos))

        def body(carry, _):
            toks, pos, key, caches, tok = carry
            x1 = self.embed.apply(params["embed"], tok[:, None]) + \
                self.pos.apply(params["pos"], pos[None])[None]
            new_caches = []
            for blk, cache in zip(self.blocks, caches):
                x1, cache = blk.apply_decode(params[blk.name], x1, cache,
                                             pos)
                new_caches.append(cache)
            x1 = self.ln_f.apply(params["ln_f"], x1)
            row = self.head.apply(params["head"], x1)[:, 0]
            nxt, key = self._next_token(row, key, temp)
            toks = jax.lax.dynamic_update_slice(toks, nxt[:, None],
                                                (0, pos + 1))
            return (toks, pos + 1, key, tuple(new_caches), nxt), nxt

        (toks, _, _, _, _), rest = jax.lax.scan(
            body, (toks, start_pos, key, tuple(caches), nxt0), None,
            length=max_new - 1)
        out = jnp.concatenate([nxt0[:, None], rest.T], axis=1)
        return toks, out                # [B, max_new]


class MoETransformerLM(TransformerLM):
    """Sparse-FFN variant: every ``moe_every``-th block's MLP is a Switch
    top-1 mixture of ``moe_experts`` experts (``parallel/moe.py``).  Under
    ``tp > 1`` the experts are SHARDED over the ``'model'`` axis (expert
    parallelism) while attention stays tensor-parallel on the same axis.
    The Switch load-balance loss is added to the objective with coefficient
    ``moe_aux`` and surfaced per-step via ``current_info``-style cost."""

    moe_experts = 4
    moe_every = 2          # every k-th block is MoE (1 = all blocks)
    moe_topk = 1           # experts per token (2 = GShard-style top-2)
    moe_aux = 0.01
    capacity_factor = 1.25

    def build_model(self) -> None:
        super().build_model()
        cd = self.config.get("compute_dtype", jnp.bfloat16)
        for k in ("moe_experts", "moe_every", "moe_topk"):
            if k in self.config:
                setattr(self, k, int(self.config[k]))
        assert self.pp == 1 or self.moe_every == 1, (
            "pipeline parallelism needs a homogeneous block stack: the "
            "mixed MoE/dense stack (moe_every > 1) does not stack over "
            "'pipe'; use moe_every=1 (every block MoE) with pp")
        for k in ("moe_aux", "capacity_factor"):
            if k in self.config:
                setattr(self, k, float(self.config[k]))
        if self.tp > 1:
            assert self.moe_experts % self.tp == 0, (
                f"moe_experts={self.moe_experts} not divisible by "
                f"tp/ep={self.tp}")
        if self.sp > 1 and self.tp == 1:
            assert self.moe_experts % self.sp == 0, (
                f"moe_experts={self.moe_experts} not divisible by "
                f"sp={self.sp} (experts shard over 'seq')")
        attn_impl = str(self.config.get("attn_impl", "reference"))
        self.blocks = [
            MoEBlock(self.d_model, self.n_head, self.moe_experts, cd=cd,
                     tp=self.tp, sp=self.sp,
                     capacity_factor=self.capacity_factor,
                     top_k=self.moe_topk,
                     attn_impl=attn_impl, name=f"block{i}")
            if (i + 1) % self.moe_every == 0 else
            Block(self.d_model, self.n_head, cd=cd, tp=self.tp, sp=self.sp,
                  attn_impl=attn_impl, name=f"block{i}")
            for i in range(self.n_layer)]

    def _forward(self, params, x, *, train):
        t = x.shape[1]
        h = self.embed.apply(params["embed"], x) + \
            self.pos.apply(params["pos"], self._pos_ids(t))[None]
        if self.pp > 1:
            # homogeneous all-MoE stack over 'pipe': each stage's aux rides
            # the pipeline (bubble ticks masked), normalized to the dense
            # layout's mean-aux-per-layer
            from ..parallel import pipeline as pl
            tpl = self.blocks[0]

            def stage_fn(stack, hm):
                def body(carry, lp):
                    hh, aux = carry
                    y, a = tpl.apply(lp, hh, train=train)
                    return (y, aux + a), None

                # zero scalar derived from ONE element of hm so the scan
                # carry inherits its full set of varying mesh axes (fresh
                # zeros would be device-invariant and fail the carry typing;
                # a full-tensor reduce would pay O(mb·T·D) per tick)
                aux0 = hm.reshape(-1)[0].astype(jnp.float32) * 0
                (hh, aux), _ = jax.lax.scan(body, (hm, aux0), stack)
                return hh, aux

            hm = pl.microbatch(h, self.pp_microbatches)
            hm, aux_sum = pl.pipeline_apply(stage_fn, params["blocks"], hm,
                                            with_aux=True,
                                            interleave=self.pp_interleave)
            h = pl.unmicrobatch(hm)
            # KNOWN DEVIATION from the dense layout: this is the mean of
            # per-MICROBATCH load-balance losses, not the aux of the full
            # batch's routing fractions — microbatch f_e/P_e are noisier, so
            # the pp objective differs slightly from dense (the main loss is
            # pinned equal; the aux parity claim is scoped to dense/tp/ep)
            aux = aux_sum / (self.pp_microbatches * self.n_layer)
            if self.sp > 1:
                # each microbatch aux is seq-invariant (pmean'd in the MoE
                # layer) but the scan carry was seeded from a seq-VARYING
                # zero for its axis typing — re-anchor bit-exactly so the
                # loss out-spec sees the invariance
                from ..parallel.mesh import SEQ_AXIS
                from ..parallel.steps import anchor_invariant
                aux = anchor_invariant(aux, (SEQ_AXIS,))
        else:
            aux = jnp.zeros((), jnp.float32)
            n_moe = 0
            for blk in self.blocks:
                out = blk.apply(params[blk.name], h, train=train)
                if isinstance(blk, MoEBlock):
                    h, a = out
                    aux = aux + a
                    n_moe += 1
                else:
                    h = out
            aux = aux / max(n_moe, 1)
        h = self.ln_f.apply(params["ln_f"], h)
        logits = self.head.apply(params["head"], h)
        return logits, aux

    def apply_model(self, params, x, *, train, rng, state):
        logits, _ = self._forward(params, x, train=train)
        return logits, state

    def loss_and_metrics(self, params, bn_state, batch, rng, train):
        logits, aux = self._forward(params, batch["x"], train=train)
        v = logits.shape[-1]
        flat = logits.reshape(-1, v)
        y = batch["y"].reshape(-1)
        ls = self._label_smoothing(train)
        if self.tp > 1:
            from ..parallel import tp as tplib
            cost = tplib.tp_softmax_cross_entropy(flat, y,
                                                  label_smoothing=ls)
            err = tplib.tp_errors(flat, y)
        else:
            cost = L.softmax_cross_entropy(flat, y, ls)
            err = L.errors(flat, y)
        if self.sp > 1:
            # per-token CE/err are over the local token block; the aux is
            # already seq-invariant (pmean'd inside the MoE layer)
            from ..parallel.sp import sp_mean
            cost, err = sp_mean(cost), sp_mean(err)
        return cost + self.moe_aux * aux, (err, bn_state)
