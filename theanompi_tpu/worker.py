"""Worker main loops.

TPU-native rebuild of Theano-MPI's per-rule worker files
(``theanompi/worker.py``, ``easgd_worker.py`` + ``easgd_server.py``,
``gosgd_worker.py`` — SURVEY.md §2.5, §3.1–3.3): the epoch/batch driver that
calls ``model.train_iter`` → ``exchanger.exchange`` → recorder, runs the
per-epoch validation loop, ``adjust_hyperp``, and checkpointing.

One class per rule, as in the reference; they differ only in which exchanger
they construct and its cadence.  There is no separate EASGD *server* process:
on SPMD TPU the center parameter store is replicated mesh state inside the
EASGD exchanger (SURVEY.md §7 "asynchrony on SPMD hardware") — a chip is not
burned on serving parameters.
"""

from __future__ import annotations

import time
from typing import Optional

from .base import MeshProcess
from .parallel.exchanger import get_exchanger
from .utils import devprof, numerics, telemetry, tracing
from .utils.recorder import Recorder
from .utils.sentry import TrainingSentry
from .utils.watchdog import StallWatchdog


def _jax_profiler():
    """Lazy jax.profiler handle (module-cached import — a dict hit per
    call, no backend work)."""
    import jax
    return jax.profiler


class Worker(MeshProcess):
    """Generic rule-driven worker (≙ reference ``BSP_Worker`` et al.)."""

    rule = "bsp"

    def __init__(self, config: Optional[dict] = None):
        super().__init__(config)
        self.get_internode_comm()
        self.init_device()
        # process-wide telemetry (utils/telemetry): on when record_dir is
        # set (or telemetry=true for in-memory metrics), else the inert
        # no-op; every component reads telemetry.active() lazily
        self.telemetry = telemetry.init(self.config)
        # causal tracing (docs/design.md §17): off unless tracing=true —
        # the exchanger's span stream + the wire propagation both gate on
        # the ONE tracer `enabled` check
        self.tracing = tracing.init(self.config)
        self.recorder = Recorder(self.config)
        self.recorder.telemetry = self.telemetry
        self.exchanger = get_exchanger(self.config.get("rule", self.rule),
                                       self.config)

    def run(self, model) -> Recorder:
        """The reference's ``run(model)`` epoch/batch loop (SURVEY.md §3.1)."""
        config = self.config
        # the compile recorder bucket: XLA compile on a cold start, the
        # executable-cache deserialize (~seconds) on a warm one — per-epoch
        # records then show compile going to ~0 on a cache-hit resume
        self.recorder.start()
        model.compile_iter_fns(self.exchanger)
        self.recorder.end("compile")
        self._log_compile_cache(model)
        if config.get("scale_lr", True) and self.size > 1:
            model.scale_lr(self.size)

        start_epoch = 0
        ckpt_dir = config.get("ckpt_dir")
        if ckpt_dir and config.get("resume", False):
            restored = model.load(ckpt_dir)
            if restored is not None:
                start_epoch = restored + 1
                if config.get("record_dir"):
                    # restore BOTH record lists (train + epoch) so the next
                    # save() rewrites the JSONL with the pre-resume lines
                    # intact — without this, Recorder.load()'s lossless
                    # round-trip never runs on the supervised-restart path
                    # it exists for
                    self.recorder.load(config["record_dir"])
                if self.verbose:
                    print(f"resumed from epoch {restored}", flush=True)

        # steps_per_call > 1: each train_iter dispatch covers several
        # steps; an epoch advances count by spc·(n_batch_train // spc)
        # (drop-last windows), NOT n_batch_train — the resume count must
        # replay the strided stream or the per-step rng fold desyncs from
        # the uninterrupted run when spc doesn't divide n_batch_train
        spc = max(1, int(getattr(model, "steps_per_call", 1)))
        count = start_epoch * ((model.data.n_batch_train // spc) * spc)
        epochs = config.get("epochs", model.epochs)
        # Timeline tracing (beyond the reference's wall-clock buckets,
        # SURVEY.md §5): trace_dir enables a jax.profiler capture of
        # trace_iters iterations starting at trace_start — view in
        # TensorBoard / Perfetto.
        trace_dir = config.get("trace_dir")
        trace_start = int(config.get("trace_start", 5))
        trace_iters = max(1, int(config.get("trace_iters", 5)))
        trace_pending = trace_dir is not None
        trace_stop_at = None

        def _stop_trace():
            nonlocal trace_stop_at
            import jax
            jax.block_until_ready(model.step_state["params"])
            jax.profiler.stop_trace()
            trace_stop_at = None
            if self.verbose:
                print(f"profiler trace saved to {trace_dir}", flush=True)
            # device-time attribution (utils/devprof): parse the capture
            # into compute/comm/exposed-comm/overlap and feed the device.*
            # gauges — the host-side phase.comm bracket goes blind once
            # collectives overlap backprop; this is the honest breakdown
            try:
                prof = devprof.profile_dir(trace_dir)
            except Exception as e:
                prof = None
                print(f"devprof: trace attribution failed ({e!r})",
                      flush=True)
            if prof is not None:
                if telem.enabled:
                    devprof.feed_telemetry(prof, telem)
                if self.verbose:
                    print(devprof.format_profile(prof, top=5), flush=True)
            if sentry is not None:
                # the block_until_ready + trace parse above is dead wall
                # time inside the next record's images/sec window — same
                # discontinuity as the val/ckpt boundary
                sentry.notice_discontinuity()

        t0 = time.time()
        # count strides by spc; leftover batches < spc roll to the next
        # epoch's shuffle, like the reference's drop-last batching.  When
        # compile_iter_fns fused the rule's exchange cadence into the
        # scanned dispatch (exchanger.fused), the Python exchange hook is
        # skipped outright — one XLA dispatch per k-step window covers the
        # steps AND their cadenced exchanges.
        fused = bool(getattr(self.exchanger, "fused", False))
        # failure detection (SURVEY §5): stall_timeout seconds without an
        # iteration completing → off-thread diagnostic (hung collectives /
        # transfers block the main thread inside jax, so detection can't
        # live on it).  0 (default) = off.  stall_action='exit' additionally
        # kills the process (exit code 42) after the dump so a supervisor
        # (launcher --supervise) can restart from the latest checkpoint —
        # only sane when the worker IS a subprocess; the in-process session
        # API should keep the default 'trace'.
        stall_action = str(config.get("stall_action", "trace"))
        assert stall_action in ("trace", "exit"), (
            f"unknown stall_action {stall_action!r}: use 'trace' "
            f"(diagnostic dump only) or 'exit' (kill for supervisor restart)")

        telem = self.telemetry
        # membership lease (parallel/membership.py): when lease_dir is set
        # this worker heartbeats wherever it beats the watchdog (every
        # iteration, every val batch) so an elastic controller can tell
        # dead/wedged from slow at ANY print cadence — the lease throttles
        # itself (min_interval_s), so the per-iteration cost is one
        # time.time() check, not a file write
        lease = None
        if config.get("lease_dir"):
            from .parallel.membership import WorkerLease
            lease = WorkerLease(config["lease_dir"],
                                int(config.get("rank", self.rank)),
                                telemetry_=telem)
            lease.beat(count)
        # training sentry (utils/sentry): NaN/inf + loss-spike + rolling
        # throughput-regression detection over the print-cadence records —
        # anomaly events + a flight dump instead of a silently sick run.
        # Costs nothing per step (it only sees what print_train_info
        # already materialized); on whenever telemetry is, sentry=false
        # opts out.
        sentry = None
        if telem.enabled and config.get("sentry", True):
            sentry = TrainingSentry(config, telem)
        self.sentry = sentry
        # live ops endpoint (utils/tracing, docs/design.md §17): a tiny
        # statusz socket answering health/uptime/current-span/last-events
        # queries over the wire framing, registered in the run dir so
        # scripts/fleetz.py can aggregate the whole fleet.  Idle cost is
        # zero (it only ever reads state other paths already maintain);
        # statusz=false opts out.
        statusz = None
        if telem.enabled and config.get("record_dir") and \
                config.get("statusz", True):
            statusz = tracing.StatuszServer(
                "worker", ident=int(config.get("rank", self.rank)),
                run_dir=config["record_dir"], telemetry_=telem,
                tracer_=self.tracing)
            statusz.start()
        # fleet health plane (utils/fleetmon, docs/design.md §20): a
        # low-rate daemon thread streaming metric snapshots (phase
        # p50/p99, img/s, HBM headroom, queue depth, wire health) to the
        # run's FleetCollector.  Never touches this hot loop — it reads
        # the registry the loop already feeds.
        streamer = None
        if telem.enabled and config.get("metrics_addr"):
            from .utils.fleetmon import MetricStreamer
            streamer = MetricStreamer(
                str(config["metrics_addr"]),
                rank=int(config.get("rank", self.rank)), role="worker",
                interval_s=float(config.get("metrics_interval_s", 1.0)),
                telemetry_=telem)
            streamer.start()

        def on_stall(elapsed, label):
            StallWatchdog._default_handler(watchdog, elapsed, label)
            if telem.enabled:
                # the flight ring holds the beats/phases leading into the
                # hang — dump it whether or not we are about to die
                telem.event("stall", elapsed=round(elapsed, 1), label=label,
                            action=stall_action)
                telem.dump_flight(reason=f"watchdog stall {elapsed:.0f}s "
                                         f"at {label}")
            if stall_action == "exit":
                import os
                if telem.enabled:
                    telem.close()
                print("WATCHDOG: stall_action=exit — terminating for "
                      "supervisor restart", flush=True)
                os._exit(42)

        watchdog = StallWatchdog(float(config.get("stall_timeout", 0)),
                                 on_stall=on_stall)
        if telem.enabled:
            telem.event("train_begin", rule=self.config.get("rule", self.rule),
                        model=type(model).__name__, spc=spc,
                        start_epoch=start_epoch, epochs=epochs,
                        size=self.size)
        try:
            with watchdog:
                for epoch in range(start_epoch, epochs):
                    model.adjust_hyperp(epoch)
                    model.data.shuffle_data(epoch + model.seed)
                    for _ in range(model.data.n_batch_train // spc):
                        count += spc
                        if trace_pending and count >= trace_start:
                            import jax
                            jax.profiler.start_trace(trace_dir)
                            trace_pending = False
                            # clamp the window to the dispatch stride:
                            # count advances by spc per iteration, so the
                            # old `count + 1 >= stop` check overshot by up
                            # to spc-1 iterations — round trace_iters up
                            # to whole windows instead
                            trace_stop_at = count + max(
                                1, (trace_iters + spc - 1) // spc) * spc
                        # dispatch anchor: a devprof capture counts these
                        # spans so per-dispatch attribution never guesses
                        # the iteration count from op repetitions (a
                        # TraceMe no-op while no profiler is active)
                        with _jax_profiler().TraceAnnotation(
                                devprof.TRAIN_DISPATCH_SPAN):
                            model.train_iter(count, self.recorder)
                        if not fused:
                            self.exchanger.exchange(self.recorder, count)
                        watchdog.beat(f"epoch {epoch} iter {count}")
                        if lease is not None:
                            lease.beat(count)
                        if trace_stop_at is not None and count + spc >= trace_stop_at:
                            _stop_trace()
                        rec = self.recorder.print_train_info(count,
                                                             stride=spc)
                        if rec and telem.enabled:
                            # periodic gauge snapshot at print cadence:
                            # device HBM in-use/peak, host RSS, iteration
                            # rate — the HBM-headroom and throughput
                            # timelines in telemetry_report
                            telem.system_snapshot(
                                iter=count, epoch=epoch,
                                images_per_sec=rec["images_per_sec"])
                        # numerics health plane (§25): materialize the
                        # device aux exactly when cost/error already
                        # materialize — the in-graph sampler added no
                        # host round-trip, and this one rides the print
                        # cadence the run pays anyway
                        n_report = None
                        if rec and telem.enabled and \
                                getattr(model, "numerics_aux",
                                        None) is not None:
                            import jax
                            n_report = numerics.host_report(
                                jax.device_get(model.numerics_aux))
                            numerics.record(
                                telem, n_report,
                                rank=int(config.get("rank", self.rank)))
                        if rec and sentry is not None:
                            sentry.observe_record(rec)
                            if n_report is not None:
                                sentry.observe_numerics(n_report)

                    model.begin_val()
                    for _ in range(model.data.n_batch_val):
                        model.val_iter(count, self.recorder)
                        watchdog.beat(f"epoch {epoch} val @ iter {count}")
                        if lease is not None:
                            lease.beat(count)
                    model.end_val()
                    self.recorder.print_val_info(count)

                    if ckpt_dir:
                        model.save(ckpt_dir, epoch, count)
                    if config.get("record_dir"):
                        self.recorder.save(config["record_dir"])
                    watchdog.beat(f"epoch {epoch} end (ckpt/records saved)")
                    if lease is not None:
                        lease.beat(count, epoch=epoch)
                    if sentry is not None:
                        # the next print record's images/sec spans this
                        # val pass + ckpt + shuffle wall time — not a
                        # throughput regression
                        sentry.notice_discontinuity()
        except BaseException as e:
            # crash: leave the flight-recorder trail (last N events — beats,
            # phase brackets, gauges) next to the records, then re-raise;
            # launcher --supervise sweeps the dumps aside before restarting
            if telem.enabled:
                telem.event("crash", error=repr(e)[:300])
                telem.dump_flight(reason=repr(e)[:200])
                telem.close()
            raise
        finally:
            # async_ckpt: a completed epoch's in-flight write must land even
            # when an exception (or Ctrl-C) unwinds the loop — the daemon
            # writer would otherwise die mid-np.savez, truncating the file
            if hasattr(model, "wait_pending_ckpt"):
                import sys as _sys
                # capture BEFORE the try: inside an except block exc_info
                # reports the caught exception, not the unwinding one
                unwinding = _sys.exc_info()[0] is not None
                try:
                    model.wait_pending_ckpt()
                except Exception as ckpt_exc:
                    if not unwinding:
                        raise       # sole failure: surface it
                    print(f"async checkpoint ALSO failed during unwind: "
                          f"{ckpt_exc!r}", file=_sys.stderr, flush=True)
            if statusz is not None:
                # only a CLEAN exit deregisters: a crash keeps the
                # discovery doc so fleetz lists this worker DOWN
                import sys as _sys2
                statusz.stop(deregister=_sys2.exc_info()[0] is None)
            if streamer is not None:
                # a clean exit retires this rank at the collector; a
                # crash leaves the stream silent so heartbeat_age alerts
                import sys as _sys3
                streamer.stop(final=_sys3.exc_info()[0] is None)
        if trace_stop_at is not None:   # window outlived training: flush it
            _stop_trace()
        if lease is not None:
            lease.release()     # clean departure: 'finished', not a death
        if telem.enabled:
            telem.event("train_end", secs=round(time.time() - t0, 3),
                        epochs=epochs - start_epoch)
            telem.close()       # flush the stream + write the summary sidecar
        if self.verbose:
            print(f"training finished in {time.time() - t0:.1f}s "
                  f"({epochs - start_epoch} epochs)", flush=True)
        return self.recorder


    def _log_compile_cache(self, model) -> None:
        """Startup line for the AOT executable cache (utils/compile_cache):
        per-program hit/miss + wall time, and the process counters — the
        at-a-glance evidence that a wedge-recovery restart or checkpoint
        resume deserialized instead of recompiling."""
        if not self.verbose:
            return
        cache = getattr(model, "compile_cache", None)
        info = getattr(model, "compile_info", None) or {}
        if cache is None or not cache.enabled:
            return
        parts = [f"{k}: {v['cache']}"
                 + (f" ({v['compile_secs']:.1f}s)"
                    if v.get("compile_secs") is not None else "")
                 for k, v in info.items()
                 if isinstance(v, dict) and "cache" in v]
        print(f"compile cache [{cache.describe()}] " + " | ".join(parts),
              flush=True)


class BSP_Worker(Worker):
    rule = "bsp"


class EASGD_Worker(Worker):
    rule = "easgd"


class ASGD_Worker(Worker):
    rule = "asgd"


class GOSGD_Worker(Worker):
    rule = "gosgd"


WORKERS = {
    "bsp": BSP_Worker,
    "easgd": EASGD_Worker,
    "asgd": ASGD_Worker,
    "gosgd": GOSGD_Worker,
}


def main(argv=None):
    """CLI entry: ``python -m theanompi_tpu.worker <rule> <modelfile>
    <modelclass> [key=value ...]`` — the per-rank command the reference's
    launcher composed into its ``mpirun`` line (SURVEY.md §2.6)."""
    import sys

    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 3:
        print("usage: python -m theanompi_tpu.worker <rule> <modelfile> "
              "<modelclass> [key=value ...]")
        return 1
    rule, modelfile, modelclass = argv[:3]
    config = {"rule": rule}
    for kv in argv[3:]:
        k, _, v = kv.partition("=")
        try:
            config[k] = int(v)
        except ValueError:
            try:
                config[k] = float(v)
            except ValueError:
                config[k] = {"true": True, "false": False}.get(v.lower(), v)
    worker = WORKERS[rule](config)
    model = worker.build_model(modelfile, modelclass)
    worker.run(model)
    return 0


if __name__ == "__main__":
    # a CLI worker owns its process: a fatal signal (supervisor kill,
    # scheduler preemption) dumps the flight recorder before dying.  The
    # in-process session API never installs these — host applications and
    # tests own their handlers (the hooks are no-ops while telemetry is
    # disabled, so installing before config parsing is safe).
    telemetry.install_signal_hooks()
    raise SystemExit(main())
