"""Launcher.

TPU-native rebuild of Theano-MPI's ``theanompi/launcher.py``
(SURVEY.md §2.6): the reference composed an ``mpirun -np N ... python -u -m
theanompi.<worker> <modelfile> <modelclass>`` command line (MPMD for EASGD's
server+workers) with per-rank ``THEANO_FLAGS`` env, spawned it, and forwarded
worker stdout.

On TPU there is nothing to spawn on a single host — one process drives all
local chips — so the local path simply runs the worker in-process.  For a
multi-host TPU pod slice the launcher composes the per-host command lines
(every host runs the SAME program under ``jax.distributed``; rank binding is
automatic), either printing them for ``gcloud compute tpus tpu-vm ssh
--worker=all --command=...`` or executing the local host's share.
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
from typing import List, Optional


def compose_worker_cmd(rule: str, modelfile: str, modelclass: str,
                       config_kv: List[str],
                       coordinator: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> List[str]:
    """Build the per-host worker command (≙ the reference's mpirun line)."""
    cmd = [sys.executable, "-u", "-m", "theanompi_tpu.worker",
           rule, modelfile, modelclass]
    if coordinator:
        cmd.append(f"coordinator_address={coordinator}")
    if num_processes:
        cmd.append(f"num_processes={num_processes}")
    if process_id is not None:
        cmd.append(f"process_id={process_id}")
    cmd.extend(config_kv)
    return cmd


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="theanompi_tpu.launcher",
        description="Launch distributed training (≙ Theano-MPI's mpirun "
                    "composition). Local: runs in-process over all chips. "
                    "--num-hosts>1: prints/executes per-host commands.")
    p.add_argument("--rule", default="bsp",
                   choices=["bsp", "easgd", "asgd", "gosgd"])
    p.add_argument("--modelfile", default="theanompi_tpu.models.cifar10")
    p.add_argument("--modelclass", default="Cifar10_model")
    p.add_argument("--n-workers", type=int, default=None,
                   help="chips to use on this host (default: all)")
    p.add_argument("--num-hosts", type=int, default=1)
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 (multi-host)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's index (multi-host exec mode)")
    p.add_argument("--emit-only", action="store_true",
                   help="print the per-host commands instead of executing")
    p.add_argument("--supervise", type=int, default=0, metavar="N",
                   help="run the worker as a supervised subprocess and "
                        "restart it (with resume=true) up to N times on "
                        "crash — pair with ckpt_dir for checkpoint-based "
                        "recovery (single-host)")
    p.add_argument("--min-uptime", type=float, default=0.0, metavar="SEC",
                   help="crash-loop guard: a nonzero exit within SEC "
                        "seconds is treated as unrecoverable (config/usage "
                        "error) and is NOT retried; 0 = always retry")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent AOT executable cache dir "
                        "(utils/compile_cache): compile_iter_fns "
                        "deserializes pre-built executables instead of "
                        "recompiling — pre-populate off-line with "
                        "scripts/prewarm_cache.py; supervised restarts and "
                        "checkpoint resumes then skip the XLA compile "
                        "(defaults to $THEANOMPI_COMPILE_CACHE if set)")
    p.add_argument("--record-dir", default=None, metavar="DIR",
                   help="record/telemetry directory (same as the "
                        "record_dir=DIR config key): recorder dumps, the "
                        "per-rank telemetry_rank*.jsonl event streams, and "
                        "crash flight recordings all land here — report "
                        "with scripts/telemetry_report.py DIR")
    p.add_argument("config", nargs="*", help="key=value model/worker config")
    args = p.parse_args(argv)

    kv = list(args.config)
    if args.n_workers:
        kv.append(f"n_workers={args.n_workers}")
    if args.compile_cache and \
            not any(c.startswith("compile_cache=") for c in kv):
        kv.append(f"compile_cache={args.compile_cache}")
    if args.record_dir and \
            not any(c.startswith("record_dir=") for c in kv):
        kv.append(f"record_dir={args.record_dir}")
    record_dir = next((c.partition("=")[2] for c in kv
                       if c.startswith("record_dir=")), None)
    if record_dir and not any(c.startswith("run_id=") for c in kv):
        # one run id for every host/restart of this launch: per-rank
        # telemetry streams (utils/telemetry) then correlate into one run
        # for scripts/telemetry_report.py
        import time as _t
        kv.append(f"run_id=run{int(_t.time())}")

    if args.num_hosts > 1:
        cmds = [compose_worker_cmd(args.rule, args.modelfile, args.modelclass,
                                   kv, args.coordinator, args.num_hosts, i)
                for i in range(args.num_hosts)]
        if args.emit_only or args.process_id is None:
            print("# run on each TPU host (e.g. via gcloud compute tpus "
                  "tpu-vm ssh --worker=all):")
            for i, c in enumerate(cmds):
                print(f"# host {i}:")
                print(shlex.join(c))
            return 0
        return subprocess.call(cmds[args.process_id])

    if args.supervise > 0:
        # Failure recovery (SURVEY §5): the worker runs as a subprocess so a
        # crash (or a watchdog-triggered exit) doesn't take the supervisor
        # down; each restart resumes from the latest per-epoch checkpoint.
        if not any(c.startswith("ckpt_dir=") for c in kv):
            print("warning: --supervise without ckpt_dir= restarts training "
                  "from scratch each time", file=sys.stderr)
        base = compose_worker_cmd(args.rule, args.modelfile, args.modelclass,
                                  kv)
        import time as _time

        def sweep(attempt: int, rc: int) -> None:
            # a dead worker's flight recordings (utils/telemetry dumps
            # flight_rank*.jsonl into record_dir on crash/stall-exit) are
            # moved aside per attempt, so the restart's own eventual dumps
            # can't overwrite the trail that explains THIS death
            if not record_dir:
                return
            from .utils.telemetry import sweep_flight_dumps
            dest = sweep_flight_dumps(record_dir,
                                      f"attempt{attempt}_rc{rc}")
            if dest:
                print(f"swept flight recordings to {dest}", file=sys.stderr)

        rc = 1
        for attempt in range(args.supervise + 1):
            cmd = base if attempt == 0 else base + ["resume=true"]
            t0 = _time.monotonic()
            rc = subprocess.call(cmd)
            if rc == 0:
                return 0
            sweep(attempt, rc)
            uptime = _time.monotonic() - t0
            if args.min_uptime and uptime < args.min_uptime:
                print(f"worker exited rc={rc} after only {uptime:.1f}s "
                      f"(< --min-uptime {args.min_uptime}s) — treating as "
                      f"unrecoverable, not retrying", file=sys.stderr)
                return rc
            if attempt < args.supervise:
                print(f"worker exited rc={rc}; restarting "
                      f"({attempt + 1}/{args.supervise})", file=sys.stderr)
        return rc

    # single host: in-process (no spawn needed — the mesh IS the workers)
    from .worker import main as worker_main
    return worker_main([args.rule, args.modelfile, args.modelclass] + kv)


if __name__ == "__main__":
    raise SystemExit(main())
