"""Launcher.

TPU-native rebuild of Theano-MPI's ``theanompi/launcher.py``
(SURVEY.md §2.6): the reference composed an ``mpirun -np N ... python -u -m
theanompi.<worker> <modelfile> <modelclass>`` command line (MPMD for EASGD's
server+workers) with per-rank ``THEANO_FLAGS`` env, spawned it, and forwarded
worker stdout.

On TPU there is nothing to spawn on a single host — one process drives all
local chips — so the local path simply runs the worker in-process.  For a
multi-host TPU pod slice the launcher composes the per-host command lines
(every host runs the SAME program under ``jax.distributed``; rank binding is
automatic), either printing them for ``gcloud compute tpus tpu-vm ssh
--worker=all --command=...`` or executing the local host's share.
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
from typing import List, Optional


def compose_worker_cmd(rule: str, modelfile: str, modelclass: str,
                       config_kv: List[str],
                       coordinator: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> List[str]:
    """Build the per-host worker command (≙ the reference's mpirun line)."""
    cmd = [sys.executable, "-u", "-m", "theanompi_tpu.worker",
           rule, modelfile, modelclass]
    if coordinator:
        cmd.append(f"coordinator_address={coordinator}")
    if num_processes:
        cmd.append(f"num_processes={num_processes}")
    if process_id is not None:
        cmd.append(f"process_id={process_id}")
    cmd.extend(config_kv)
    return cmd


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="theanompi_tpu.launcher",
        description="Launch distributed training (≙ Theano-MPI's mpirun "
                    "composition). Local: runs in-process over all chips. "
                    "--num-hosts>1: prints/executes per-host commands.")
    p.add_argument("--rule", default="bsp",
                   choices=["bsp", "easgd", "asgd", "gosgd"])
    p.add_argument("--modelfile", default="theanompi_tpu.models.cifar10")
    p.add_argument("--modelclass", default="Cifar10_model")
    p.add_argument("--n-workers", type=int, default=None,
                   help="chips to use on this host (default: all)")
    p.add_argument("--num-hosts", type=int, default=1)
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 (multi-host)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's index (multi-host exec mode)")
    p.add_argument("--emit-only", action="store_true",
                   help="print the per-host commands instead of executing")
    p.add_argument("--supervise", type=int, default=0, metavar="N",
                   help="run the worker as a supervised subprocess and "
                        "restart it (with resume=true) up to N times on "
                        "crash — pair with ckpt_dir for checkpoint-based "
                        "recovery (single-host); restarts back off "
                        "exponentially (--backoff) and a crash loop "
                        "(--crash-limit failures within --crash-window) "
                        "exits nonzero with the flight-recorder tail")
    p.add_argument("--min-uptime", type=float, default=0.0, metavar="SEC",
                   help="crash-loop guard: a nonzero exit within SEC "
                        "seconds is treated as unrecoverable (config/usage "
                        "error) and is NOT retried; 0 = always retry")
    p.add_argument("--backoff", type=float, default=1.0, metavar="SEC",
                   help="supervised-restart backoff base: delay before "
                        "restart N is min(SEC·2^N, --backoff-max) ±25%% "
                        "jitter (the bench probe-recovery pattern); "
                        "0 = immediate restarts")
    p.add_argument("--backoff-max", type=float, default=30.0, metavar="SEC",
                   help="supervised-restart backoff cap (default 30)")
    p.add_argument("--crash-limit", type=int, default=5, metavar="N",
                   help="crash-loop breaker: N worker failures within "
                        "--crash-window seconds exit nonzero immediately "
                        "with the flight-recorder tail printed instead of "
                        "burning the remaining restarts (default 5)")
    p.add_argument("--crash-window", type=float, default=300.0,
                   metavar="SEC",
                   help="crash-loop breaker window (default 300)")
    p.add_argument("--elastic", type=int, default=0, metavar="N",
                   help="elastic membership mode (easgd/asgd): spawn N "
                        "island workers around a center server under the "
                        "membership controller — dead/preempted workers "
                        "leave and rejoin WITHOUT stopping the run "
                        "(parallel/membership.py; BSP instead uses "
                        "--supervise world restarts)")
    p.add_argument("--elastic-steps", type=int, default=256, metavar="K",
                   help="elastic mode: local steps per worker before a "
                        "clean exit (default 256)")
    p.add_argument("--host-devices", type=int, default=0, metavar="K",
                   help="elastic mode, CPU venue: each worker simulates K "
                        "chips on the cpu backend (0 = real hardware)")
    p.add_argument("--center-proc", action="store_true",
                   help="elastic mode: run the center server as its OWN "
                        "supervised process — crash-atomic snapshots, "
                        "respawn-from-snapshot with backoff, the "
                        "center_down/center_restored event pair; workers "
                        "ride a center outage out on wire retries "
                        "(parallel/wire.py, design.md §15)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent AOT executable cache dir "
                        "(utils/compile_cache): compile_iter_fns "
                        "deserializes pre-built executables instead of "
                        "recompiling — pre-populate off-line with "
                        "scripts/prewarm_cache.py; supervised restarts and "
                        "checkpoint resumes then skip the XLA compile "
                        "(defaults to $THEANOMPI_COMPILE_CACHE if set)")
    p.add_argument("--record-dir", default=None, metavar="DIR",
                   help="record/telemetry directory (same as the "
                        "record_dir=DIR config key): recorder dumps, the "
                        "per-rank telemetry_rank*.jsonl event streams, and "
                        "crash flight recordings all land here — report "
                        "with scripts/telemetry_report.py DIR")
    p.add_argument("config", nargs="*", help="key=value model/worker config")
    args = p.parse_args(argv)

    kv = list(args.config)
    if args.n_workers:
        kv.append(f"n_workers={args.n_workers}")
    if args.compile_cache and \
            not any(c.startswith("compile_cache=") for c in kv):
        kv.append(f"compile_cache={args.compile_cache}")
    if args.record_dir and \
            not any(c.startswith("record_dir=") for c in kv):
        kv.append(f"record_dir={args.record_dir}")
    record_dir = next((c.partition("=")[2] for c in kv
                       if c.startswith("record_dir=")), None)
    if record_dir and not any(c.startswith("run_id=") for c in kv):
        # one run id for every host/restart of this launch: per-rank
        # telemetry streams (utils/telemetry) then correlate into one run
        # for scripts/telemetry_report.py
        import time as _t
        kv.append(f"run_id=run{int(_t.time())}")

    if args.num_hosts > 1:
        cmds = [compose_worker_cmd(args.rule, args.modelfile, args.modelclass,
                                   kv, args.coordinator, args.num_hosts, i)
                for i in range(args.num_hosts)]
        if args.emit_only or args.process_id is None:
            print("# run on each TPU host (e.g. via gcloud compute tpus "
                  "tpu-vm ssh --worker=all):")
            for i, c in enumerate(cmds):
                print(f"# host {i}:")
                print(shlex.join(c))
            return 0
        return subprocess.call(cmds[args.process_id])

    if args.elastic > 0:
        # Elastic membership (parallel/membership.py): workers join/leave
        # mid-run; the async center algebra absorbs the churn — no
        # world restart.  BSP has no shrink reaction: refuse early.
        from .parallel.membership import parse_kv, run_elastic
        return run_elastic(args.rule, args.modelfile, args.modelclass,
                           parse_kv(kv), args.elastic,
                           steps=args.elastic_steps,
                           host_devices=args.host_devices,
                           center_proc=args.center_proc)

    if args.supervise > 0:
        # Failure recovery (SURVEY §5): the worker runs as a subprocess so a
        # crash (or a watchdog-triggered exit) doesn't take the supervisor
        # down; each restart resumes — after a bounded-backoff wait — from
        # the latest *valid* per-epoch checkpoint (utils/checkpoint's
        # crash-atomic writes + newest-valid fallback make a SIGKILL
        # mid-save unable to brick the resume).
        if not any(c.startswith("ckpt_dir=") for c in kv):
            print("warning: --supervise without ckpt_dir= restarts training "
                  "from scratch each time", file=sys.stderr)
        base = compose_worker_cmd(args.rule, args.modelfile, args.modelclass,
                                  kv)
        import time as _time

        from .parallel.membership import (Backoff, CrashLoopBreaker,
                                          flight_tail_lines)
        backoff = Backoff(base=args.backoff, cap=args.backoff_max) \
            if args.backoff > 0 else None
        breaker = CrashLoopBreaker(limit=args.crash_limit,
                                   window_s=args.crash_window)

        def sweep(attempt: int, rc: int) -> None:
            # a dead worker's flight recordings (utils/telemetry dumps
            # flight_rank*.jsonl into record_dir on crash/stall-exit) are
            # moved aside per attempt, so the restart's own eventual dumps
            # can't overwrite the trail that explains THIS death
            if not record_dir:
                return
            from .utils.telemetry import sweep_flight_dumps
            dest = sweep_flight_dumps(record_dir,
                                      f"attempt{attempt}_rc{rc}")
            if dest:
                print(f"swept flight recordings to {dest}", file=sys.stderr)

        def print_flight_tail() -> None:
            if record_dir:
                for line in flight_tail_lines(record_dir):
                    print(line, file=sys.stderr)

        rc = 1
        for attempt in range(args.supervise + 1):
            cmd = base if attempt == 0 else base + ["resume=true"]
            t0 = _time.monotonic()
            rc = subprocess.call(cmd)
            if rc == 0:
                return 0
            sweep(attempt, rc)
            uptime = _time.monotonic() - t0
            if args.min_uptime and uptime < args.min_uptime:
                print(f"worker exited rc={rc} after only {uptime:.1f}s "
                      f"(< --min-uptime {args.min_uptime}s) — treating as "
                      f"unrecoverable, not retrying", file=sys.stderr)
                return rc
            if breaker.record_failure():
                # systemic failure (bad config, poisoned state, dead
                # backend): retrying just hides it — stop with evidence
                print(f"crash loop: {args.crash_limit} failures within "
                      f"{args.crash_window:.0f}s — giving up (rc={rc})",
                      file=sys.stderr)
                print_flight_tail()
                return rc
            if attempt < args.supervise:
                delay = backoff.delay(attempt) if backoff else 0.0
                print(f"worker exited rc={rc}; restarting in {delay:.1f}s "
                      f"({attempt + 1}/{args.supervise})", file=sys.stderr)
                if delay:
                    _time.sleep(delay)
        print(f"supervised restarts exhausted ({args.supervise}) — "
              f"giving up (rc={rc})", file=sys.stderr)
        print_flight_tail()
        return rc

    # single host: in-process (no spawn needed — the mesh IS the workers)
    from .worker import main as worker_main
    return worker_main([args.rule, args.modelfile, args.modelclass] + kv)


if __name__ == "__main__":
    raise SystemExit(main())
