"""theanompi_tpu — a TPU-native distributed training framework with the
capabilities of Theano-MPI (wanjinchang/Theano-MPI; see SURVEY.md).

Public session API (contract-compatible with the reference, SURVEY.md §2.6):

    from theanompi_tpu import BSP
    rule = BSP()
    rule.init(devices=4, modelfile='theanompi_tpu.models.cifar10',
              modelclass='Cifar10_model')
    rule.wait()
"""

from .sync_rule import ASGD, BSP, EASGD, GOSGD, SyncRule

__version__ = "0.1.0"
__all__ = ["BSP", "EASGD", "ASGD", "GOSGD", "SyncRule", "__version__"]
